"""Stage-graph micro-serving: hive-visible DAG jobs (ISSUE 20).

The swarm's serving plane already had every seam of a disaggregated
pipeline — encode is cache-backed, denoise is a chunked/checkpointed
program, decode/postprocess are separate trace spans — but the hive
still served each request as ONE monolithic lease. This module turns a
workflow submission (``POST /api/workflows``) into a DAG of
**stage-jobs**: each stage is a real :class:`~.queue.JobRecord` with its
own lease, class, timeline, SLO attribution, and cancel/TTL semantics,
so every existing mechanism (gang dispatch, WAL durability, tracing,
accounting) applies per stage with no special cases. Stage outputs hand
off as content-addressed spool artifacts (the settle path already
stores them), successors are admitted the moment their needs settle,
and the parent workflow id aggregates status/trace/usage across its
stages.

Durability: the graph itself rides the WAL as the ``ev_dag`` event
(journal.py) — the FULL workflow state, restored by replacement exactly
like ``ev_checkpoint``. Stage-job ids are deterministic
(``<workflow>-s<index>-<name>``), so queue-level id dedup makes stage
admission exactly-once across SIGKILL replay, compaction, and standby
promotion; :meth:`DagTable.reconcile` re-admits any ready stage whose
admission was lost between a settle and the matching ``ev_dag`` append.

Placement: stage NAMES are the dispatch vocabulary (``coalesce.py``
owns it, shared with the worker). Chip stages (denoise, upscale, svd)
only land on hosts advertising chips; encode/decode/postprocess can
land on a jax-free host. A worker that never advertises ``stages`` on
/work sees only monolithic jobs — legacy-poller opacity.

jax-free by design (SW001): a chip-less coordinator imports this.
"""

from __future__ import annotations

import uuid

from .. import telemetry
from ..coalesce import CHIP_STAGES, stage_of  # noqa: F401  (re-exported)
from . import accounting
from .queue import job_class
from .trace import _GAP_LABELS, worker_stages

_STAGES = telemetry.counter(
    "swarm_hive_dag_stages_total",
    "Stage-job lifecycle outcomes across all workflows",
    ("stage", "outcome"))
_READY = telemetry.gauge(
    "swarm_hive_dag_ready_depth",
    "Stage-jobs admitted (deps satisfied) but not yet settled")
_WORKFLOWS = telemetry.gauge(
    "swarm_hive_dag_workflows",
    "Workflows the hive currently tracks, by aggregate state",
    ("state",))
_STAGE_WAIT = telemetry.histogram(
    "swarm_hive_dag_stage_queue_wait_seconds",
    "Per-stage queue wait (admit -> first dispatch), labelled by stage",
    ("stage",))

# identity keys every stage-job inherits from the workflow submission so
# class/tenant/TTL semantics attribute per stage with no special cases
_INHERITED_KEYS = ("tenant", "priority", "sdaas_priority", "ttl_s")

# payload keys that are workflow-graph structure, never stage-job content
_GRAPH_KEYS = ("id", "stages", "links", "image_stage")

_DEFAULT_IMAGE_MODEL = "stabilityai/stable-diffusion-2-1"

# stage name a monolithic wire workflow maps to in explicit chains
_WORKFLOW_STAGE_NAMES = {
    "txt2img": "denoise", "img2img": "denoise", "inpaint": "denoise",
    "upscale": "upscale", "img2vid": "svd", "txt2vid": "txt2vid",
    "vid2vid": "vid2vid", "txt2audio": "audio", "stitch": "stitch",
    "img2txt": "caption", "echo": "postprocess",
}

_TERMINAL = ("done", "failed", "cancelled", "expired")


class WorkflowError(ValueError):
    """A workflow submission the expander refuses (400 on the wire)."""


def _stage_id(workflow_id: str, index: int, name: str) -> str:
    """Deterministic stage-job id: the same workflow replayed after a
    crash admits the same ids, so queue-level dedup is the exactly-once
    mechanism."""
    return f"{workflow_id}-s{index}-{name}"


def _inherit(payload: dict) -> dict:
    return {k: payload[k] for k in _INHERITED_KEYS if k in payload}


def _stage(workflow_id: str, index: int, name: str, needs: list[int],
           job: dict, handoff: str | None = None) -> dict:
    job = dict(job)
    job["id"] = _stage_id(workflow_id, index, name)
    job["stage"] = {"workflow": workflow_id, "name": name, "index": index,
                    "needs": list(needs)}
    if handoff:
        job["stage"]["handoff"] = handoff
    return {"name": name, "index": index, "needs": list(needs),
            "job_id": job["id"], "state": "blocked", "handoff": handoff,
            "job": job}


def _expand_diffusion(payload: dict, workflow_id: str) -> list[dict]:
    """txt2img (optionally upscale-after-txt2img) -> encode / denoise
    [/ upscale] / decode. The denoise stage is the parent job verbatim
    minus the chained-upscale key, so it inherits the gang/coalesce/
    adapter-affinity machinery unchanged; encode and decode are
    jax-free-capable."""
    base = {k: v for k, v in payload.items() if k not in _GRAPH_KEYS}
    model = base.get("model_name")
    if not isinstance(model, str) or not model:
        raise WorkflowError("workflow needs a model_name")
    stages: list[dict] = []
    encode_job = {
        "workflow": base.get("workflow", "txt2img"), "model_name": model,
        "prompt": base.get("prompt", ""),
        "negative_prompt": base.get("negative_prompt", ""),
        **({"parameters": {"test_tiny_model": True}}
           if (base.get("parameters") or {}).get("test_tiny_model")
           or base.get("test_tiny_model") else {}),
        **_inherit(payload),
    }
    stages.append(_stage(workflow_id, 0, "encode", [], encode_job))
    denoise_job = dict(base)
    denoise_job.pop("upscale", None)
    stages.append(_stage(workflow_id, 1, "denoise", [0], denoise_job,
                         handoff="raw"))
    prev = 1
    if base.get("upscale"):
        upscale_job = {
            "workflow": base.get("workflow", "txt2img"),
            "model_name": model, "prompt": base.get("prompt", ""),
            "upscale": base.get("upscale"),
            **({"parameters": dict(base["parameters"])}
               if isinstance(base.get("parameters"), dict) else {}),
            **_inherit(payload),
        }
        stages.append(_stage(workflow_id, 2, "upscale", [1], upscale_job,
                             handoff="raw"))
        prev = 2
    decode_job = {
        "workflow": base.get("workflow", "txt2img"), "model_name": model,
        **{k: base[k] for k in ("content_type", "outputs", "nsfw_filter")
           if k in base},
        **_inherit(payload),
    }
    stages.append(_stage(workflow_id, prev + 1, "decode", [prev],
                         decode_job, handoff="raw"))
    return stages


def _expand_img2vid(payload: dict, workflow_id: str) -> list[dict]:
    """img2vid WITHOUT a start image -> the txt2img stage renders it,
    the svd stage animates it via the spool handoff (ISSUE 20 satellite:
    the graph path serves more than still images)."""
    source = payload.get("image_stage")
    if not isinstance(source, dict):
        source = {}
    image_model = source.get("model_name") or _DEFAULT_IMAGE_MODEL
    prompt = source.get("prompt", payload.get("prompt", ""))
    encode_job = {
        "workflow": "txt2img", "model_name": image_model, "prompt": prompt,
        "negative_prompt": source.get("negative_prompt", ""),
        **_inherit(payload),
    }
    denoise_job = {
        "workflow": "txt2img", "model_name": image_model, "prompt": prompt,
        **{k: v for k, v in source.items() if k not in _GRAPH_KEYS},
        **_inherit(payload),
    }
    svd_job = {k: v for k, v in payload.items() if k not in _GRAPH_KEYS}
    return [
        _stage(workflow_id, 0, "encode", [], encode_job),
        _stage(workflow_id, 1, "denoise", [0], denoise_job),
        _stage(workflow_id, 2, "svd", [1], svd_job, handoff="image"),
    ]


def _expand_explicit(payload: dict, workflow_id: str) -> list[dict]:
    """Explicit chain: ``stages`` is a list of ordinary wire jobs, each
    consuming its predecessor's primary artifact (stitch chains, audio
    chains, anything the templates don't know)."""
    entries = payload.get("stages")
    if not isinstance(entries, list) or not entries:
        raise WorkflowError("stages must be a non-empty list of jobs")
    stages = []
    for i, entry in enumerate(entries):
        if not isinstance(entry, dict):
            raise WorkflowError(f"stage {i} is not a job object")
        job = {k: v for k, v in entry.items() if k not in ("id", "stage")}
        name = str(entry.get("stage_name") or _WORKFLOW_STAGE_NAMES.get(
            str(entry.get("workflow")), f"stage{i}"))
        job.pop("stage_name", None)
        for k in _INHERITED_KEYS:
            if k in payload:
                job.setdefault(k, payload[k])
        needs = [i - 1] if i else []
        stages.append(_stage(workflow_id, i, name, needs, job,
                             handoff="image" if i else None))
    return stages


def expand_workflow(payload: dict, workflow_id: str) -> list[dict]:
    """One workflow submission -> its stage list, or WorkflowError."""
    if not isinstance(payload, dict):
        raise WorkflowError("workflow must be a JSON object")
    if isinstance(payload.get("stages"), list):
        return _expand_explicit(payload, workflow_id)
    workflow = payload.get("workflow")
    if workflow == "img2vid" and not payload.get("start_image_uri"):
        return _expand_img2vid(payload, workflow_id)
    if workflow in ("txt2img", "img2img"):
        return _expand_diffusion(payload, workflow_id)
    raise WorkflowError(
        f"workflow {workflow!r} has no stage-graph expansion; submit an "
        "explicit `stages` list or use POST /api/jobs")


class Workflow:
    """One submitted stage-graph: the parent id, the original payload,
    and the per-stage states. Serializes losslessly to/from the ev_dag
    WAL event (plain JSON types only)."""

    def __init__(self, workflow_id: str, job: dict, stages: list[dict],
                 submitted_wall: float):
        self.workflow_id = workflow_id
        self.job = job
        self.stages = stages
        self.submitted_wall = submitted_wall
        self.state = "running"
        self.done_wall: float | None = None

    @property
    def tenant(self) -> str:
        return accounting.tenant_of(self.job)

    def stage(self, index: int) -> dict:
        return self.stages[index]

    def to_state(self) -> dict:
        return {
            "id": self.workflow_id, "job": self.job, "state": self.state,
            "submitted_wall": self.submitted_wall,
            "done_wall": self.done_wall,
            "stages": [dict(s) for s in self.stages],
        }

    @classmethod
    def from_state(cls, state: dict) -> "Workflow":
        wf = cls(str(state.get("id", "")), state.get("job") or {},
                 [dict(s) for s in (state.get("stages") or [])],
                 float(state.get("submitted_wall", 0.0)))
        wf.state = str(state.get("state", "running"))
        done = state.get("done_wall")
        wf.done_wall = float(done) if done is not None else None
        return wf


class DagTable:
    """The hive's workflow graphs. Owns NO job state — stages live in
    the PriorityJobQueue as ordinary records; this table only tracks the
    edges between them and aggregates the parent view."""

    def __init__(self, clock, history_limit: int = 256):
        self.clock = clock
        self.history_limit = max(int(history_limit), 1)
        self.workflows: dict[str, Workflow] = {}
        self.by_stage: dict[str, tuple[str, int]] = {}

    # --- submission -------------------------------------------------

    def submit(self, payload: dict, queue) -> tuple[Workflow, list]:
        """Expand one workflow submission and admit its ready stages.
        Returns (workflow, newly admitted records). Raises WorkflowError
        on a payload the expander refuses; queue.QueueFull propagates
        (the caller answers 429 and the workflow is not registered)."""
        workflow_id = str(payload.get("id") or f"wf-{uuid.uuid4().hex[:12]}")
        existing = self.workflows.get(workflow_id)
        if existing is not None:
            return existing, []
        stages = expand_workflow(payload, workflow_id)
        wf = Workflow(workflow_id, dict(payload), stages,
                      round(self.clock.wall(), 3))
        admitted = self._admit_ready(wf, queue)
        self.workflows[workflow_id] = wf
        for s in wf.stages:
            self.by_stage[s["job_id"]] = (workflow_id, s["index"])
        self._prune()
        self._refresh_gauges()
        return wf, admitted

    def _admit_ready(self, wf: Workflow, queue) -> list:
        """Admit every blocked stage whose needs are all done. Queue-id
        dedup makes this idempotent (replay/reconcile safe)."""
        admitted = []
        for s in wf.stages:
            if s["state"] != "blocked":
                continue
            if any(wf.stages[n]["state"] != "done" for n in s["needs"]):
                continue
            job = dict(s["job"])
            job["stage"] = dict(job.get("stage") or {})
            inputs = self._inputs_for(wf, s, queue)
            if inputs:
                job["stage"]["inputs"] = inputs
            known = job["id"] in queue.records
            record = queue.submit(job)
            s["state"] = "queued"
            if not known:
                admitted.append(record)
                _STAGES.inc(stage=s["name"], outcome="admitted")
        return admitted

    def _inputs_for(self, wf: Workflow, stage: dict, queue) -> list[dict]:
        """Predecessor spool artifacts, injected into the successor's
        stage context: content-addressed references ({sha256, bytes,
        href}) the worker rehydrates through its authed artifact
        client. The handoff is how stage outputs travel — never inline
        blobs through the queue."""
        inputs = []
        for n in stage.get("needs", ()):
            pred = wf.stages[n]
            record = queue.records.get(pred["job_id"])
            artifacts = {}
            if record is not None and isinstance(record.result, dict):
                for key, art in (record.result.get("artifacts")
                                 or {}).items():
                    if isinstance(art, dict) and art.get("sha256"):
                        artifacts[key] = {
                            k: art[k] for k in
                            ("sha256", "bytes", "href", "content_type")
                            if k in art}
            inputs.append({"stage": pred["name"], "index": n,
                           "artifacts": artifacts})
        return inputs

    # --- lifecycle hooks (called from the settle/cancel paths) ------

    def workflow_of(self, record) -> Workflow | None:
        ref = self.by_stage.get(getattr(record, "job_id", None))
        return self.workflows.get(ref[0]) if ref else None

    def note_settle(self, record, queue) -> tuple[Workflow | None, list]:
        """A stage-job settled: mark it done, admit newly-ready
        successors, and finish the workflow when the last stage lands.
        Returns (workflow, newly admitted records) — (None, []) for a
        monolithic job."""
        ref = self.by_stage.get(record.job_id)
        if ref is None:
            return None, []
        wf = self.workflows.get(ref[0])
        if wf is None:
            return None, []
        stage = wf.stage(ref[1])
        if stage["state"] == "done":
            return wf, []  # duplicate settle: already advanced
        stage["state"] = "done"
        _STAGES.inc(stage=stage["name"], outcome="done")
        if record.queue_wait_s is not None:
            _STAGE_WAIT.observe(float(record.queue_wait_s),
                                stage=stage["name"])
        admitted = []
        if wf.state == "running":
            admitted = self._admit_ready(wf, queue)
            if all(s["state"] == "done" for s in wf.stages):
                wf.state = "done"
                wf.done_wall = round(self.clock.wall(), 3)
        self._refresh_gauges()
        return wf, admitted

    def note_terminal(self, record, outcome: str, queue) -> tuple[
            Workflow | None, list]:
        """A stage-job ended without settling (cancelled/expired/failed):
        the workflow fails closed — descendants are never admitted, and
        still-queued sibling stages are cancelled (returned for the
        caller to journal). Idempotent."""
        ref = self.by_stage.get(getattr(record, "job_id", None))
        if ref is None:
            return None, []
        wf = self.workflows.get(ref[0])
        if wf is None:
            return None, []
        stage = wf.stage(ref[1])
        if stage["state"] in _TERMINAL:
            return wf, []
        stage["state"] = outcome if outcome in _TERMINAL else "failed"
        _STAGES.inc(stage=stage["name"], outcome=stage["state"])
        cascaded = []
        if wf.state == "running":
            wf.state = "cancelled" if outcome == "cancelled" else "failed"
            wf.done_wall = round(self.clock.wall(), 3)
            for s in wf.stages:
                if s["state"] == "blocked":
                    s["state"] = "cancelled"
                    _STAGES.inc(stage=s["name"], outcome="cancelled")
                elif s["state"] == "queued":
                    sibling = queue.records.get(s["job_id"])
                    if sibling is not None and sibling.state == "queued":
                        queue.mark_cancelled(sibling, "queued")
                        s["state"] = "cancelled"
                        _STAGES.inc(stage=s["name"], outcome="cancelled")
                        cascaded.append(sibling)
        self._refresh_gauges()
        return wf, cascaded

    # --- recovery ---------------------------------------------------

    def restore(self, state: dict) -> None:
        """ev_dag replay: restore-by-replacement, like ev_checkpoint.
        The LAST event for a workflow id wins."""
        wf = Workflow.from_state(state)
        if not wf.workflow_id:
            return
        old = self.workflows.pop(wf.workflow_id, None)
        if old is not None:
            for s in old.stages:
                self.by_stage.pop(s["job_id"], None)
        self.workflows[wf.workflow_id] = wf
        for s in wf.stages:
            self.by_stage[s["job_id"]] = (wf.workflow_id, s["index"])
        self._refresh_gauges()

    def reconcile(self, queue) -> list:
        """Post-replay repair: the WAL may have settled a stage without
        the matching ev_dag (crash between the two appends). Re-derive
        stage states from the records and admit any ready stage that is
        not yet queued — exactly-once via deterministic ids."""
        admitted = []
        for wf in self.workflows.values():
            if wf.state != "running":
                continue
            for s in wf.stages:
                record = queue.records.get(s["job_id"])
                if record is None:
                    if s["state"] == "queued":
                        # admitted once, then pruned/lost: re-admit below
                        s["state"] = "blocked"
                    continue
                if record.state == "done" and s["state"] != "done":
                    s["state"] = "done"
                elif record.state in ("cancelled", "expired", "failed") \
                        and s["state"] not in _TERMINAL:
                    s["state"] = record.state
            if any(s["state"] in ("cancelled", "expired", "failed")
                   for s in wf.stages):
                wf.state = "failed"
                wf.done_wall = wf.done_wall or round(self.clock.wall(), 3)
                continue
            admitted.extend(self._admit_ready(wf, queue))
            if all(s["state"] == "done" for s in wf.stages):
                wf.state = "done"
                wf.done_wall = wf.done_wall or round(self.clock.wall(), 3)
        self._refresh_gauges()
        return admitted

    # --- aggregation (the parent view) ------------------------------

    def status(self, wf: Workflow, queue) -> dict:
        stages = []
        records = []
        for s in wf.stages:
            record = queue.records.get(s["job_id"])
            if record is not None:
                records.append(record)
            stages.append({
                "stage": s["name"], "index": s["index"], "id": s["job_id"],
                "status": record.state if record is not None else s["state"],
                "attempts": record.attempts if record is not None else 0,
                "worker": record.worker if record is not None else None,
            })
        out = {
            "id": wf.workflow_id,
            "workflow": wf.job.get("workflow"),
            "class": job_class(wf.job),
            "tenant": wf.tenant,
            "status": wf.state,
            "stages": stages,
            "usage": accounting.render_usage(
                accounting.usage_summary(records))["totals"],
        }
        if wf.state == "done" and wf.stages:
            final = queue.records.get(wf.stages[-1]["job_id"])
            if final is not None and final.result is not None:
                out["result"] = final.result
        return out

    def build_trace(self, wf: Workflow, queue, now_wall: float) -> dict:
        """The parent trace: every stage's timeline merged on one wall
        clock, gaps attributed with the shared labels plus the
        settle->admit `stage_handoff` seam, and the workers' stage spans
        aggregated per stage. Shaped so a COMPLETED workflow passes the
        same `trace_missing` oracle a monolithic job does."""
        events: list[dict] = []
        spans: list[dict] = []
        attempts = 0
        placement = None
        queue_wait = None
        for s in wf.stages:
            record = queue.records.get(s["job_id"])
            if record is None:
                continue
            attempts += record.attempts
            if record.placement:
                placement = record.placement
            if queue_wait is None and record.queue_wait_s is not None:
                queue_wait = record.queue_wait_s
            for e in record.timeline:
                if isinstance(e, dict):
                    events.append(dict(e, stage=s["name"]))
            stage_spans = worker_stages(record.result)
            if stage_spans:
                spans.extend({"stage": f"{s['name']}:{sp['stage']}",
                              "seconds": sp["seconds"]}
                             for sp in stage_spans)
            elif record.state == "done":
                # synthetic envelopes carry no timings; the dispatch ->
                # settle window is still honest per-stage attribution
                walls = {e.get("event"): float(e.get("wall", 0.0))
                         for e in record.timeline if isinstance(e, dict)}
                if "dispatch" in walls and "settle" in walls:
                    spans.append({
                        "stage": s["name"],
                        "seconds": round(max(
                            walls["settle"] - walls["dispatch"], 0.0), 3)})
        events.sort(key=lambda e: float(e.get("wall", 0.0)))
        t0 = float(events[0]["wall"]) if events else now_wall
        for e in events:
            e["t_s"] = round(float(e.get("wall", t0)) - t0, 3)
        gaps = []
        for prev, nxt in zip(events, events[1:]):
            pair = (prev.get("event"), nxt.get("event"))
            attribution = _GAP_LABELS.get(pair, "other")
            if pair == ("settle", "admit"):
                attribution = "stage_handoff"
            gap = {
                "from": prev.get("event"), "to": nxt.get("event"),
                "seconds": round(
                    float(nxt["wall"]) - float(prev["wall"]), 3),
                "attribution": attribution,
            }
            if prev.get("stage") != nxt.get("stage"):
                gap["stages"] = [prev.get("stage"), nxt.get("stage")]
            gaps.append(gap)
        open_ended = wf.state == "running"
        end = now_wall if open_ended else float(
            wf.done_wall or (events[-1]["wall"] if events else now_wall))
        return {
            "id": wf.workflow_id,
            "class": job_class(wf.job),
            "status": wf.state,
            "attempts": attempts,
            "placement": placement,
            "queue_wait_s": queue_wait,
            "workflow": True,
            "stage_states": {s["name"]: s["state"] for s in wf.stages},
            "events": events,
            "events_resorted": False,
            "gaps": gaps,
            "total_s": max(round(end - t0, 3), 0.0),
            "open": open_ended,
            "worker": {
                "stages": spans,
                "total_s": round(sum(sp["seconds"] for sp in spans), 3),
                "trace": {},
            },
        }

    # --- bookkeeping ------------------------------------------------

    def summary(self) -> dict:
        states = {"running": 0, "done": 0, "failed": 0, "cancelled": 0}
        ready = 0
        for wf in self.workflows.values():
            states[wf.state] = states.get(wf.state, 0) + 1
            if wf.state == "running":
                ready += sum(1 for s in wf.stages if s["state"] == "queued")
        return {"total": len(self.workflows), "ready_stages": ready,
                **states}

    def _refresh_gauges(self) -> None:
        summary = self.summary()
        _READY.set(summary["ready_stages"])
        for state in ("running", "done", "failed", "cancelled"):
            _WORKFLOWS.set(summary.get(state, 0), state=state)

    def _prune(self) -> None:
        """Bound history like the queue's retired-record window: oldest
        TERMINAL workflows fall off first; running graphs are never
        dropped."""
        while len(self.workflows) > self.history_limit:
            victim = next((wid for wid, wf in self.workflows.items()
                           if wf.state != "running"), None)
            if victim is None:
                return
            wf = self.workflows.pop(victim)
            for s in wf.stages:
                self.by_stage.pop(s["job_id"], None)
