"""Embedded hive coordinator: the missing half of the swarm topology.

The repo reproduced only the worker side of the paper's hive/worker
split; every end-to-end path terminated in the hand-rolled test double
(tests/fake_hive.py). This package is a real, in-repo coordinator
speaking the exact wire protocol in `chiaswarm_tpu/hive.py` — a pristine
`Worker` connects to it unmodified:

- `queue.py`    priority-class job queue (interactive > default > batch,
                FIFO within class) with class-aware load shedding (per-
                class depth watermarks: batch sheds first, interactive
                last) and O(1) lazy-deletion dispatch;
- `journal.py`  write-ahead journal under $SDAAS_ROOT/hive_wal/ — every
                queue/lease transition is an append-only JSONL line with
                periodic compaction, so a SIGKILL'd hive replays to its
                pre-crash state (recovered leases get a fresh deadline);
- `clock.py`    the wall-vs-monotonic convention: intervals are
                monotonic, persisted instants are wall-clock and
                re-anchored on replay;
- `dispatch.py` residency-aware dispatcher reading each worker's
                advertised resident models and chip capabilities from the
                /work query — the slice-level placement logic of
                chips/allocator.py lifted one level up, to workers;
- `leases.py`   lease table re-queuing jobs whose results never arrive
                (bounded redeliveries, then a failed state) so a dead
                worker costs one lease deadline, not the job;
- `spool.py`    content-addressed artifact store for accepted results;
- `app.py`      the aiohttp server tying it together (bearer auth,
                400-with-message refusals, idempotent result ACKs,
                /metrics + /healthz from the shared telemetry registry);
- `replication.py` WAL-shipped standby + health-checked failover: a
                second hive tails the primary's journal event stream
                (`GET /api/replication/stream`), refuses work until the
                primary goes silent past `hive_failover_grace_s`, then
                promotes itself — fresh lease deadlines, a bumped
                fencing epoch, and 409s for stale-epoch traffic, so a
                revived deposed primary cannot double-settle against
                any client that has contacted the promoted hive (see
                replication.py for the honest limits of a two-node,
                no-quorum fence under asymmetric partitions);
- `harness.py`  in-process swarm (HiveServer + real Workers over real
                sockets) for e2e tests, chaos scenarios, and the bench.

Entry point: `tools/hive_serve.py` (or `python -m
chiaswarm_tpu.hive_server`).
"""

from .app import HiveServer
from .clock import CLOCK, HiveClock
from .journal import HiveJournal
from .queue import JOB_CLASSES, JobRecord, PriorityJobQueue, QueueFull, job_class
from .replication import StandbyHive


def __getattr__(name):
    # LocalSwarm pulls in the whole Worker runtime (jax included); the
    # coordinator itself must stay importable on a chip-less host, so
    # the harness loads only when actually asked for
    if name == "LocalSwarm":
        from .harness import LocalSwarm

        return LocalSwarm
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

__all__ = [
    "HiveServer",
    "StandbyHive",
    "HiveJournal",
    "HiveClock",
    "CLOCK",
    "LocalSwarm",
    "JOB_CLASSES",
    "JobRecord",
    "PriorityJobQueue",
    "QueueFull",
    "job_class",
]
