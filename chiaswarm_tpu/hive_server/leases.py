"""Lease table: a dispatched job is a loan, not a transfer.

The worker side already treats result delivery as at-least-once (durable
outbox, redelivery across restarts); this module is the counterparty
that makes those semantics mean something. Every job handed out on
/work gets a lease with a deadline; a result arriving before the
deadline settles it, and the reaper re-queues anything else — a worker
that died mid-denoise costs one lease deadline, not the job. After
`max_redeliveries` expiries the job parks in a `failed` state with the
history visible, so a poison job cannot ping-pong around the swarm
forever.
"""

from __future__ import annotations

from .. import telemetry
from .clock import CLOCK, HiveClock
from .queue import JobRecord, PriorityJobQueue

_LEASES_ACTIVE = telemetry.gauge(
    "swarm_hive_leases_active", "Jobs currently leased to a worker")
_LEASES_EXPIRED = telemetry.counter(
    "swarm_hive_leases_expired_total",
    "Leases that hit their deadline without a result (each one is a "
    "redelivery, or the final failure when the budget is spent)",
)
_JOBS_FAILED = telemetry.counter(
    "swarm_hive_jobs_failed_total",
    "Jobs parked as failed: redelivery budget exhausted, or unplaceable "
    "(no live worker can run the model family)",
)


class Lease:
    __slots__ = ("record", "worker", "expires_at")

    def __init__(self, record: JobRecord, worker: str, expires_at: float):
        self.record = record
        self.worker = worker
        self.expires_at = expires_at


class LeaseTable:
    def __init__(self, deadline_s: float, max_redeliveries: int,
                 clock: HiveClock | None = None):
        self.deadline_s = max(float(deadline_s), 0.0)
        self.max_redeliveries = max(int(max_redeliveries), 0)
        self.clock = clock or CLOCK
        self._leases: dict[str, Lease] = {}
        # flap detection (ISSUE 18): consecutive lease expiries per
        # worker — reset to zero the moment one of its leases settles,
        # so only an unbroken run of losses counts as flapping. Purely
        # derived dispatch-bias state: never journaled, rebuilt from
        # live traffic after a restart (a restarted hive giving a
        # formerly-flappy worker a clean slate is the right call).
        self.flaps: dict[str, int] = {}

    def __len__(self) -> int:
        return len(self._leases)

    def get(self, job_id: str) -> Lease | None:
        return self._leases.get(job_id)

    def active(self) -> list[Lease]:
        """Snapshot of every live lease (promotion re-grants these with
        fresh deadlines; tests assert against them)."""
        return list(self._leases.values())

    def grant(self, record: JobRecord, worker: str) -> Lease:
        lease = Lease(record, worker, self.clock.mono() + self.deadline_s)
        self._leases[record.job_id] = lease
        # trace timeline: a lease event per grant — re-grants (WAL
        # recovery, standby promotion) appear too, which is the point:
        # the timeline shows WHY a job's deadline restarted. Replay
        # paths overwrite the timeline from the journal event afterwards,
        # so replayed grants never double-stamp.
        record.timeline.append({
            "event": "lease", "wall": self.clock.wall(), "worker": worker,
            "deadline_s": self.deadline_s})
        _LEASES_ACTIVE.set(len(self._leases))
        return lease

    def restore(self, record: JobRecord, worker: str) -> Lease:
        """Replay a journaled lease after a restart. The journaled
        deadline is a dead process's monotonic offset, so the recovered
        lease gets a FRESH full deadline — the worker may still be
        running the job (the idempotent-ACK path absorbs its result), or
        may be long gone (the reaper redelivers one deadline from NOW,
        never in the past)."""
        return self.grant(record, worker)

    def settle(self, job_id: str) -> Lease | None:
        """Drop the lease on a result arrival (normal completion — also
        called for late results so an already-expired worker's answer
        stops any further redelivery)."""
        lease = self._leases.pop(job_id, None)
        if lease is not None:
            # a delivered result breaks the worker's expiry streak
            self.flaps.pop(lease.worker, None)
        _LEASES_ACTIVE.set(len(self._leases))
        return lease

    def flapping(self, threshold: int) -> set[str]:
        """Workers whose consecutive-expiry count has reached
        `threshold` (0 disables). The dispatcher withholds fresh seeds
        from them within the affinity-hold window — prefers, never
        starves — and /healthz surfaces the set."""
        if threshold <= 0:
            return set()
        return {w for w, n in self.flaps.items() if n >= threshold}

    def reap(self, queue: PriorityJobQueue) -> list[JobRecord]:
        """Expire overdue leases: re-queue while the redelivery budget
        lasts, park as failed after. Returns the records that changed
        state (the caller logs them)."""
        now = self.clock.mono()
        changed: list[JobRecord] = []
        for job_id, lease in list(self._leases.items()):
            if lease.expires_at > now:
                continue
            del self._leases[job_id]
            record = lease.record
            _LEASES_EXPIRED.inc()
            self.flaps[lease.worker] = self.flaps.get(lease.worker, 0) + 1
            # attempts counts dispatches; the budget bounds how many
            # times the job may be handed out in total
            if record.attempts > self.max_redeliveries:
                record.state = "failed"
                record.error = (
                    f"lease expired {record.attempts} time(s) "
                    f"(deadline {self.deadline_s:g}s, last worker "
                    f"{lease.worker}); redelivery budget "
                    f"{self.max_redeliveries} exhausted"
                )
                record.timeline.append({
                    "event": "park", "wall": self.clock.wall(),
                    "worker": lease.worker,
                    "reason": "redelivery budget exhausted"})
                _JOBS_FAILED.inc()
            else:
                queue.requeue_front(record)
            changed.append(record)
        _LEASES_ACTIVE.set(len(self._leases))
        return changed
