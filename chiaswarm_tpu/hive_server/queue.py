"""Priority-class job queue for the hive coordinator.

Three classes — interactive > default > batch — each FIFO, dispatched
strictly in class order (an interactive job submitted last still leaves
before every queued batch job). The class comes from the job's own
`priority` field (or the legacy `sdaas_priority` spelling), the same key
the worker's BatchScheduler fast-path reads, so priority is one value
end to end: hive queue class -> job dict on the wire -> linger-skip on
the slice.

Admission is backpressure, not silent truncation: past
`depth_limit` total queued jobs, `submit` raises QueueFull and the HTTP
layer answers 429 with a message — the submitter decides whether to
retry, the hive never grows an unbounded backlog.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
import uuid
from collections import deque

from .. import telemetry

# dispatch order, highest first
JOB_CLASSES = ("interactive", "default", "batch")

_QUEUE_DEPTH = telemetry.gauge(
    "swarm_hive_queue_depth",
    "Jobs queued at the hive awaiting dispatch, by priority class",
    ("class",),
)
_SUBMITTED = telemetry.counter(
    "swarm_hive_jobs_submitted_total",
    "Jobs accepted into the hive queue, by priority class",
    ("class",),
)
_REFUSED = telemetry.counter(
    "swarm_hive_jobs_refused_total",
    "Job submissions refused by admission control (queue depth limit)",
)
_QUEUE_WAIT = telemetry.histogram(
    "swarm_hive_queue_wait_seconds",
    "Hive-side wait from job submission to dispatch to a worker",
)


def job_class(job: dict) -> str:
    """The queue class a raw job dict belongs to; unknown/absent
    priorities land in "default" (legacy hives send no priority at all).
    """
    for key in ("priority", "sdaas_priority"):
        value = str(job.get(key, "")).lower()
        if value in JOB_CLASSES:
            return value
    return "default"


class QueueFull(Exception):
    """Admission control refused the job; the message is wire-ready."""


@dataclasses.dataclass
class JobRecord:
    """One job's hive-side lifecycle. `state` walks
    queued -> leased -> settling -> done, with the exit `failed`
    (redelivery budget exhausted) and a leased->queued loop on lease
    expiry ("settling" = result accepted, artifact spool write in
    flight)."""

    job: dict
    job_id: str
    job_class: str
    submitted_at: float  # monotonic
    seq: int
    state: str = "queued"
    attempts: int = 0  # dispatches so far
    worker: str | None = None  # current/last lessee
    completed_by: str | None = None
    queue_wait_s: float | None = None  # first submit -> first dispatch
    placement: str | None = None  # last dispatch outcome
    result: dict | None = None  # spooled envelope (blob refs, not blobs)
    error: str | None = None
    done_at: float | None = None  # monotonic, stamped on result acceptance
    retired: bool = False  # already counted against history_limit

    def status(self) -> dict:
        """JSON-ready snapshot for GET /api/jobs/{id}."""
        return {
            "id": self.job_id,
            "class": self.job_class,
            "status": self.state,
            "attempts": self.attempts,
            "worker": self.worker,
            "completed_by": self.completed_by,
            "queue_wait_s": self.queue_wait_s,
            "placement": self.placement,
            "error": self.error,
            "result": self.result,
        }


class PriorityJobQueue:
    """Class-ordered FIFO queue + the record table for every job the hive
    has ever admitted this process. Single-threaded by design: every
    caller is an aiohttp handler or the reaper task on one event loop."""

    def __init__(self, depth_limit: int = 0, history_limit: int = 0):
        self.depth_limit = int(depth_limit)
        # finished (done/failed) records kept for GET /api/jobs/{id};
        # past this many the oldest are forgotten so a long-running
        # coordinator's memory is bounded by the limit, not its job
        # history (0 = keep everything)
        self.history_limit = int(history_limit)
        self._queues: dict[str, deque[JobRecord]] = {
            cls: deque() for cls in JOB_CLASSES
        }
        self.records: dict[str, JobRecord] = {}
        self._finished: deque[str] = deque()
        self._seq = itertools.count()
        self._refresh_gauges()

    def _refresh_gauges(self) -> None:
        for cls, q in self._queues.items():
            _QUEUE_DEPTH.set(len(q), **{"class": cls})

    @property
    def depth(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def depths(self) -> dict[str, int]:
        return {cls: len(q) for cls, q in self._queues.items()}

    def submit(self, job: dict) -> JobRecord:
        """Admit one raw job dict; assigns an id when the submitter sent
        none. Raises QueueFull past the depth limit (interactive jobs
        included — a full hive must shed load, not reorder it away)."""
        job = dict(job)
        job_id = str(job.get("id") or uuid.uuid4().hex)
        job["id"] = job_id
        if job_id in self.records:
            # resubmission of a known id: hand back the existing record
            # (the hive dedupes by job id, mirroring what workers already
            # assume when they redeliver results at-least-once); dedup
            # beats admission — a retry of an admitted job is not load
            return self.records[job_id]
        if self.depth_limit > 0 and self.depth >= self.depth_limit:
            _REFUSED.inc()
            raise QueueFull(
                f"hive queue full ({self.depth} jobs, limit "
                f"{self.depth_limit}); resubmit later"
            )
        cls = job_class(job)
        record = JobRecord(
            job=job,
            job_id=job_id,
            job_class=cls,
            submitted_at=time.monotonic(),
            seq=next(self._seq),
        )
        self.records[job_id] = record
        self._queues[cls].append(record)
        _SUBMITTED.inc(**{"class": cls})
        self._refresh_gauges()
        return record

    def iter_queued(self):
        """Records in dispatch order: class rank, FIFO within class.
        Snapshot copy — callers take() entries while iterating."""
        for cls in JOB_CLASSES:
            yield from list(self._queues[cls])

    def take(self, record: JobRecord, worker: str, outcome: str) -> None:
        """Remove a queued record for dispatch and stamp its lease-side
        bookkeeping (attempts, queue wait on the first dispatch)."""
        self._queues[record.job_class].remove(record)
        record.state = "leased"
        record.worker = worker
        record.attempts += 1
        record.placement = outcome
        if record.queue_wait_s is None:
            record.queue_wait_s = round(
                time.monotonic() - record.submitted_at, 3)
            _QUEUE_WAIT.observe(record.queue_wait_s)
        self._refresh_gauges()

    def requeue_front(self, record: JobRecord) -> None:
        """Put an expired-lease job back at the FRONT of its class: a
        redelivery has already waited a full lease deadline and must not
        queue behind fresh arrivals of the same class. `worker` keeps
        the expired lessee's name — a LATE result from it is attributed
        correctly, and the next take() overwrites it anyway."""
        record.state = "queued"
        self._queues[record.job_class].appendleft(record)
        self._refresh_gauges()

    def retire(self, record: JobRecord) -> None:
        """Note a record reaching a terminal state and prune the oldest
        finished ones past `history_limit`. Spooled artifact files stay
        on disk (content-addressed); only the in-memory status entry is
        forgotten — a later poll for a pruned id answers 404, the same
        as a job this hive never knew."""
        if self.history_limit <= 0:
            return
        if record.retired:
            # a failed job completed later by a late result passes
            # through twice (reaper, then _results); one _finished slot
            # per record or the pruning loop evicts other records early
            return
        record.retired = True
        self._finished.append(record.job_id)
        while len(self._finished) > self.history_limit:
            old = self._finished.popleft()
            stale = self.records.get(old)
            if stale is not None and stale.state in ("done", "failed"):
                del self.records[old]

    def discard_queued(self, record: JobRecord) -> None:
        """Drop a record from its class queue if present (a late result
        arrived for a job we had already re-queued)."""
        try:
            self._queues[record.job_class].remove(record)
        except ValueError:
            return
        self._refresh_gauges()
