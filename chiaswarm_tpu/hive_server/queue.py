"""Priority-class job queue for the hive coordinator.

Three classes — interactive > default > batch — each FIFO, dispatched
strictly in class order (an interactive job submitted last still leaves
before every queued batch job). The class comes from the job's own
`priority` field (or the legacy `sdaas_priority` spelling), the same key
the worker's BatchScheduler fast-path reads, so priority is one value
end to end: hive queue class -> job dict on the wire -> linger-skip on
the slice.

Admission is backpressure, not silent truncation — and it degrades in
priority order. Each class has a watermark, a fraction of `depth_limit`
past which NEW submissions of that class are shed with a 429 (counted in
`swarm_hive_shed_total{class}`): batch sheds first, interactive last, so
an overloaded hive keeps serving the traffic that cares about latency
while telling bulk submitters to come back later. A watermark of 1.0
reproduces the old flat limit for that class.

Internally each class queue is a deque of `(token, record)` entries with
LAZY deletion: `take()` / `discard_queued()` mark the record (state
change or token bump) instead of an O(n) `deque.remove`, and stale
entries are skipped on iteration and compacted away once they outnumber
the live ones. Dispatch cost therefore stays flat at thousands of queued
jobs — the same "stays cheap at thousands" direction as the worker
directory.

Gang scheduling (ISSUE 9) adds a SECONDARY index over the same entries:
(class, coalesce key) -> deque of the identical (token, record) tuples,
so the dispatcher can find a picked job's queued batchmates in O(1)
instead of scanning the class queue. The index shares the tombstone
discipline (an entry is live iff `_is_live`), is rebuilt for free by
WAL replay and replication resets (it is maintained inside `_enqueue`,
which every restore path already goes through), and is never persisted
— it is pure derived state.
"""

from __future__ import annotations

import dataclasses
import logging
import math
import uuid
from collections import OrderedDict, deque

from .. import telemetry
from .accounting import tenant_of
from ..coalesce import coalesce_key
from .clock import CLOCK, HiveClock

logger = logging.getLogger(__name__)

# dispatch order, highest first
JOB_CLASSES = ("interactive", "default", "batch")

# per-class shed watermarks as fractions of depth_limit; parsed from
# Settings.hive_shed_watermarks ("interactive:1.0,default:0.85,batch:0.5")
DEFAULT_SHED_WATERMARKS = {
    "interactive": 1.0,
    "default": 0.85,
    "batch": 0.5,
}

_QUEUE_DEPTH = telemetry.gauge(
    "swarm_hive_queue_depth",
    "Jobs queued at the hive awaiting dispatch, by priority class",
    ("class",),
)
_SUBMITTED = telemetry.counter(
    "swarm_hive_jobs_submitted_total",
    "Jobs accepted into the hive queue, by priority class",
    ("class",),
)
_REFUSED = telemetry.counter(
    "swarm_hive_jobs_refused_total",
    "Job submissions refused by admission control (queue depth limit)",
)
_SHED = telemetry.counter(
    "swarm_hive_shed_total",
    "Job submissions shed by class-aware admission (per-class depth "
    "watermark crossed; batch sheds first, interactive last)",
    ("class",),
)
_CANCELLED = telemetry.counter(
    "swarm_hive_cancelled_total",
    "Jobs cancelled via POST /api/jobs/{id}/cancel, by the lifecycle "
    "stage the cancel caught them in (queued = tombstoned before any "
    "dispatch; leased = revoked mid-flight via the /work piggyback)",
    ("stage",),
)
_EXPIRED = telemetry.counter(
    "swarm_hive_expired_total",
    "Queued jobs parked as expired by the admission-time TTL "
    "(hive_job_ttl_s / per-job deadline_s) before wasting a dispatch",
)
# hive-side latency buckets: 5 ms (a poll already in flight) up to 10
# minutes (a batch job parked behind a long compile) — the stage
# histograms' DEFAULT_BUCKETS stop at 300 s, too short for queue waits
HIVE_LATENCY_BUCKETS = telemetry.DEFAULT_BUCKETS + (600.0,)

_QUEUE_WAIT = telemetry.histogram(
    "swarm_hive_queue_wait_seconds",
    "Hive-side wait from job submission to first dispatch to a worker, "
    "by priority class",
    ("class",),
    buckets=HIVE_LATENCY_BUCKETS,
)
_DISPATCH_TO_SETTLE = telemetry.histogram(
    "swarm_hive_dispatch_to_settle_seconds",
    "Hive-side wait from the LAST dispatch of a job to its settled "
    "result, by priority class (the queue-wait histogram's twin: "
    "together they split a job's hive wall clock into waiting and "
    "executing)",
    ("class",),
    buckets=HIVE_LATENCY_BUCKETS,
)

# shed submissions remembered for trace assembly (job id -> events): a
# shed job has no record, but if the submitter retries the same id after
# backoff the admitted record's timeline should show the shed attempts.
# Both dimensions are bounded: distinct ids, AND events per id — a
# client hammering one id against a saturated hive must not grow a
# timeline that every later WAL event would then carry in full
_SHED_TRACE_LIMIT = 256
_SHED_EVENTS_PER_ID = 8


def job_class(job: dict) -> str:
    """The queue class a raw job dict belongs to; unknown/absent
    priorities land in "default" (legacy hives send no priority at all).
    """
    for key in ("priority", "sdaas_priority"):
        value = str(job.get(key, "")).lower()
        if value in JOB_CLASSES:
            return value
    return "default"


def parse_shed_watermarks(spec: str | None) -> dict[str, float]:
    """Parse "interactive:1.0,default:0.85,batch:0.5" (``=`` also
    accepted) into a class->fraction map; unknown classes are logged and
    dropped, values clamp to (0, 1], absent classes default to 1.0 (the
    flat limit). An empty spec means the stock degradation order."""
    marks = dict(DEFAULT_SHED_WATERMARKS)
    if spec is None:
        return marks
    spec = spec.strip()
    if not spec:
        return marks
    marks = {cls: 1.0 for cls in JOB_CLASSES}
    for part in spec.replace(";", ",").split(","):
        part = part.strip()
        if not part:
            continue
        sep = ":" if ":" in part else "="
        cls, _, value = part.partition(sep)
        cls = cls.strip().lower()
        if cls not in JOB_CLASSES:
            logger.warning("unknown class %r in shed watermark spec %r "
                           "ignored", cls, spec)
            continue
        try:
            marks[cls] = min(max(float(value), 1e-9), 1.0)
        except ValueError:
            logger.warning("unparseable shed watermark %r ignored", part)
    return marks


class QueueFull(Exception):
    """Admission control refused the job; the message is wire-ready."""


@dataclasses.dataclass
class JobRecord:
    """One job's hive-side lifecycle. `state` walks
    queued -> leased -> settling -> done, with the exits `failed`
    (redelivery budget exhausted), `cancelled` (revoked via
    POST /api/jobs/{id}/cancel — from queued or leased), and `expired`
    (the admission-time TTL lapsed while still queued), and a
    leased->queued loop on lease expiry ("settling" = result accepted,
    artifact spool write in flight)."""

    job: dict
    job_id: str
    job_class: str
    submitted_at: float  # monotonic (intervals); NEVER persisted as-is
    seq: int
    submitted_wall: float = 0.0  # wall clock twin, for the journal
    state: str = "queued"
    attempts: int = 0  # dispatches so far
    worker: str | None = None  # current/last lessee
    completed_by: str | None = None
    queue_wait_s: float | None = None  # first submit -> first dispatch
    placement: str | None = None  # last dispatch outcome
    result: dict | None = None  # spooled envelope (blob refs, not blobs)
    error: str | None = None
    done_at: float | None = None  # monotonic, stamped on result acceptance
    dispatched_at: float | None = None  # monotonic, LAST dispatch instant
    retired: bool = False  # already counted against history_limit
    # per-job trace timeline: ordered wall-stamped lifecycle events
    # ({"event", "wall", ...detail}), appended at every mutation site and
    # persisted verbatim with each journal event so a timeline survives
    # crash recovery, compaction, and standby promotion exactly like the
    # lease state it describes (GET /api/jobs/{id}/trace renders it)
    timeline: list = dataclasses.field(default_factory=list)
    # lazy-deletion bookkeeping: a deque entry (token, record) is live
    # iff the record is queued AND the token matches (requeue_front /
    # discard_queued bump it, turning older entries into tombstones)
    enqueue_token: int = 0
    # coalesce-compatibility bucket (coalesce.py), computed once at
    # admit/restore; None = not batchable. Derived state — never
    # journaled, always recomputable from the job dict
    coalesce: tuple | None = None
    # admission-time TTL: monotonic instant past which a still-QUEUED
    # job parks as `expired` (None = no deadline). Derived at admit and
    # restore from submitted_at + the job's own `deadline_s` (or the
    # hive_job_ttl_s default), so it spans restarts via the re-anchored
    # submitted_at — never persisted as-is
    expires_at: float | None = None
    # which lifecycle stage a cancel caught this job in ("queued" |
    # "leased"); carried in the WAL cancel event so replay, compaction,
    # and replication all reconstruct it
    cancel_stage: str | None = None
    # preemption-tolerant denoise (ISSUE 18): the latest mid-pass
    # checkpoint this job's lessee shipped ({step, sha256, signature,
    # bytes} — the blob itself lives in the spool, content-addressed)
    # and the progressive previews decoded so far ([{step, sha256,
    # bytes, href}]). Both ride the WAL (ev_checkpoint) so a restarted
    # or promoted hive still offers the resume and serves the previews;
    # both are cleared (and their blobs dropped) on terminal states.
    checkpoint: dict | None = None
    previews: list = dataclasses.field(default_factory=list)

    @property
    def tenant(self) -> str:
        """The submitter this job bills to (accounting.py). Derived from
        the job dict — which every WAL admit event carries verbatim — so
        attribution is replay- and replication-safe for free."""
        return tenant_of(self.job)

    def status(self) -> dict:
        """JSON-ready snapshot for GET /api/jobs/{id}."""
        out = {
            "id": self.job_id,
            "class": self.job_class,
            "tenant": self.tenant,
            "status": self.state,
            "attempts": self.attempts,
            "worker": self.worker,
            "completed_by": self.completed_by,
            "queue_wait_s": self.queue_wait_s,
            "placement": self.placement,
            "error": self.error,
            "result": self.result,
        }
        # progressive previews (ISSUE 18): while the pass is still
        # in flight, a poll carries the intermediate decodes so far (the
        # `partial` disposition) — terminal states clear them, so a
        # finished job's status never advertises stale partials
        if self.previews and self.state not in ("done", "failed",
                                                "cancelled", "expired"):
            out["partial"] = {
                "previews": [
                    {"step": int(p.get("step", 0)), "href": p.get("href")}
                    for p in self.previews
                ],
                **({"checkpoint_step": int(self.checkpoint.get("step", 0))}
                   if self.checkpoint else {}),
            }
        return out


class PriorityJobQueue:
    """Class-ordered FIFO queue + the record table for every job the hive
    has ever admitted this process. Single-threaded by design: every
    caller is an aiohttp handler or the reaper task on one event loop."""

    def __init__(self, depth_limit: int = 0, history_limit: int = 0,
                 shed_watermarks: dict[str, float] | None = None,
                 clock: HiveClock | None = None, job_ttl_s: float = 0.0):
        self.depth_limit = int(depth_limit)
        # admission-time TTL default (per-job `deadline_s` overrides);
        # 0 = queued jobs never expire
        self.job_ttl_s = max(float(job_ttl_s), 0.0)
        # finished (done/failed) records kept for GET /api/jobs/{id};
        # past this many the oldest are forgotten so a long-running
        # coordinator's memory is bounded by the limit, not its job
        # history (0 = keep everything)
        self.history_limit = int(history_limit)
        self.shed_watermarks = dict(
            shed_watermarks if shed_watermarks is not None
            else DEFAULT_SHED_WATERMARKS)
        self.clock = clock or CLOCK
        # SLO engine hook (slo.py, installed by HiveServer): the same
        # queue-wait / settle measurements the histograms observe also
        # feed the sliding-window burn-rate evaluation — one
        # measurement, two views. Replay paths never come through the
        # observing methods, so recovered history can't pollute a
        # live-traffic SLO window.
        self.slo = None
        self._queues: dict[str, deque[tuple[int, JobRecord]]] = {
            cls: deque() for cls in JOB_CLASSES
        }
        # live (queued) entries per class; deque lengths include
        # tombstones and must never be used as a depth
        self._live: dict[str, int] = {cls: 0 for cls in JOB_CLASSES}
        # gang index: (class, coalesce key) -> deque of the SAME
        # (token, record) tuples the class queue holds, so liveness is
        # one shared predicate. Per-class keying keeps gang pulls from
        # ever reordering across priority classes.
        self._by_key: dict[tuple, deque[tuple[int, JobRecord]]] = {}
        self.records: dict[str, JobRecord] = {}
        self._finished: deque[str] = deque()
        self._next_seq = 0
        # shed events for ids that were never admitted, folded into the
        # record's timeline if the id is later admitted (bounded)
        self.shed_traces: OrderedDict[str, list] = OrderedDict()
        self._refresh_gauges()

    def _refresh_gauges(self) -> None:
        for cls, n in self._live.items():
            _QUEUE_DEPTH.set(n, **{"class": cls})

    @property
    def depth(self) -> int:
        return sum(self._live.values())

    def depths(self) -> dict[str, int]:
        return dict(self._live)

    # --- lazy-deletion internals ---

    @staticmethod
    def _is_live(entry: tuple[int, JobRecord]) -> bool:
        token, record = entry
        return record.state == "queued" and token == record.enqueue_token

    def _enqueue(self, record: JobRecord, front: bool = False) -> None:
        record.enqueue_token += 1
        entry = (record.enqueue_token, record)
        q = self._queues[record.job_class]
        if front:
            q.appendleft(entry)
        else:
            q.append(entry)
        if record.coalesce is not None:
            # mirror the entry (not a copy) into the gang index; FIFO
            # position within the key tracks class-queue position because
            # both honor the same `front` flag
            kq = self._by_key.setdefault(
                (record.job_class, record.coalesce), deque())
            if front:
                kq.appendleft(entry)
            else:
                kq.append(entry)
        self._live[record.job_class] += 1
        self._refresh_gauges()

    def _dequeued(self, record: JobRecord) -> None:
        """Note one live entry of `record` turning into a tombstone (the
        caller already changed state / bumped the token). Compacts the
        class deque once tombstones outnumber live entries."""
        cls = record.job_class
        self._live[cls] = max(self._live[cls] - 1, 0)
        q = self._queues[cls]
        if len(q) - self._live[cls] > max(self._live[cls], 8):
            self._queues[cls] = deque(e for e in q if self._is_live(e))
            self._compact_key_index(cls)
        self._refresh_gauges()

    def _compact_key_index(self, cls: str) -> None:
        """Drop tombstones (and empty keys) from the gang index for one
        class — piggybacks on class-queue compaction so the index's
        memory is bounded by the same live-entry count."""
        for key in [k for k in self._by_key if k[0] == cls]:
            live = deque(e for e in self._by_key[key] if self._is_live(e))
            if live:
                self._by_key[key] = live
            else:
                del self._by_key[key]

    def queued_peers(self, record: JobRecord):
        """Queued batchmates of `record` — same class, same coalesce
        key, FIFO order, `record` itself excluded. Lazily sheds
        tombstones from the front as it walks. O(peers) per call."""
        if record.coalesce is None:
            return
        kq = self._by_key.get((record.job_class, record.coalesce))
        if not kq:
            return
        # shed dead entries at the head so a hot key's deque can't grow
        # unboundedly between compactions
        while kq and not self._is_live(kq[0]):
            kq.popleft()
        for entry in list(kq):
            if not self._is_live(entry):
                continue
            peer = entry[1]
            if peer is record:
                continue
            yield peer

    # --- admission ---

    def _ttl_of(self, job: dict) -> float:
        """Effective TTL for one job: its own `deadline_s` field when
        positive, else the hive-wide default. 0 = never expires."""
        raw = job.get("deadline_s")
        try:
            ttl = float(raw) if raw is not None else 0.0
        except (TypeError, ValueError):
            ttl = 0.0
        return ttl if ttl > 0 else self.job_ttl_s

    def shed_threshold(self, cls: str) -> int:
        """Queued-job count at which class `cls` submissions shed
        (0 = unlimited)."""
        if self.depth_limit <= 0:
            return 0
        # ceil, so a watermark of 1.0 reproduces the flat limit exactly
        # and tiny limits don't truncate a class to zero admission
        return max(math.ceil(
            self.depth_limit * self.shed_watermarks.get(cls, 1.0)), 1)

    def shedding(self) -> list[str]:
        """Classes whose watermark the current depth has crossed (for
        /healthz degraded reasons)."""
        if self.depth_limit <= 0:
            return []
        depth = self.depth
        return [cls for cls in JOB_CLASSES
                if depth >= self.shed_threshold(cls)]

    def submit(self, job: dict) -> JobRecord:
        """Admit one raw job dict; assigns an id when the submitter sent
        none. Raises QueueFull past the class's shed watermark — batch
        sheds first, interactive only at the full depth limit (a full
        hive must shed load, not reorder it away)."""
        job = dict(job)
        # noted BEFORE the id is filled in: only a submitter-chosen id
        # can ever recur, so only those are worth a shed-trace slot
        explicit_id = bool(job.get("id"))
        job_id = str(job.get("id") or uuid.uuid4().hex)
        job["id"] = job_id
        if job_id in self.records:
            # resubmission of a known id: hand back the existing record
            # (the hive dedupes by job id, mirroring what workers already
            # assume when they redeliver results at-least-once); dedup
            # beats admission — a retry of an admitted job is not load
            return self.records[job_id]
        cls = job_class(job)
        threshold = self.shed_threshold(cls)
        if threshold and self.depth >= threshold:
            _REFUSED.inc()
            _SHED.inc(**{"class": cls})
            if explicit_id:
                # an anonymous shed submission's generated id can never
                # recur; remembering it would only churn the bounded map
                # and evict a correlatable client's shed history
                self._note_shed(job_id, cls, threshold)
            raise QueueFull(
                f"hive queue full for {cls} jobs ({self.depth} queued, "
                f"limit {self.depth_limit}, {cls} sheds at {threshold}); "
                "resubmit later"
            )
        record = JobRecord(
            job=job,
            job_id=job_id,
            job_class=cls,
            submitted_at=self.clock.mono(),
            submitted_wall=self.clock.wall(),
            seq=self._next_seq,
            coalesce=coalesce_key(job),
        )
        ttl = self._ttl_of(job)
        if ttl > 0:
            record.expires_at = record.submitted_at + ttl
        # shed attempts for this id (the submitter backed off and
        # retried) lead the timeline — the backoff gap is real latency
        # the trace must attribute
        record.timeline = self.shed_traces.pop(job_id, [])
        record.timeline.append({
            "event": "admit", "wall": record.submitted_wall, "class": cls})
        self._next_seq += 1
        self.records[job_id] = record
        self._enqueue(record)
        _SUBMITTED.inc(**{"class": cls})
        return record

    def _note_shed(self, job_id: str, cls: str, threshold: int) -> None:
        """Remember a shed submission (trace assembly); only explicit
        submitter-chosen ids can ever be correlated with a later retry."""
        events = self.shed_traces.setdefault(job_id, [])
        events.append({
            "event": "shed", "wall": self.clock.wall(), "class": cls,
            "depth": self.depth, "threshold": threshold})
        if len(events) > _SHED_EVENTS_PER_ID:
            # keep the FIRST shed (when the backoff began) and the most
            # recent ones; the middle of a retry storm carries no signal
            del events[1:len(events) - (_SHED_EVENTS_PER_ID - 1)]
        self.shed_traces.move_to_end(job_id)
        while len(self.shed_traces) > _SHED_TRACE_LIMIT:
            self.shed_traces.popitem(last=False)

    def iter_queued(self):
        """Records in dispatch order: class rank, FIFO within class.
        Snapshot copy — callers take() entries while iterating."""
        for cls in JOB_CLASSES:
            for entry in list(self._queues[cls]):
                if self._is_live(entry):
                    yield entry[1]

    def take(self, record: JobRecord, worker: str, outcome: str,
             gang: dict | None = None) -> None:
        """Remove a queued record for dispatch and stamp its lease-side
        bookkeeping (attempts, queue wait on the first dispatch). `gang`
        is the dispatch-time grouping context ({id, size, index}) when
        this dispatch rode a gang-scheduled /work reply — recorded in
        the timeline (and therefore WAL-durable) so a trace shows the
        job arrived pre-batched."""
        record.state = "leased"
        record.worker = worker
        record.attempts += 1
        record.placement = outcome
        record.dispatched_at = self.clock.mono()
        if record.queue_wait_s is None:
            record.queue_wait_s = round(
                self.clock.mono() - record.submitted_at, 3)
            _QUEUE_WAIT.observe(record.queue_wait_s,
                                **{"class": record.job_class})
            if self.slo is not None:
                self.slo.observe(record.job_class, "queue_wait",
                                 record.queue_wait_s)
        event = {
            "event": "dispatch", "wall": self.clock.wall(),
            "worker": worker, "outcome": outcome,
            "attempt": record.attempts}
        if gang is not None:
            event["gang"] = str(gang.get("id"))
            event["gang_size"] = int(gang.get("size", 0))
            event["gang_index"] = int(gang.get("index", 0))
        record.timeline.append(event)
        self._dequeued(record)

    def observe_settle(self, record: JobRecord) -> None:
        """Feed the dispatch-to-settle histogram (the queue-wait twin);
        called once per settled result, never on replay."""
        if record.dispatched_at is None or record.done_at is None:
            return
        d2s = max(record.done_at - record.dispatched_at, 0.0)
        _DISPATCH_TO_SETTLE.observe(d2s, **{"class": record.job_class})
        if self.slo is not None:
            self.slo.observe(record.job_class, "dispatch_to_settle", d2s)
            self.slo.observe(
                record.job_class, "e2e",
                max(record.done_at - record.submitted_at, 0.0))

    def requeue_front(self, record: JobRecord) -> None:
        """Put an expired-lease job back at the FRONT of its class: a
        redelivery has already waited a full lease deadline and must not
        queue behind fresh arrivals of the same class. `worker` keeps
        the expired lessee's name — a LATE result from it is attributed
        correctly, and the next take() overwrites it anyway."""
        record.state = "queued"
        record.timeline.append({
            "event": "redeliver", "wall": self.clock.wall(),
            "worker": record.worker, "attempt": record.attempts})
        self._enqueue(record, front=True)

    # states a record can end in (history pruning + status rendering)
    TERMINAL_STATES = ("done", "failed", "cancelled", "expired")

    # --- mid-pass durability (ISSUE 18) ---

    def note_checkpoint(self, record: JobRecord, meta: dict) -> str | None:
        """Record the lessee's latest mid-pass checkpoint ({step, sha256,
        signature, bytes}); only the NEWEST is kept — a resume always
        wants the furthest step. Returns the superseded blob digest (for
        the caller to drop from the spool) or None."""
        old = (record.checkpoint or {}).get("sha256")
        record.checkpoint = dict(meta)
        record.timeline.append({
            "event": "checkpoint", "wall": self.clock.wall(),
            "step": int(meta.get("step", 0)),
            "bytes": int(meta.get("bytes", 0))})
        new = record.checkpoint.get("sha256")
        return old if old and old != new else None

    def note_preview(self, record: JobRecord, meta: dict) -> None:
        """Append one progressive preview ({step, sha256, bytes, href})
        to the record's partial disposition."""
        record.previews.append(dict(meta))
        record.timeline.append({
            "event": "preview", "wall": self.clock.wall(),
            "step": int(meta.get("step", 0)),
            "bytes": int(meta.get("bytes", 0))})

    def clear_partial(self, record: JobRecord) -> list[str]:
        """Drop a record's checkpoint + previews (terminal states keep
        neither: the final artifact supersedes every partial). Returns
        the now-unreferenced blob digests for the caller to drop from
        the spool."""
        digests = []
        if record.checkpoint:
            digests.append(record.checkpoint.get("sha256"))
        digests.extend(p.get("sha256") for p in record.previews)
        record.checkpoint = None
        record.previews = []
        return [d for d in digests if d]

    def partial_digests(self) -> set[str]:
        """Every blob digest a live checkpoint or preview still
        references (the spool retention sweep must not collect them)."""
        live: set[str] = set()
        for record in self.records.values():
            if record.state in self.TERMINAL_STATES:
                continue
            if record.checkpoint and record.checkpoint.get("sha256"):
                live.add(record.checkpoint["sha256"])
            for p in record.previews:
                if p.get("sha256"):
                    live.add(p["sha256"])
        return live

    def mark_cancelled(self, record: JobRecord, stage: str) -> None:
        """Move a record to the terminal `cancelled` state. `stage` names
        where the cancel caught it: "queued" (tombstoned from its class
        queue and the gang index before any dispatch) or "leased" (the
        lease is the caller's to settle; the record keeps its lessee so
        the /work piggyback knows whom to notify). Counted once per
        transition — replay paths restore state directly and never come
        through here."""
        self.discard_queued(record)
        record.state = "cancelled"
        record.cancel_stage = stage
        record.error = f"cancelled while {stage}"
        record.timeline.append({
            "event": "cancel", "wall": self.clock.wall(), "stage": stage,
            **({"worker": record.worker} if stage == "leased" else {})})
        _CANCELLED.inc(stage=stage)

    def mark_expired(self, record: JobRecord) -> None:
        """Move a still-queued record to the terminal `expired` state:
        its admission-time TTL lapsed before any worker could take it.
        Dispatch never sees it again, and a submitter poll reads the
        honest outcome instead of a stale queue position."""
        self.discard_queued(record)
        record.state = "expired"
        ttl = self._ttl_of(record.job)
        record.error = (
            f"expired: still queued {ttl:g}s after submission "
            "(hive_job_ttl_s / per-job deadline_s)")
        record.timeline.append({
            "event": "expire", "wall": self.clock.wall(), "ttl_s": ttl})
        _EXPIRED.inc()

    def expired_queued(self) -> list[JobRecord]:
        """Queued records whose TTL has lapsed (the caller parks them,
        journals the transition, and retires)."""
        now = self.clock.mono()
        return [r for r in self.iter_queued()
                if r.expires_at is not None and r.expires_at <= now]

    def retire(self, record: JobRecord) -> list[str]:
        """Note a record reaching a terminal state and prune the oldest
        finished ones past `history_limit`. Returns the pruned job ids
        (the journal must forget them too). Spooled artifact files stay
        on disk subject only to the retention sweep; a later poll for a
        pruned id answers 404, the same as a job this hive never knew."""
        if self.history_limit <= 0:
            return []
        if record.retired:
            # a failed job completed later by a late result passes
            # through twice (reaper, then _results); one _finished slot
            # per record or the pruning loop evicts other records early
            return []
        record.retired = True
        self._finished.append(record.job_id)
        pruned: list[str] = []
        while len(self._finished) > self.history_limit:
            old = self._finished.popleft()
            stale = self.records.get(old)
            if stale is not None and stale.state in self.TERMINAL_STATES:
                del self.records[old]
                pruned.append(old)
        return pruned

    def discard_queued(self, record: JobRecord) -> None:
        """Drop a record from its class queue if present (a late result
        arrived for a job we had already re-queued)."""
        if record.state != "queued":
            return
        # the token bump tombstones the deque entry whatever state the
        # caller moves the record to next
        record.enqueue_token += 1
        self._dequeued(record)

    # --- journal replay (no admission, no counters: these rebuild state
    # the metrics already counted in a previous process) ---

    def restore(self, job: dict, cls: str, seq: int, submitted_wall: float,
                queue_wait_s: float | None = None) -> JobRecord:
        """Recreate one admitted record from its journal event, queued.
        `submitted_at` is re-anchored into this process's monotonic
        timebase so interval arithmetic (queue wait, affinity hold,
        unplaceable parking) spans the restart correctly."""
        job_id = str(job.get("id", ""))
        record = JobRecord(
            job=dict(job),
            job_id=job_id,
            job_class=cls if cls in JOB_CLASSES else job_class(job),
            submitted_at=self.clock.mono_from_wall(submitted_wall),
            submitted_wall=submitted_wall,
            seq=int(seq),
            queue_wait_s=queue_wait_s,
            coalesce=coalesce_key(job),
        )
        ttl = self._ttl_of(record.job)
        if ttl > 0:
            # submitted_at was re-anchored above, so the TTL window spans
            # the restart: a job that expired while the hive was down
            # parks on the first post-recovery expiry sweep
            record.expires_at = record.submitted_at + ttl
        self._next_seq = max(self._next_seq, record.seq + 1)
        self.records[job_id] = record
        self._enqueue(record)
        return record

    def restore_leased(self, record: JobRecord, worker: str, attempts: int,
                       placement: str | None,
                       queue_wait_s: float | None) -> None:
        """Replay a dispatch: dequeue + stamp, without re-counting the
        queue-wait histogram or dispatch metrics."""
        record.state = "leased"
        record.worker = worker
        record.attempts = int(attempts)
        record.placement = placement
        # re-anchored to NOW, matching the fresh deadline the restored
        # lease gets — dispatch-to-settle for a recovered lease measures
        # from the recovery, never from a dead process's offset
        record.dispatched_at = self.clock.mono()
        if record.queue_wait_s is None:
            record.queue_wait_s = queue_wait_s
        self._dequeued(record)

    def forget(self, job_id: str) -> None:
        """Replay a history prune: the record is gone, as it was in the
        process that journaled the retire event."""
        record = self.records.pop(job_id, None)
        if record is not None:
            try:
                self._finished.remove(job_id)
            except ValueError:
                pass
