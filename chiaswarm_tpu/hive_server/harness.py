"""In-process swarm: a real HiveServer plus real Workers on real sockets.

The worker tests fake the hive; the hive tests fake the worker. This
harness is the seam where neither is faked: it stands up a HiveServer on
an ephemeral loopback port and N pristine `Worker` instances pointed at
it via HTTP, then drives jobs through POST /api/jobs. Used by the e2e
tests (tests/test_hive_server.py), the chaos lease-takeover scenario
(tools/chaos_smoke.py), and anything else that needs the whole swarm
loop without subprocesses.

Note: in-process workers share one registry/residency map, so two
LocalSwarm workers always advertise identical resident models. Scenarios
that need residency to DIFFER per worker (the affinity acceptance test,
the bench row) use worker subprocesses or simulated pollers instead.
"""

from __future__ import annotations

import asyncio
import dataclasses
from typing import TYPE_CHECKING, Any

import aiohttp

from ..settings import Settings
from .app import HiveServer
from .replication import StandbyHive

if TYPE_CHECKING:  # worker-side types only; see lazy import below
    from ..worker import Worker


class LocalSwarm:
    def __init__(self, n_workers: int = 1, chips_per_job: int = 0,
                 settings: Settings | None = None,
                 worker_overrides: dict[str, Any] | None = None,
                 standby: bool = False):
        self.settings = settings or Settings(
            sdaas_token="local-swarm", worker_name="swarm-worker",
            hive_port=0, metrics_port=0)
        self.n_workers = n_workers
        self.chips_per_job = chips_per_job
        self.worker_overrides = worker_overrides or {}
        # standby=True stands a WAL-shipped standby hive next to the
        # primary (replication.py) and gives every worker BOTH endpoints,
        # so failover scenarios — kill_primary(), promote() — run in
        # process. The standby journals to its own WAL dir; the
        # content-addressed artifact spool is shared by design.
        self.with_standby = standby
        self.standby: StandbyHive | None = None
        self.hive: HiveServer | None = None
        self.workers: list["Worker"] = []
        self._worker_tasks: list[asyncio.Task] = []
        self._session: aiohttp.ClientSession | None = None

    async def __aenter__(self) -> "LocalSwarm":
        return await self.start()

    async def __aexit__(self, *exc) -> None:
        await self.stop()

    async def start(self) -> "LocalSwarm":
        self.hive = await HiveServer(self.settings, port=0).start()
        if self.with_standby:
            wal = str(getattr(self.settings, "hive_wal_dir", "hive_wal"))
            self.standby = await StandbyHive(
                dataclasses.replace(
                    self.settings, hive_port=0,
                    hive_wal_dir=f"{wal}_standby" if wal else ""),
                primary_uri=self.hive.uri).start()
        for i in range(self.n_workers):
            self.add_worker(f"swarm-worker-{i}")
        self._session = aiohttp.ClientSession()
        return self

    @property
    def active_hive(self) -> HiveServer:
        """The hive currently entitled to serve: the promoted standby
        once promote()/failover happened, the primary before."""
        if self.standby is not None and self.standby.promoted:
            return self.standby.server
        return self.hive

    def worker_endpoints(self) -> list[str] | str:
        if self.standby is not None:
            return [self.hive.api_uri, self.standby.api_uri]
        return self.hive.api_uri

    def add_worker(self, name: str) -> "Worker":
        """Start one more pristine Worker against the hive (the
        second-worker half of takeover scenarios). Workers inherit the
        swarm's settings (a caller tuning e.g. job_deadline_s or
        batch_linger_ms configures the whole swarm, not just the hive),
        with per-worker identity and `worker_overrides` on top."""
        fields = {"metrics_port": 0}
        # overrides win over the harness defaults (a scenario that wants
        # a live worker /metrics endpoint passes metrics_port explicitly)
        # — except worker_name: per-worker identity keys the hive's
        # directory and lease attribution, so a shared override would
        # silently conflate every worker in the swarm
        fields.update(self.worker_overrides)
        fields["worker_name"] = name
        # lazy: the worker half pulls jax; a chip-less host must be able
        # to import hive_server.harness for its hive-only surface (SW001)
        from ..chips.allocator import SliceAllocator
        from ..worker import Worker

        worker = Worker(
            settings=dataclasses.replace(self.settings, **fields),
            allocator=SliceAllocator(chips_per_job=self.chips_per_job),
            hive_uri=self.worker_endpoints(),
        )
        self.workers.append(worker)
        self._worker_tasks.append(
            asyncio.create_task(worker.run(), name=f"swarm_{name}"))
        return worker

    async def kill_primary(self) -> None:
        """Hard-stop the primary hive: sockets close, in-flight requests
        die — externally indistinguishable from SIGKILLing its process
        (workers see refused connections, the standby sees stream+health
        silence and eventually promotes itself)."""
        await self.hive.stop()

    async def promote(self) -> HiveServer:
        """Promote the standby explicitly (the operator seam; the
        health-check loop does the same on its own after
        hive_failover_grace_s of primary silence)."""
        return await self.standby.promote()

    async def restart_hive(self) -> HiveServer:
        """Hard-stop the hive and stand a fresh instance up over the same
        $SDAAS_ROOT and port — the in-process analog of a coordinator
        restart. With the WAL enabled (the default) the new instance
        replays to the pre-stop queue + lease state; workers keep polling
        the same URI and never learn a restart happened beyond a few
        refused connections."""
        port = self.hive.port
        await self.hive.stop()
        self.hive = await HiveServer(self.settings, port=port).start()
        return self.hive

    async def stop_worker(self, worker: "Worker") -> None:
        """Hard-stop one worker (no drain) — 'the worker died mid-lease'."""
        idx = self.workers.index(worker)
        worker.stop()
        task = self._worker_tasks[idx]
        await asyncio.wait_for(
            asyncio.gather(task, return_exceptions=True), 10)

    async def stop(self) -> None:
        for worker in self.workers:
            worker.stop()
        await asyncio.gather(*self._worker_tasks, return_exceptions=True)
        self.workers.clear()
        self._worker_tasks.clear()
        if self._session is not None:
            await self._session.close()
            self._session = None
        if self.standby is not None:
            await self.standby.stop()
        if self.hive is not None:
            await self.hive.stop()

    # --- client surface (real HTTP against the hive) ---

    def _headers(self) -> dict:
        return {"Authorization": f"Bearer {self.settings.sdaas_token}",
                "Content-type": "application/json"}

    async def submit(self, job: dict) -> str:
        import json

        async with self._session.post(
                f"{self.active_hive.api_uri}/jobs", data=json.dumps(job),
                headers=self._headers()) as resp:
            resp.raise_for_status()
            payload = await resp.json()
            return payload["id"]

    async def cancel(self, job_id: str) -> dict:
        """POST /api/jobs/{id}/cancel against the active hive (the
        submitter-side revoke the cancellation scenarios drive)."""
        async with self._session.post(
                f"{self.active_hive.api_uri}/jobs/{job_id}/cancel",
                headers=self._headers()) as resp:
            resp.raise_for_status()
            return await resp.json()

    async def job_status(self, job_id: str) -> dict:
        async with self._session.get(
                f"{self.active_hive.api_uri}/jobs/{job_id}",
                headers=self._headers()) as resp:
            resp.raise_for_status()
            return await resp.json()

    async def wait_done(self, job_id: str, timeout: float = 240.0,
                        accept_failed: bool = False) -> dict:
        """Poll until the job reaches a terminal state; returns the
        status snapshot (result included, blobs as spool refs)."""
        deadline = asyncio.get_running_loop().time() + timeout
        while True:
            status = await self.job_status(job_id)
            if status["status"] == "done":
                return status
            if status["status"] == "failed":
                if accept_failed:
                    return status
                raise AssertionError(
                    f"job {job_id} failed at the hive: {status['error']}")
            if asyncio.get_running_loop().time() >= deadline:
                raise asyncio.TimeoutError(
                    f"job {job_id} still {status['status']} "
                    f"after {timeout:.0f}s")
            await asyncio.sleep(0.05)

    async def artifact(self, href_or_digest: str) -> bytes:
        path = (href_or_digest if href_or_digest.startswith("/")
                else f"/api/artifacts/{href_or_digest}")
        async with self._session.get(f"{self.active_hive.uri}{path}",
                                     headers=self._headers()) as resp:
            resp.raise_for_status()
            return await resp.read()
