"""Fleet stage statistics and straggler detection.

A gang-scheduled swarm has a new failure mode the per-worker telemetry
cannot see: one slice that runs every stage 3x slower than its peers —
a thermally throttled chip, a host swapping, a half-broken driver —
silently drags EVERY gang it joins down to its pace. The worker itself
looks healthy (it polls, it completes jobs); only a FLEET-relative view
can tell it is the straggler.

The raw material rides the /work poll: each worker piggybacks a compact
per-stage EWMA blob in the ``stats`` query param (worker.py maintains
the EWMAs from its own settled envelopes' stage timings — per Worker
instance, alpha = ``hive_stats_ewma_alpha``)::

    stats={"a": 0.2, "s": {"job": [1.234, 17], "denoise": [0.81, 17]}}

(``s`` maps stage -> [ewma_seconds, sample_count]; the param is
conformance-pinned, and a hive that predates it simply ignores the
unknown key.)

This module keeps the latest blob per worker and compares each worker's
per-stage EWMA against the MEDIAN of its live peers (median of the
*others*, so one extreme value cannot drag the baseline toward itself —
with two workers the peer median is simply the other worker). A worker
is an outlier on a stage when its EWMA exceeds
``hive_straggler_factor`` x the peer median AND beats it by an absolute
floor (MIN_DELTA_S — microsecond-scale stages must not flag on noise),
with at least MIN_SAMPLES observations on both sides and at least
MIN_REPORTERS live workers reporting the stage.

Outliers are exported as ``swarm_hive_worker_outlier{worker}`` (live
workers only — series retire with the directory) and surfaced to the
dispatcher, which deprioritizes stragglers for INTERACTIVE seeds: an
interactive job inside its placement-hold window is withheld from an
outlier's poll while a healthy capable worker is live (counted as
``swarm_hive_dispatch_total{outcome="straggler_hold"}``), so
observability feeds placement — the slow slice keeps serving batch
traffic, but latency-sensitive work routes around it.
"""

from __future__ import annotations

import json
import logging
import statistics

from .. import telemetry

logger = logging.getLogger(__name__)

# minimum EWMA sample count before a stage participates (both for the
# candidate and for any peer feeding the median)
MIN_SAMPLES = 3
# minimum live workers reporting a stage before anyone can be judged
MIN_REPORTERS = 2
# absolute slowdown floor: a stage must be this many seconds over the
# peer median (on top of the factor) to flag — sub-50ms jitter on fast
# stages is noise, not a straggler
MIN_DELTA_S = 0.05

_OUTLIER = telemetry.gauge(
    "swarm_hive_worker_outlier",
    "1 while this worker's per-stage EWMA marks it a fleet straggler "
    "(slower than hive_straggler_factor x the live peer median on some "
    "stage), 0 for a healthy live worker",
    ("worker",),
)


def parse_stats(raw: str | None) -> dict[str, tuple[float, int]]:
    """The /work ``stats`` query param -> {stage: (ewma_s, n)}. Tolerant
    of anything — the blob is worker-volunteered advisory data and a
    malformed one must cost the stats, never the poll."""
    if not raw:
        return {}
    try:
        blob = json.loads(raw)
    except ValueError:
        return {}
    stages = blob.get("s") if isinstance(blob, dict) else None
    if not isinstance(stages, dict):
        return {}
    out: dict[str, tuple[float, int]] = {}
    for stage, pair in stages.items():
        if not (isinstance(stage, str)
                and isinstance(pair, (list, tuple)) and len(pair) == 2):
            continue
        try:
            ewma, n = float(pair[0]), int(pair[1])
        except (ValueError, TypeError):
            continue  # one bad entry must not cost the rest
        if ewma >= 0 and n >= 0:
            out[stage] = (ewma, n)
    return out


class FleetStats:
    """Latest per-worker stage EWMAs + the fleet-relative outlier
    verdicts. Owned by the HiveServer, fed by WorkerDirectory.observe,
    read by Dispatcher.select — all on one event loop."""

    def __init__(self, factor: float = 2.5):
        self.factor = max(float(factor), 1.0)
        self._stats: dict[str, dict[str, tuple[float, int]]] = {}
        self._exported: set[str] = set()
        # verdict memo: every poll reads verdicts (observe refreshes the
        # gauge, select gates placement for each live peer), so the full
        # evaluation is computed ONCE per (stats generation, live set)
        # in a single pass over the fleet instead of per caller
        self._gen = 0
        self._verdict_key: tuple | None = None
        self._verdicts: dict[str, list[str]] = {}

    def note(self, worker: str, stages: dict[str, tuple[float, int]]) -> None:
        if stages and self._stats.get(worker) != stages:
            self._stats[worker] = stages
            self._gen += 1

    def forget(self, worker: str) -> None:
        """Directory aged the worker out; its stats and gauge series go
        with it (a dead worker is not a straggler, it is gone)."""
        if self._stats.pop(worker, None) is not None:
            self._gen += 1
        if worker in self._exported:
            _OUTLIER.remove(worker=worker)
            self._exported.discard(worker)

    def stages_of(self, worker: str) -> dict[str, tuple[float, int]]:
        return dict(self._stats.get(worker, {}))

    def verdicts(self, live: list[str]) -> dict[str, list[str]]:
        """{reporting live worker: stages flagged} — the whole fleet
        judged in one pass (per stage: collect the qualifying reporters,
        compare each against the median of the OTHERS), memoized until
        the stats or the live set change."""
        key = (self._gen, tuple(sorted(live)))
        if key == self._verdict_key:
            return self._verdicts
        result: dict[str, list[str]] = {
            w: [] for w in live if w in self._stats}
        by_stage: dict[str, list[tuple[str, float]]] = {}
        for worker in result:
            for stage, (ewma, n) in self._stats[worker].items():
                if n >= MIN_SAMPLES:
                    by_stage.setdefault(stage, []).append((worker, ewma))
        for stage, pairs in by_stage.items():
            if len(pairs) < MIN_REPORTERS:
                continue
            values = sorted(e for _, e in pairs)
            for worker, ewma in pairs:
                # peer baseline: the sorted values minus ONE instance of
                # this worker's own (equal values are interchangeable)
                i = values.index(ewma)
                baseline = statistics.median(values[:i] + values[i + 1:])
                if (ewma > self.factor * baseline
                        and ewma - baseline > MIN_DELTA_S):
                    result[worker].append(stage)
        self._verdict_key, self._verdicts = key, result
        return result

    def outlier_stages(self, worker: str, live: list[str]) -> list[str]:
        """Stages on which `worker` is a straggler relative to its live
        peers' median (see module docstring for the gate stack)."""
        return self.verdicts(live).get(worker, [])

    def is_outlier(self, worker: str, live: list[str]) -> bool:
        return bool(self.outlier_stages(worker, live))

    def snapshot(self, live: list[str]) -> dict:
        """/healthz view: per-live-worker flagged stages (empty list =
        healthy), for operators and swarm_top."""
        return dict(self.verdicts(live))

    def refresh_metrics(self, live: list[str]) -> None:
        """Re-export the outlier gauge for exactly the live reporters;
        series for departed workers are removed, not zeroed forever."""
        verdicts = self.verdicts(live)
        for worker, flagged in verdicts.items():
            _OUTLIER.set(1 if flagged else 0, worker=worker)
        for stale in self._exported - set(verdicts):
            _OUTLIER.remove(worker=stale)
        self._exported = set(verdicts)
