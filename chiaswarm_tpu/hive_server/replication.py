"""Replicated hive: a WAL-shipped standby with health-checked failover.

PR 6 made one hive durable — its queue/lease state survives its own
death because the WAL replays on restart. The hive HOST was still a
single point of failure: nothing restarts a process whose machine is
gone. This module closes that gap with the classic primary/standby
shape, built on the journal the durability work already pays for:

- the **standby** is a full :class:`~.app.HiveServer` in standby mode
  (409 not-primary on /work, /results, and /api/jobs until promoted)
  that tails the primary's WAL event stream over HTTP
  (``GET /api/replication/stream?since=<rs>``) every
  ``hive_replication_poll_s`` and applies events through the exact
  replay path recovery uses (:func:`~.journal.apply_events`), so the
  replica is correct by the same argument restart-recovery is;
- the stream is **resumable**: every journal event carries a replication
  sequence (``rs``); a standby presents the last one it applied and gets
  the tail. Compaction retires history — a standby whose position was
  compacted away receives the full compacted snapshot with
  ``reset=True`` and rebuilds from scratch, never replaying retired
  events. Torn WAL tails never reach a replica (the stream is served
  from the journal's in-memory mirror);
- the standby **health-checks** the primary: a stream failure is
  confirmed against ``/healthz`` (any HTTP answer, even a degraded 503,
  means the process lives), and after ``hive_failover_grace_s`` of
  unbroken silence the standby **promotes itself** — drains the stream
  best-effort, re-grants every replicated lease with a fresh full
  deadline (PR 6 semantics: a surviving lessee's result lands on the
  idempotent-ACK path, a dead one costs one deadline), bumps the fencing
  **epoch**, journals it durably, and starts answering /work;
- the **epoch** is the split-brain fence. Every hive answer advertises
  its epoch (``X-Hive-Epoch``); workers track the maximum (persisted per
  worker host, so it survives restarts) and echo it on every request. A
  deposed primary that comes back sees requests stamped with a newer
  epoch than its own and answers 409 (``_refuse_stale_epoch``) instead
  of dispatching or settling — its late ACKs cannot double-settle a job
  the promoted hive owns, and workers treat the 409 as a not-primary
  refusal and stay failed over.

Scope of the fence, stated honestly: it reaches every client that
CONTACTS the promoted hive — which multi-endpoint workers do the moment
their pinned primary errors or refuses. What a two-node,
no-quorum design cannot fence is a clean asymmetric partition that cuts
only the hive-to-hive link while the old primary stays reachable: the
standby (unable to see /healthz) promotes, and a client that never
talks to the promoted side never learns the new epoch, so the deposed
primary can still serve it. The at-least-once lifecycle bounds the
damage to duplicate compute (settles are idempotent per hive), but
submitters who must not land work on a deposed primary during such a
partition should use the same multi-endpoint failover the workers do
(so they learn the epoch), or front the pair with an external health
check. Leases replicated at promotion get a FRESH deadline either way,
so nothing is lost — at worst re-run.

Deploy: run the standby with ``hive_standby_of`` /
``CHIASWARM_HIVE_STANDBY_OF`` pointing at the primary's site URI (its
own ``hive_wal_dir`` must be a different directory when both share a
filesystem); point workers at both hives via ``sdaas_uris`` /
``CHIASWARM_HIVE_URIS``. The worker-side half lives in
``chiaswarm_tpu/hive.py`` (endpoint pinning + failover).
"""

from __future__ import annotations

import asyncio
import logging

import aiohttp

from .. import faults, telemetry
from ..settings import Settings, load_settings
from .app import HiveServer
from .clock import CLOCK
from .journal import apply_events, snapshot_events

logger = logging.getLogger(__name__)

_APPLIED = telemetry.counter(
    "swarm_hive_replication_applied_total",
    "Journal events applied from the primary's replication stream")
_RESETS = telemetry.counter(
    "swarm_hive_replication_resets_total",
    "Full standby resyncs (the standby's stream position was compacted "
    "away on the primary; state rebuilt from the snapshot)")
_PROMOTIONS = telemetry.counter(
    "swarm_hive_promotions_total",
    "Standby self-promotions after the primary failed its health checks")
_LAG = telemetry.gauge(
    "swarm_hive_replication_lag_s",
    "Seconds since the standby last applied the primary's stream tip")


class StandbyHive:
    """One standby instance: a HiveServer in standby mode plus the
    replication tail and the failover watchdog. ``start()`` serves and
    begins tailing; ``promote()`` can also be called explicitly (operator
    seam, LocalSwarm.promote(), tests)."""

    def __init__(self, settings: Settings | None = None,
                 primary_uri: str | None = None,
                 host: str | None = None, port: int | None = None):
        self.settings = settings or load_settings()
        g = lambda name, default: getattr(self.settings, name, default)  # noqa: E731
        self.primary_uri = str(
            primary_uri or g("hive_standby_of", "")).rstrip("/")
        if not self.primary_uri:
            raise ValueError(
                "a standby needs the primary's URI (hive_standby_of / "
                "CHIASWARM_HIVE_STANDBY_OF or the primary_uri argument)")
        self.poll_s = max(float(g("hive_replication_poll_s", 1.0)), 0.02)
        self.grace_s = max(float(g("hive_failover_grace_s", 10.0)), 0.0)
        # replication-lag health: past this many seconds without an
        # applied sync the standby's /healthz goes degraded (503) — a
        # silently stalled standby must not look healthy right up until
        # the failover it can no longer serve (0 disables)
        self.lag_degraded_s = float(g("hive_replication_lag_degraded_s", 30.0))
        self.server = HiveServer(
            self.settings, host=host, port=port, standby=True)
        # the standby's /healthz carries the replication view + verdict
        self.server.extra_health = self.health
        # the primary's stream is authoritative from the first sync:
        # whatever a stale standby-side WAL replayed is discarded (a
        # standby restart full-resyncs rather than trusting old state)
        self._reset_state()
        self.promoted = False
        self.since = 0
        self.primary_epoch = 0
        # the primary's stream tip as of the last successful fetch: the
        # rs delta vs `since` is the apply backlog (0 when caught up)
        self.primary_seq = 0
        self.last_sync_mono: float | None = None
        self.started_mono = CLOCK.mono()
        self._first_failure: float | None = None
        self._session: aiohttp.ClientSession | None = None
        self._tasks: list[asyncio.Task] = []

    # --- lifecycle ---

    @property
    def uri(self) -> str:
        return self.server.uri

    @property
    def api_uri(self) -> str:
        return self.server.api_uri

    async def __aenter__(self) -> "StandbyHive":
        return await self.start()

    async def __aexit__(self, *exc) -> None:
        await self.stop()

    async def start(self) -> "StandbyHive":
        await self.server.start()
        self._tasks = [asyncio.create_task(
            self._replicate_loop(), name="hive_standby_replicator")]
        logger.info(
            "hive standby on %s replicating from %s (poll %.2gs, "
            "failover grace %.2gs)",
            self.server.uri, self.primary_uri, self.poll_s, self.grace_s)
        return self

    async def stop(self) -> None:
        for task in self._tasks:
            task.cancel()
        if self._tasks:
            await asyncio.gather(*self._tasks, return_exceptions=True)
            self._tasks = []
        if self._session is not None and not self._session.closed:
            await self._session.close()
        await self.server.stop()

    # --- replication tail ---

    def _reset_state(self) -> None:
        """Discard the replica and start over from the primary's
        snapshot (initial sync, or the stream position was compacted
        away). Safe because the standby refuses every mutating request
        until promoted — nothing else touches these tables."""
        self.server.queue, self.server.leases = self.server._new_state()
        self.server.dag = self.server._new_dag()
        self.since = 0

    async def _get_session(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession()
        return self._session

    def _headers(self) -> dict[str, str]:
        token = self.server.token
        return {"Authorization": f"Bearer {token}"} if token else {}

    async def sync_once(self) -> int:
        """One stream fetch + apply; returns the number of events
        applied. Raises on any transport/protocol failure — the loop
        (or the caller) decides what a failure means."""
        # deterministic injection: the stream fetch dies (partition /
        # primary mid-crash); the next sync must resume cleanly
        faults.fire("drop_replication")
        session = await self._get_session()
        async with session.get(
                f"{self.primary_uri}/api/replication/stream",
                params={"since": str(self.since)},
                headers=self._headers(),
                timeout=aiohttp.ClientTimeout(total=10),
        ) as resp:
            if resp.status != 200:
                raise RuntimeError(
                    f"replication stream answered {resp.status}: "
                    f"{(await resp.text())[:200]}")
            payload = await resp.json()
        events = payload.get("events") or []
        if payload.get("reset"):
            _RESETS.inc()
            logger.warning(
                "replication reset: position %d was compacted away on "
                "the primary; rebuilding from its %d-event snapshot",
                self.since, len(events))
            self._reset_state()
        if events:
            summary = apply_events(
                events, self.server.queue, self.server.leases,
                dag=self.server.dag)
            _APPLIED.inc(len(events))
            logger.debug("replicated %d event(s) -> %s", len(events), summary)
            # replicated settles carry usage (the ledger is derived from
            # the records); refresh the per-tenant gauges here or this
            # standby's /metrics would disagree with its own /api/usage
            # until promotion — once per applied sync, never per event
            self.server.refresh_usage_metrics()
        # a reset ADOPTS the primary's position outright (it may be LOWER
        # than ours was — wiped/truncated primary WAL); only incremental
        # replies move the cursor monotonically. (_reset_state already
        # zeroed self.since above, so max() would behave identically —
        # this spells the contract out rather than relying on that.)
        seq = int(payload.get("seq", self.since))
        self.primary_seq = seq
        self.since = seq if payload.get("reset") else max(self.since, seq)
        self.primary_epoch = max(
            self.primary_epoch, int(payload.get("epoch", 0)))
        # track the primary's epoch while standby so promotion always
        # bumps PAST it, and stale-epoch fencing stays coherent
        if self.server.epoch < self.primary_epoch:
            self.server.epoch = self.primary_epoch
            self.server.note_role_change()
        self.last_sync_mono = CLOCK.mono()
        _LAG.set(0.0)
        return len(events)

    async def _primary_alive(self) -> bool:
        """ANY HTTP answer from /healthz counts as alive — a degraded
        503 primary is still the primary; only silence (connection
        refused, timeout) argues for failover."""
        try:
            session = await self._get_session()
            timeout = aiohttp.ClientTimeout(
                total=max(min(self.grace_s / 2, 5.0), 0.25))
            async with session.get(f"{self.primary_uri}/healthz",
                                   timeout=timeout):
                return True
        except asyncio.CancelledError:
            raise
        except Exception:
            return False

    async def _replicate_loop(self) -> None:
        while not self.promoted:
            try:
                await self.sync_once()
                self._first_failure = None
            except asyncio.CancelledError:
                raise
            except Exception as e:
                if self.last_sync_mono is not None:
                    _LAG.set(round(CLOCK.mono() - self.last_sync_mono, 1))
                if await self._primary_alive():
                    # the process answers health but not the stream
                    # (e.g. WAL disabled, auth mismatch): not a failover
                    # case — promotion here would split the brain
                    self._first_failure = None
                    logger.warning(
                        "replication stream failed (%s) but the primary "
                        "answers /healthz; not counting toward failover",
                        e)
                else:
                    now = CLOCK.mono()
                    if self._first_failure is None:
                        self._first_failure = now
                        logger.warning(
                            "primary %s unreachable (%s); failover in "
                            "%.2gs unless it recovers",
                            self.primary_uri, e, self.grace_s)
                    elif now - self._first_failure >= self.grace_s:
                        logger.error(
                            "primary %s silent for %.2gs; promoting",
                            self.primary_uri, now - self._first_failure)
                        try:
                            await self.promote()
                            return
                        except asyncio.CancelledError:
                            raise
                        except Exception:
                            # the watchdog must never die silently half-
                            # promoted; promote() is idempotent-safe to
                            # retry (the epoch only moves forward)
                            logger.exception(
                                "promotion attempt failed; retrying")
            await asyncio.sleep(self.poll_s)

    # --- failover ---

    async def promote(self) -> HiveServer:
        """Promote this standby to primary: drain the stream best-effort,
        bump the fencing epoch past everything seen, re-grant every
        replicated lease with a fresh full deadline, persist it all to
        the standby's own WAL, and start serving. Idempotent."""
        if self.promoted:
            return self.server
        try:
            await self.sync_once()
        except Exception as e:
            logger.warning(
                "promotion: final stream drain failed (%s); proceeding "
                "with the replicated state at position %d", e, self.since)
        srv = self.server
        srv.epoch = max(srv.epoch, self.primary_epoch) + 1
        regranted = 0
        for lease in srv.leases.active():
            # fresh full deadline, exactly like WAL-replay recovery: the
            # lessee may still be running (idempotent-ACK absorbs its
            # result) or died with the primary (one deadline, then
            # redelivery)
            srv.leases.grant(lease.record, lease.worker)
            regranted += 1
        srv.standby = False
        # the stream may have delivered a stage settle without its
        # trailing ev_dag (primary died between the appends): re-derive
        # stage states from the replicated records and re-admit ready
        # successors before this hive serves its first poll
        srv.dag.reconcile(srv.queue)
        if srv.journal is not None:
            try:
                srv.journal.compact(
                    snapshot_events(srv.queue, srv.leases, srv.epoch,
                                    dag=srv.dag))
            except OSError:
                # same degradation policy as HiveServer._journal: a full
                # disk costs restart-durability of the promotion, never
                # the promotion itself — the swarm needs a primary NOW
                logger.exception(
                    "promotion snapshot failed; serving as primary at "
                    "epoch %d anyway (state is NOT restart-durable)",
                    srv.epoch)
        # replication applied cancel events straight into the record
        # table; the promoted hive must also take over the NOTIFY half
        # (tell surviving lessees about revocations on their next poll)
        srv.rebuild_cancel_notify()
        # ...and the tenant gauges must reflect the replicated ledger
        # from the promoted hive's first scrape (the final drain above
        # may have failed, so don't rely on sync_once's refresh)
        srv.refresh_usage_metrics()
        srv.note_role_change()
        _PROMOTIONS.inc()
        self.promoted = True
        logger.warning(
            "standby promoted to PRIMARY at epoch %d: %d job record(s), "
            "%d lease(s) re-granted with fresh %gs deadlines",
            srv.epoch, len(srv.queue.records), regranted,
            srv.leases.deadline_s)
        return srv

    def health(self) -> dict:
        """Replication-side health, installed as the server's
        ``extra_health`` so the standby's /healthz carries it: the
        applied replication position vs the primary's stream tip (rs
        delta = apply backlog) and the seconds since the last applied
        sync. Past ``hive_replication_lag_degraded_s`` of stall the
        standby reports itself degraded (503) — a silently stalled
        standby must not look healthy until the failover that then finds
        it hopelessly behind. A standby that has NEVER synced reports
        ``last_sync_age_s: null`` (the stall clock still runs from
        standby start, so it degrades on schedule — but nobody is told a
        sync happened that never did)."""
        never_synced = self.last_sync_mono is None
        stalled_s = round(CLOCK.mono() - (
            self.started_mono if never_synced else self.last_sync_mono), 2)
        reasons: list[str] = []
        if (not self.promoted and self.lag_degraded_s > 0
                and stalled_s > self.lag_degraded_s):
            reasons.append(
                "replication stalled: "
                + ("NO sync has ever been applied"
                   if never_synced else
                   f"last applied sync {stalled_s:.0f}s ago")
                + f" (degraded past {self.lag_degraded_s:g}s; applied rs "
                f"{self.since}, primary tip rs {self.primary_seq})")
        return {
            "replication": {
                "promoted": self.promoted,
                "primary_uri": self.primary_uri,
                "rs_applied": self.since,
                "rs_primary_tip": self.primary_seq,
                "rs_delta": max(self.primary_seq - self.since, 0),
                "last_sync_age_s": None if never_synced else stalled_s,
                "lag_degraded_s": self.lag_degraded_s,
            },
            "degraded_reasons": reasons,
        }
