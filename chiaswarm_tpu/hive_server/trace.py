"""Per-job trace assembly: one ordered, gap-attributed timeline.

The raw material is collected elsewhere — the hive stamps wall-clock
lifecycle events into ``JobRecord.timeline`` at every mutation site
(admit/shed in queue.py, dispatch in ``take()``, lease grants in
leases.py, redeliver/park in the reaper path, settle in app.py), the
journal persists the timeline with every WAL event so it survives crash
recovery, compaction, and standby promotion, and the worker's
``trace_job`` stage spans ride back inside the result envelope's
``pipeline_config.timings`` (with the wire trace context echoed under
``pipeline_config.trace``). This module is the read side: it merges
those sources into the one answer nobody could give before —
"where did job X spend its 40 seconds?" — served at
``GET /api/jobs/{id}/trace`` and asserted gap-free by the bench's
trace_e2e row.

The timeline contract:

- events are ordered by their wall stamps (they are appended in order;
  sorting is stable, so two events sharing an instant — dispatch and its
  lease — keep their append order);
- every inter-event gap is attributed (hive_queue, executing,
  lease_lost, resubmit_backoff, ...) so the sum of gaps IS the job's
  hive wall clock, with nothing hidden;
- the final executing gap is broken down further with the worker's own
  stage spans; whatever the spans do not cover (network hops, envelope
  spooling, result upload) is reported honestly as ``unattributed_s``
  rather than silently absorbed.
"""

from __future__ import annotations

from typing import Any

# gap attribution between consecutive timeline events, keyed on
# (from_event, to_event); pairs not listed fall back to "other"
_GAP_LABELS = {
    ("shed", "shed"): "resubmit_backoff",
    ("shed", "admit"): "resubmit_backoff",
    ("admit", "dispatch"): "hive_queue",
    ("admit", "hold"): "hive_queue",
    ("hold", "dispatch"): "affinity_hold",
    ("redeliver", "hold"): "hive_queue",
    ("redeliver", "dispatch"): "hive_queue",
    ("dispatch", "lease"): "hive_grant",
    ("lease", "settle"): "executing",
    ("lease", "redeliver"): "lease_lost",
    ("lease", "lease"): "lease_regrant",
    ("lease", "park"): "lease_lost",
    ("dispatch", "settle"): "executing",
    ("admit", "park"): "unplaceable_wait",
    # cancellation & deadlines (ISSUE 10): a cancel caught the job
    # waiting (hive_queue) or executing; an expire is TTL'd queue time
    ("admit", "cancel"): "hive_queue",
    ("hold", "cancel"): "hive_queue",
    ("redeliver", "cancel"): "hive_queue",
    ("dispatch", "cancel"): "executing",
    ("lease", "cancel"): "executing",
    ("cancel", "settle"): "cancel_vs_result_race",
    ("admit", "expire"): "ttl_expired",
    ("hold", "expire"): "ttl_expired",
    ("redeliver", "expire"): "ttl_expired",
    # mid-pass durability (ISSUE 18): checkpoint/preview events land
    # DURING execution, so the spans around them are still executing
    # time; a lease lost after a checkpoint is the resume-saved window
    ("lease", "checkpoint"): "executing",
    ("dispatch", "checkpoint"): "executing",
    ("checkpoint", "checkpoint"): "executing",
    ("checkpoint", "preview"): "executing",
    ("checkpoint", "settle"): "executing",
    ("checkpoint", "redeliver"): "lease_lost",
    ("checkpoint", "cancel"): "executing",
    ("checkpoint", "park"): "lease_lost",
    ("lease", "preview"): "executing",
    ("dispatch", "preview"): "executing",
    ("preview", "preview"): "executing",
    ("preview", "checkpoint"): "executing",
    ("preview", "settle"): "executing",
    ("preview", "redeliver"): "lease_lost",
    ("preview", "cancel"): "executing",
    ("preview", "park"): "lease_lost",
    # a redelivered dispatch carrying a resume offer stamps it between
    # the lease grant and the (shorter) execution window
    ("lease", "resume_offer"): "hive_grant",
    ("resume_offer", "checkpoint"): "executing",
    ("resume_offer", "preview"): "executing",
    ("resume_offer", "settle"): "executing",
    ("resume_offer", "redeliver"): "lease_lost",
    ("resume_offer", "cancel"): "executing",
    ("resume_offer", "park"): "lease_lost",
}

def worker_stages(result: dict | None) -> list[dict]:
    """The worker's stage spans from a settled envelope, in the order
    the worker recorded them: ``pipeline_config.timings``'s ``*_s``
    entries (insertion order is stage order — JSON preserves it)."""
    if not isinstance(result, dict):
        return []
    cfg = result.get("pipeline_config")
    if not isinstance(cfg, dict):
        return []
    timings = cfg.get("timings")
    if not isinstance(timings, dict):
        return []
    stages = []
    # every *_s timing is a stage — queue_wait_s included: the worker-
    # side handoff wait is a real slice of the execution window
    for key, value in timings.items():
        if not isinstance(key, str) or not key.endswith("_s"):
            continue
        try:
            stages.append({"stage": key[:-2], "seconds": float(value)})
        except (TypeError, ValueError):
            continue
    return stages


def wire_trace_context(record, gang: dict | None = None) -> dict:
    """The trace context a /work reply carries into the worker: enough
    for the worker to stamp its half of the trace into the envelope and
    for the hive to attribute the returning spans to the right dispatch
    attempt. Field set is pinned by the protocol-conformance suite.

    `gang` ({id, size, index}) rides along when this dispatch left as
    part of a gang-scheduled group — the worker's poll loop uses the id
    to feed the members into its BatchScheduler as one pre-formed group
    (flush reason "gang", no linger). Solo dispatches carry NO gang key
    at all, so a legacy worker sees nothing new."""
    dispatched_wall = None
    for entry in reversed(record.timeline):
        if entry.get("event") == "dispatch":
            dispatched_wall = entry.get("wall")
            break
    context = {
        "id": record.job_id,
        "attempt": record.attempts,
        "dispatched_wall": dispatched_wall,
        "queue_wait_s": record.queue_wait_s,
    }
    if gang is not None:
        context["gang"] = {
            "id": str(gang.get("id")),
            "size": int(gang.get("size", 0)),
            "index": int(gang.get("index", 0)),
        }
    stage = record.job.get("stage") if isinstance(record.job, dict) else None
    if isinstance(stage, dict) and stage.get("workflow"):
        # stage-jobs (ISSUE 20) carry their graph coordinates so the
        # worker's envelope echo — and anything tailing the wire — can
        # attribute spans to the parent workflow; monolithic dispatches
        # carry NO stage key, keeping the legacy wire shape untouched
        context["stage"] = {
            "workflow_id": str(stage.get("workflow")),
            "stage": str(stage.get("name", "")),
            "index": int(stage.get("index", 0)),
        }
    return context


def envelope_trace(result: dict | None) -> dict:
    """The worker-echoed trace context from a settled envelope."""
    if isinstance(result, dict):
        cfg = result.get("pipeline_config")
        if isinstance(cfg, dict) and isinstance(cfg.get("trace"), dict):
            return cfg["trace"]
    return {}


def build_trace(record, now_wall: float) -> dict[str, Any]:
    """Assemble the ordered, gap-attributed trace payload for one job."""
    raw = [dict(e) for e in record.timeline if isinstance(e, dict)]
    events = sorted(raw, key=lambda e: float(e.get("wall", 0.0)))
    # append order IS the causal order; a sort that actually changed it
    # means the stored timeline was scrambled (replay bug, clock skew) —
    # rendered sorted for display, but flagged so trace_missing (and the
    # chaos no-reordering assertion) can see the repair instead of being
    # silently satisfied by it
    events_resorted = events != raw
    t0 = float(events[0]["wall"]) if events else now_wall
    for event in events:
        event["t_s"] = round(float(event.get("wall", t0)) - t0, 3)

    stages = worker_stages(record.result)
    worker_total = round(sum(s["seconds"] for s in stages), 3)
    echoed = envelope_trace(record.result)

    gaps: list[dict] = []
    for prev, nxt in zip(events, events[1:]):
        seconds = round(float(nxt["wall"]) - float(prev["wall"]), 3)
        gap = {
            "from": prev.get("event"),
            "to": nxt.get("event"),
            "seconds": seconds,
            "attribution": _GAP_LABELS.get(
                (prev.get("event"), nxt.get("event")), "other"),
        }
        if gap["attribution"] == "executing" and stages:
            # the worker's own spans carve the execution window up;
            # the remainder is wire + spool + upload overhead, reported
            # rather than absorbed
            gap["worker_stages"] = stages
            gap["worker_total_s"] = worker_total
            gap["unattributed_s"] = round(max(seconds - worker_total, 0.0), 3)
        gaps.append(gap)

    terminal = events[-1].get("event") if events else None
    open_ended = terminal not in ("settle", "park", "cancel", "expire")
    total_s = round(
        (now_wall if open_ended else float(events[-1]["wall"])) - t0, 3)

    payload: dict[str, Any] = {
        "id": record.job_id,
        "class": record.job_class,
        "status": record.state,
        "attempts": record.attempts,
        "placement": record.placement,
        "queue_wait_s": record.queue_wait_s,
        "events": events,
        "events_resorted": events_resorted,
        "gaps": gaps,
        "total_s": max(total_s, 0.0),
        "open": open_ended,
        "worker": {
            "stages": stages,
            "total_s": worker_total,
            "trace": echoed,
        },
    }
    return payload


def build_shed_trace(job_id: str, shed_events: list[dict]) -> dict[str, Any]:
    """Trace payload for an id that was shed but never admitted: the
    refusals ARE its timeline, and the spans between them are the
    submitter's backoff — reported with the same gap arithmetic an
    admitted record gets, not flattened to zero."""
    events = sorted((dict(e) for e in shed_events if isinstance(e, dict)),
                    key=lambda e: float(e.get("wall", 0.0)))
    t0 = float(events[0]["wall"]) if events else 0.0
    for event in events:
        event["t_s"] = round(float(event.get("wall", t0)) - t0, 3)
    gaps = [{
        "from": "shed", "to": "shed",
        "seconds": round(float(nxt["wall"]) - float(prev["wall"]), 3),
        "attribution": "resubmit_backoff",
    } for prev, nxt in zip(events, events[1:])]
    total_s = round(float(events[-1]["wall"]) - t0, 3) if events else 0.0
    return {
        "id": job_id, "status": "shed",
        "events": events, "gaps": gaps,
        "total_s": max(total_s, 0.0), "open": True,
    }


def trace_missing(payload: dict) -> list[str]:
    """What a COMPLETE (settled, gap-free) trace is missing, empty when
    nothing — the bench trace_e2e row and the durability tests assert
    on this instead of re-deriving completeness ad hoc.

    Complete means: admit, at least one dispatch with a placement
    outcome, and a settle are all present; events are monotonically
    ordered; the admit->dispatch queue wait is attributed; the worker's
    stage spans came back through the envelope."""
    missing: list[str] = []
    events = payload.get("events") or []
    kinds = [e.get("event") for e in events]
    if "admit" not in kinds:
        missing.append("no admit event")
    if "dispatch" not in kinds:
        missing.append("no dispatch event")
    elif not any(e.get("event") == "dispatch" and e.get("outcome")
                 for e in events):
        missing.append("dispatch event lacks a placement outcome")
    if "settle" not in kinds:
        missing.append("no settle event")
    if payload.get("events_resorted"):
        # build_trace sorts for display, so the served walls are always
        # monotone; the flag is the only witness that the STORED order
        # disagreed with the wall stamps
        missing.append("stored events were not monotonically ordered "
                       "(resorted by wall for display)")
    if payload.get("queue_wait_s") is None:
        missing.append("no queue wait recorded")
    gaps = payload.get("gaps") or []
    if not any(g.get("attribution") == "hive_queue" for g in gaps):
        missing.append("no attributed hive_queue gap")
    if not (payload.get("worker") or {}).get("stages"):
        missing.append("no worker stage spans in the envelope")
    return missing
