"""Declarative per-class latency SLOs with sliding-window burn rates.

The hive already histograms hive-side queue wait and dispatch-to-settle
per priority class, but a histogram is a lifetime statement — it cannot
answer "is the interactive class meeting its objective RIGHT NOW, and
how fast is it burning its error budget?". This engine does, the
standard SRE way:

- ``Settings.hive_slo`` declares objectives per class, e.g.::

      interactive:queue_wait_p95<2.0,e2e_p95<30;default:e2e_p95<120

  classes separated by ``;``, objectives by ``,``; each objective is
  ``<metric>_p<NN><threshold_seconds`` with metrics ``queue_wait``
  (submission -> first dispatch), ``dispatch_to_settle`` (last dispatch
  -> settled result), and ``e2e`` (submission -> settled result). An
  empty spec disables the engine (``GET /api/slo`` still answers, with
  ``enabled: false`` — the reply shape is conformance-pinned).

- the engine keeps raw timestamped observations over two sliding
  windows (``hive_slo_fast_window_s`` default 60 s,
  ``hive_slo_slow_window_s`` default 600 s), fed at the exact sites the
  existing ``swarm_hive_queue_wait_seconds`` /
  ``swarm_hive_dispatch_to_settle_seconds`` histograms observe (the
  queue's take/settle paths) — one measurement, two views. Replay and
  replication never feed it: an SLO is a statement about live traffic.

- per objective and window it reports **compliance** (fraction of
  observations within threshold) and **burn rate** — the error budget
  consumption multiplier, ``(1 - compliance) / (1 - quantile)``: burn
  1.0 exactly spends the budget (e.g. 5% of requests over threshold
  against a p95 objective), burn 2.0 spends it twice as fast. When the
  fast-window burn crosses ``FAST_BURN_DEGRADED`` the class lands in
  /healthz ``degraded_reasons`` — a page-worthy fast burn, per the
  classic multi-window alerting policy.

Exported as ``swarm_hive_slo_burn_rate{class,window}`` (worst objective
per class per window) and ``swarm_hive_slo_compliance{class}`` (worst
fast-window compliance), and served whole at ``GET /api/slo``.
"""

from __future__ import annotations

import logging
import re
from collections import deque

from .. import telemetry
from .clock import CLOCK, HiveClock

logger = logging.getLogger(__name__)

# metrics an objective may target; fed by queue.py observation hooks
METRICS = ("queue_wait", "dispatch_to_settle", "e2e")

# fast-window burn rate past which the class is a /healthz degraded
# reason: >2x budget burn sustained over the fast window is the classic
# "page now" half of a multi-window burn alert
FAST_BURN_DEGRADED = 2.0

_OBJECTIVE_RE = re.compile(
    r"^(?P<metric>[a-z0-9_]+)_p(?P<pct>\d{1,2})\s*<\s*(?P<threshold>[0-9.]+)$")

_BURN_RATE = telemetry.gauge(
    "swarm_hive_slo_burn_rate",
    "Error-budget burn-rate multiplier per priority class and window "
    "(worst objective; 1.0 = spending the budget exactly, >1 = "
    "over-budget), over the fast/slow sliding windows",
    ("class", "window"),
)
_COMPLIANCE = telemetry.gauge(
    "swarm_hive_slo_compliance",
    "Worst fast-window objective compliance per priority class "
    "(fraction of observations within threshold; 1.0 = fully compliant)",
    ("class",),
)


class Objective:
    __slots__ = ("metric", "quantile", "threshold_s")

    def __init__(self, metric: str, quantile: float, threshold_s: float):
        self.metric = metric
        self.quantile = quantile
        self.threshold_s = threshold_s

    @property
    def name(self) -> str:
        return f"{self.metric}_p{int(round(self.quantile * 100))}" \
               f"<{self.threshold_s:g}"


def parse_slo(spec: str | None) -> dict[str, list[Objective]]:
    """``hive_slo`` spec -> {class: [Objective]}. Unparseable entries
    are logged and dropped — a typo in one objective must not take the
    whole engine (or the hive) down."""
    objectives: dict[str, list[Objective]] = {}
    spec = (spec or "").strip()
    if not spec:
        return objectives
    for clause in spec.split(";"):
        clause = clause.strip()
        if not clause:
            continue
        cls, sep, body = clause.partition(":")
        cls = cls.strip().lower()
        if not sep or not cls:
            logger.warning("hive_slo clause %r has no class prefix; "
                           "ignored", clause)
            continue
        for part in body.split(","):
            part = part.strip()
            if not part:
                continue
            m = _OBJECTIVE_RE.match(part)
            if not m or m.group("metric") not in METRICS:
                logger.warning(
                    "unparseable hive_slo objective %r ignored "
                    "(want <metric>_p<NN><seconds with metric in %s)",
                    part, METRICS)
                continue
            pct = int(m.group("pct"))
            if not 0 < pct < 100:
                logger.warning("hive_slo quantile p%d out of (0,100); "
                               "%r ignored", pct, part)
                continue
            try:
                threshold = float(m.group("threshold"))
            except ValueError:
                # "1.2.3" matches the [0-9.]+ capture but is no number
                logger.warning(
                    "hive_slo threshold in %r is not a number; ignored",
                    part)
                continue
            objectives.setdefault(cls, []).append(
                Objective(m.group("metric"), pct / 100.0, threshold))
    return objectives


class SLOEngine:
    """Sliding-window compliance + burn-rate evaluation for the parsed
    objectives. Single-threaded like the rest of the hive (observe sites
    and report callers all live on the coordinator's event loop)."""

    # per (class, metric) observation cap — at any plausible settle rate
    # the slow window is long gone before this trips; it only bounds a
    # pathological burst's memory
    MAX_SAMPLES = 4096

    def __init__(self, objectives: dict[str, list[Objective]],
                 fast_window_s: float = 60.0, slow_window_s: float = 600.0,
                 clock: HiveClock | None = None):
        self.objectives = objectives
        self.fast_window_s = max(float(fast_window_s), 1.0)
        self.slow_window_s = max(float(slow_window_s), self.fast_window_s)
        self.clock = clock or CLOCK
        # (class, metric) -> deque[(mono, seconds)], newest right
        self._samples: dict[tuple[str, str], deque] = {}
        self._needed: dict[str, set[str]] = {
            cls: {o.metric for o in objs}
            for cls, objs in objectives.items()
        }

    @property
    def enabled(self) -> bool:
        return bool(self.objectives)

    def observe(self, cls: str, metric: str, seconds: float) -> None:
        """One live measurement from the queue's take/settle path; a
        class or metric no objective watches is dropped at the door."""
        if metric not in self._needed.get(cls, ()):
            return
        q = self._samples.setdefault((cls, metric), deque())
        q.append((self.clock.mono(), float(seconds)))
        if len(q) > self.MAX_SAMPLES:
            q.popleft()

    def _window(self, cls: str, metric: str, window_s: float) -> list[float]:
        q = self._samples.get((cls, metric))
        if not q:
            return []
        cutoff = self.clock.mono() - window_s
        # expire from the left while we're here: the deque stays bounded
        # by the slow window without a separate sweep
        slow_cutoff = self.clock.mono() - self.slow_window_s
        while q and q[0][0] < slow_cutoff:
            q.popleft()
        return [v for t, v in q if t >= cutoff]

    @staticmethod
    def _evaluate(objective: Objective, samples: list[float]) -> dict:
        n = len(samples)
        if n == 0:
            # no traffic = no budget burned; compliance is vacuous
            return {"samples": 0, "compliance": 1.0, "burn_rate": 0.0,
                    "met": True}
        within = sum(1 for v in samples if v <= objective.threshold_s)
        compliance = within / n
        budget = 1.0 - objective.quantile
        burn = (1.0 - compliance) / budget if budget > 0 else 0.0
        return {
            "samples": n,
            "compliance": round(compliance, 4),
            "burn_rate": round(burn, 3),
            "met": compliance >= objective.quantile,
        }

    def report(self) -> dict:
        """The GET /api/slo payload (shape conformance-pinned): every
        declared class with per-objective windowed compliance/burn, plus
        the class-level worst burns the gauges export."""
        classes: dict[str, dict] = {}
        for cls, objs in self.objectives.items():
            rows = []
            fast_burn = slow_burn = 0.0
            worst_compliance = 1.0
            for objective in objs:
                windows = {}
                for name, span in (("fast", self.fast_window_s),
                                   ("slow", self.slow_window_s)):
                    windows[name] = self._evaluate(
                        objective, self._window(cls, objective.metric, span))
                rows.append({
                    "objective": objective.name,
                    "metric": objective.metric,
                    "quantile": objective.quantile,
                    "threshold_s": objective.threshold_s,
                    "windows": windows,
                })
                fast_burn = max(fast_burn, windows["fast"]["burn_rate"])
                slow_burn = max(slow_burn, windows["slow"]["burn_rate"])
                worst_compliance = min(
                    worst_compliance, windows["fast"]["compliance"])
            classes[cls] = {
                "objectives": rows,
                "fast_burn": fast_burn,
                "slow_burn": slow_burn,
                "compliance": worst_compliance,
                "breaching": fast_burn > FAST_BURN_DEGRADED,
            }
        return {
            "enabled": self.enabled,
            "fast_window_s": self.fast_window_s,
            "slow_window_s": self.slow_window_s,
            "fast_burn_degraded": FAST_BURN_DEGRADED,
            "classes": classes,
        }

    def refresh_metrics(self, report: dict | None = None) -> dict:
        """Export the per-class gauges from a (fresh) report; returns the
        report so callers evaluating for /healthz don't compute twice."""
        report = report or self.report()
        for cls, view in report["classes"].items():
            _BURN_RATE.set(view["fast_burn"],
                           **{"class": cls, "window": "fast"})
            _BURN_RATE.set(view["slow_burn"],
                           **{"class": cls, "window": "slow"})
            _COMPLIANCE.set(view["compliance"], **{"class": cls})
        return report

    def degraded_reasons(self, report: dict | None = None) -> list[str]:
        """/healthz reasons: one per class whose fast-window burn rate
        crossed the page threshold."""
        report = report or self.report()
        reasons = []
        for cls, view in report["classes"].items():
            if view["breaching"]:
                reasons.append(
                    f"SLO fast burn for {cls}: {view['fast_burn']:.1f}x "
                    f"budget over {self.fast_window_s:g}s "
                    f"(compliance {view['compliance']:.2f})")
        return reasons
