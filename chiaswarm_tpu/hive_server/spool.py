"""Content-addressed artifact spool for accepted results.

Result envelopes arrive with base64 blobs inline (the wire format the
reference hive defined). Keeping those in memory per job would make the
hive's footprint proportional to its history, and identical artifacts
(error images, redelivered duplicates) would be stored twice. The spool
writes each decoded blob once under its own sha256
(``<dir>/<aa>/<digest>``, atomic tmp+rename like outbox.py) and hands
back the envelope with blobs replaced by references::

    {"sha256": ..., "bytes": N, "href": "/api/artifacts/<digest>"}

``GET /api/artifacts/{digest}`` serves the bytes back. Thumbnails stay
inline — they are a few KB and exist to be embedded.
"""

from __future__ import annotations

import base64
import binascii
import hashlib
import logging
import os
import threading
import uuid
from pathlib import Path

from .. import telemetry
from .clock import CLOCK, HiveClock

logger = logging.getLogger(__name__)

_SPOOLED = telemetry.counter(
    "swarm_hive_spool_writes_total",
    "Artifact blobs written to the content-addressed spool, by outcome "
    "(stored | dedup | error)",
    ("outcome",),
)
_SPOOL_BYTES = telemetry.gauge(
    "swarm_hive_spool_bytes", "Total bytes resident in the artifact spool")
_EVICTED = telemetry.counter(
    "swarm_hive_spool_evicted_total",
    "Artifact blobs deleted by the retention sweep (age or size bound; "
    "blobs referenced by a live job record are never evicted)",
)


class ArtifactSpool:
    def __init__(self, root: Path, clock: HiveClock | None = None):
        # retention compares blob mtimes (wall-clock by nature) against
        # "now"; the clock is injectable so sweep tests need not touch
        # real file ages
        self.clock = clock or CLOCK
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        # a crash between tmp write and rename leaves dot-prefixed .tmp
        # orphans (invisible to the glob below, but they leak disk)
        for orphan in self.root.glob("*/.*.tmp"):
            try:
                orphan.unlink()
            except OSError:
                pass
        self._lock = threading.Lock()
        self._bytes = sum(
            f.stat().st_size for f in self.root.glob("*/*") if f.is_file())
        _SPOOL_BYTES.set(self._bytes)
        # fleet memory census (ISSUE 17): the spool's running byte count
        # is already maintained by put/sweep — serve it, don't re-stat
        from .. import memory_census

        memory_census.register(
            "artifact_spool", lambda: {"bytes": int(self._bytes)})

    def _path(self, digest: str) -> Path:
        return self.root / digest[:2] / digest

    def put(self, payload: bytes) -> str:
        """Store one blob; returns its sha256. Idempotent — an existing
        entry is trusted by its name (content addressing). Serialized:
        store_result runs in to_thread workers, and two concurrent puts
        of the same payload must not double-count the byte gauge."""
        digest = hashlib.sha256(payload).hexdigest()
        path = self._path(digest)
        with self._lock:
            if path.exists():
                _SPOOLED.inc(outcome="dedup")
                return digest
            path.parent.mkdir(parents=True, exist_ok=True)
            tmp = path.parent / f".{digest}.{uuid.uuid4().hex}.tmp"
            tmp.write_bytes(payload)
            os.replace(tmp, path)
            self._bytes += len(payload)
            _SPOOL_BYTES.set(self._bytes)
            _SPOOLED.inc(outcome="stored")
        return digest

    def path_for(self, digest: str) -> Path | None:
        """Path to a stored blob, or None if absent/invalid. The digest
        is validated as hex before touching the filesystem — it arrives
        from a URL. HTTP handlers serve this path as a streamed file
        response instead of buffering the blob in memory."""
        if not (len(digest) == 64 and all(
                c in "0123456789abcdef" for c in digest)):
            return None
        path = self._path(digest)
        return path if path.is_file() else None

    def get(self, digest: str) -> bytes | None:
        """Blob bytes by digest, or None."""
        path = self.path_for(digest)
        if path is None:
            return None
        try:
            return path.read_bytes()
        except OSError:
            return None

    def drop(self, digest: str) -> bool:
        """Delete one blob by digest (terminal-state checkpoint/preview
        sweeping, ISSUE 18). Content addressing makes this safe only
        when the CALLER knows nothing else references the digest — the
        hive tracks checkpoint/preview digests per record and drops them
        exactly once, on the record's terminal transition. Returns True
        if a blob was deleted."""
        path = self.path_for(digest)
        if path is None:
            return False
        with self._lock:
            try:
                size = path.stat().st_size
                path.unlink()
            except OSError:
                return False
            self._bytes = max(self._bytes - size, 0)
            _SPOOL_BYTES.set(self._bytes)
            _EVICTED.inc()
        return True

    def sweep(self, max_bytes: int = 0, max_age_s: float = 0.0,
              protected: frozenset[str] | set[str] = frozenset()) -> int:
        """Retention sweep: `retire()` prunes in-memory records but the
        content-addressed blobs would otherwise live forever. Deletes
        blobs older than `max_age_s`, then the oldest remaining blobs
        while the spool exceeds `max_bytes` (either bound 0 = off).
        Digests in `protected` — everything a live (non-retired) record
        still references — are never deleted, whatever their age: a
        GET /api/jobs/{id} href must not dangle while the record can
        still answer. Returns the number of blobs evicted."""
        if max_bytes <= 0 and max_age_s <= 0:
            return 0
        with self._lock:
            entries = []
            total = 0
            for path in self.root.glob("*/*"):
                try:
                    st = path.stat()
                except OSError:
                    continue
                if not path.is_file():
                    continue
                total += st.st_size
                entries.append((st.st_mtime, st.st_size, path))
            entries.sort()  # oldest first
            evicted = 0
            now = self.clock.wall()
            survivors = []
            for mtime, size, path in entries:
                if path.name in protected:
                    continue
                if max_age_s > 0 and now - mtime > max_age_s:
                    try:
                        path.unlink()
                    except OSError:
                        continue
                    total -= size
                    evicted += 1
                else:
                    survivors.append((size, path))
            if max_bytes > 0:
                for size, path in survivors:
                    if total <= max_bytes:
                        break
                    try:
                        path.unlink()
                    except OSError:
                        continue
                    total -= size
                    evicted += 1
            self._bytes = max(total, 0)
            _SPOOL_BYTES.set(self._bytes)
            if evicted:
                _EVICTED.inc(evicted)
                logger.info("spool sweep evicted %d blob(s); %d bytes remain",
                            evicted, self._bytes)
        return evicted

    def store_result(self, result: dict) -> dict:
        """Spool every artifact blob in an envelope; returns a copy with
        blobs replaced by spool references. A blob that fails to decode
        is kept inline rather than lost — the spool is an optimization,
        never a gate on accepting a worker's result."""
        stored = dict(result)
        artifacts = result.get("artifacts")
        if not isinstance(artifacts, dict):
            return stored
        out = {}
        for name, art in artifacts.items():
            if not (isinstance(art, dict) and isinstance(
                    art.get("blob"), str)):
                out[name] = art
                continue
            try:
                payload = base64.b64decode(art["blob"])
            except (binascii.Error, ValueError):
                _SPOOLED.inc(outcome="error")
                logger.warning("artifact %r blob is not base64; kept inline",
                               name)
                out[name] = art
                continue
            digest = self.put(payload)
            ref = {k: v for k, v in art.items() if k != "blob"}
            ref["sha256"] = digest
            ref["bytes"] = len(payload)
            ref["href"] = f"/api/artifacts/{digest}"
            out[name] = ref
        stored["artifacts"] = out
        return stored
