"""`python -m chiaswarm_tpu.hive_server` — same entry as tools/hive_serve.py."""

import asyncio

from .app import serve

if __name__ == "__main__":
    try:
        asyncio.run(serve())
    except KeyboardInterrupt:
        print("hive stopped")
