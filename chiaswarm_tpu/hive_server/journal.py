"""Write-ahead journal: the hive's queue + lease state survives SIGKILL.

PR 3 made the *worker* half of the lifecycle at-least-once (durable
outbox, redelivery across restarts); until now the coordinator kept its
queue, lease table, and job records purely in memory, so a hive crash
silently lost every queued and leased job even though the artifact spool
under $SDAAS_ROOT already survived. This module closes that gap with the
same write-ahead discipline the outbox uses, at coordinator granularity:

- every state transition — admit, lease, settle, requeue, park, cancel,
  expire, retire —
  appends one JSON line to ``$SDAAS_ROOT/hive_wal/wal.jsonl`` *after* the
  in-memory mutation and *before* the HTTP response leaves (so a client
  never holds an ACK for state the journal missed);
- a restarted hive replays the stream through :func:`apply_events` and
  lands on exactly the pre-crash queue order, record table, and lease
  set;
- every ``compact_every`` appends (and once after each recovery) the
  stream is rewritten as the *minimal* event sequence reconstructing the
  current state (:func:`snapshot_events`) — an atomic tmp+rename, so the
  WAL's size is bounded by live state, not by history.

Replay is semantically correct, not just mechanical:

- monotonic instants (``submitted_at``, lease deadlines) are meaningless
  in a new process, so events persist wall-clock twins and replay
  re-anchors them through :class:`~.clock.HiveClock` — intervals like
  queue wait and the unplaceable-parking window span the restart;
- a recovered lease gets a **fresh full deadline**: the lessee may still
  be running the job (its result lands on the idempotent-ACK path as a
  duplicate) or may have died with the hive (the reaper redelivers one
  deadline from now — never "immediately" off a stale deadline);
- a torn tail — the half-written last line a crash mid-append leaves —
  is skipped and counted, never fatal; the transition it described is
  the one the crash interrupted, and the lease/redelivery machinery
  already covers an event that never happened.

Durability model: every append is flushed to the OS, so the journal
survives process death (SIGKILL included). ``fsync=True`` additionally
survives power loss at a per-transition fsync cost; compaction snapshots
are always fsynced before the rename either way.

Replication (hive_server/replication.py) rides this exact stream: every
event carries a monotonically increasing replication sequence (``rs``,
never reused, stamped on append and re-stamped fresh on compaction), and
:meth:`HiveJournal.stream_since` answers a standby's
``GET /api/replication/stream?since=<rs>`` from the journal's in-memory
mirror of the current file — incrementally while the requested position
is still continuous with the stream, or with ``reset=True`` (the full
compacted snapshot, retired history excluded) once compaction has
retired the events between. The WAL's compact-to-events format is the
replication unit, as the ROADMAP predicted.
"""

from __future__ import annotations

import json
import logging
import os
from pathlib import Path

from .. import faults, telemetry
from .leases import LeaseTable
from .queue import PriorityJobQueue

logger = logging.getLogger(__name__)

WAL_NAME = "wal.jsonl"

_APPENDS = telemetry.counter(
    "swarm_hive_wal_appends_total",
    "State transitions appended to the hive write-ahead journal, by event",
    ("event",),
)
_COMPACTIONS = telemetry.counter(
    "swarm_hive_wal_compactions_total",
    "Hive WAL compactions (stream rewritten as a minimal state snapshot)",
)
_REPLAYED = telemetry.counter(
    "swarm_hive_wal_replayed_total",
    "Journal events applied during hive recovery",
)
_TORN = telemetry.counter(
    "swarm_hive_wal_torn_lines_total",
    "Unparseable journal lines skipped during recovery (a torn tail is "
    "the expected crash artifact; mid-stream corruption is logged loudly)",
)
_RECOVERED_JOBS = telemetry.gauge(
    "swarm_hive_wal_recovered_jobs",
    "Job records reconstructed by the last WAL replay, by state",
    ("state",),
)


class HiveJournal:
    """Append-only JSONL stream + periodic compaction for one hive.

    Single-threaded by design, like everything else hive-side: appends
    happen on the event loop between an in-memory mutation and the HTTP
    response. ``snapshot_fn`` (set by the owner once recovery is done)
    supplies the minimal event sequence for compaction."""

    def __init__(self, root: Path, fsync: bool = False,
                 compact_every: int = 512):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.path = self.root / WAL_NAME
        self.fsync = bool(fsync)
        self.compact_every = int(compact_every)
        self.snapshot_fn = None
        self.appends_since_compact = 0
        self.replayed_events = 0
        self.torn_lines = 0
        # replication bookkeeping: the in-memory mirror of the current
        # file (what stream_since serves), the next sequence to stamp,
        # and the rs from which the current file reconstructs full state
        # from empty (a standby behind this point must full-resync)
        self.events: list[dict] = []
        self.next_rs = 1
        self.stream_start_rs = 1
        self._fh = None
        # a crash mid-compaction leaves a tmp beside the live stream;
        # the rename never happened, so the live stream is authoritative
        for orphan in self.root.glob(f".{WAL_NAME}.*.tmp"):
            try:
                orphan.unlink()
            except OSError:
                pass
        # fleet memory census (ISSUE 17): WAL file bytes + the in-memory
        # event mirror's length; last-constructed journal wins
        from .. import memory_census

        memory_census.register("wal", self._resident_bytes)

    def _resident_bytes(self) -> dict:
        try:
            nbytes = self.path.stat().st_size
        except OSError:
            nbytes = 0
        return {"bytes": int(nbytes), "entries": len(self.events)}

    # --- recovery ---

    def recover(self) -> list[dict]:
        """Parse the stream, tolerant of a torn tail: the last line a
        crash left half-written is skipped and counted. Corruption
        *mid*-stream (not the tail) is also skipped — losing one
        transition degrades to a redelivery, which beats refusing to
        start — but logged loudly because it means more than a crash
        happened to this file."""
        events: list[dict] = []
        try:
            raw = self.path.read_bytes()
        except FileNotFoundError:
            return events
        lines = raw.split(b"\n")
        last_index = max(
            (i for i, ln in enumerate(lines) if ln.strip()), default=-1)
        for i, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                event = json.loads(line)
                if not isinstance(event, dict) or "ev" not in event:
                    raise ValueError("journal line is not an event object")
            except (ValueError, UnicodeDecodeError) as e:
                self.torn_lines += 1
                _TORN.inc()
                if i == last_index:
                    logger.warning(
                        "hive WAL torn tail skipped (%d bytes): the crash "
                        "interrupted this append", len(line))
                else:
                    logger.error(
                        "hive WAL line %d is corrupt mid-stream (%s); "
                        "skipping it — the transition it described is "
                        "lost and will resolve as a redelivery", i, e)
                continue
            events.append(event)
        # re-establish the replication sequence: pre-replication WALs
        # carry no rs at all, and a torn tail may have clipped the
        # highest one — stamp forward monotonically either way
        last_rs = 0
        for event in events:
            rs = event.get("rs")
            rs = int(rs) if isinstance(rs, int) else last_rs + 1
            rs = max(rs, last_rs + 1)
            event["rs"] = rs
            last_rs = rs
        self.next_rs = last_rs + 1
        self.stream_start_rs = events[0]["rs"] if events else self.next_rs
        self.events = events
        self.replayed_events = len(events)
        return events

    # --- append path ---

    def _handle(self):
        if self._fh is None:
            self._fh = open(self.path, "ab")
        return self._fh

    def append(self, event: dict) -> None:
        """Persist one transition. ``kill_before_journal_sync`` fires
        here (the hive 'crashed' between the in-memory mutation and the
        journal write — recovery must tolerate the missing event); the
        exception propagates so the in-flight HTTP response dies exactly
        as it would mid-crash."""
        faults.fire("kill_before_journal_sync")
        event["rs"] = self.next_rs
        fh = self._handle()
        fh.write(json.dumps(event, separators=(",", ":")).encode() + b"\n")
        fh.flush()
        if self.fsync:
            os.fsync(fh.fileno())
        self.next_rs += 1
        self.events.append(event)
        _APPENDS.inc(event=str(event.get("ev", "?")))
        self.appends_since_compact += 1
        if (self.compact_every > 0 and self.snapshot_fn is not None
                and self.appends_since_compact >= self.compact_every):
            self.compact(self.snapshot_fn())

    def compact(self, events: list[dict]) -> None:
        """Atomically replace the stream with the given minimal event
        sequence (tmp + fsync + rename, like the outbox and the spool).
        Snapshot events get FRESH rs stamps continuing the counter —
        sequences are never reused, so a standby holding a pre-compaction
        position either continues exactly at the tip or detects the gap
        and full-resyncs from this snapshot (stream_since)."""
        events = [dict(event) for event in events]
        for event in events:
            event["rs"] = self.next_rs
            self.next_rs += 1
        tmp = self.root / f".{WAL_NAME}.{os.getpid()}.tmp"
        with open(tmp, "wb") as fh:
            for event in events:
                fh.write(
                    json.dumps(event, separators=(",", ":")).encode() + b"\n")
            fh.flush()
            os.fsync(fh.fileno())
        self.close()
        os.replace(tmp, self.path)
        self.events = events
        self.stream_start_rs = events[0]["rs"] if events else self.next_rs
        self.appends_since_compact = 0
        _COMPACTIONS.inc()

    # --- replication stream (GET /api/replication/stream) ---

    @property
    def last_rs(self) -> int:
        """The highest replication sequence stamped so far (0 = none)."""
        return self.next_rs - 1

    def stream_since(self, since: int) -> tuple[list[dict], bool]:
        """Events a standby at position `since` still needs.

        Returns ``(events, reset)``: while `since` is continuous with the
        current file (``since + 1 >= stream_start_rs``) the reply is the
        incremental tail — possibly the whole compacted snapshot, which
        applies idempotently over a standby already at the tip. Once
        compaction has retired events past the standby's position the
        reply is the FULL current stream with ``reset=True``: the standby
        discards its state and rebuilds from the snapshot, never
        replaying retired history. A position AHEAD of this journal's
        counter is also a reset — the primary lost WAL tail (power loss
        without fsync) or was stood up over a wiped directory, and an
        empty incremental reply would leave the standby silently
        filtering every future event as already-seen."""
        since = int(since)
        if since > self.last_rs:
            return list(self.events), True
        if since + 1 >= self.stream_start_rs:
            return [e for e in self.events if e["rs"] > since], False
        return list(self.events), True

    def close(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None


# --- event constructors (one vocabulary for append sites and replay) ---


def _timeline_of(record) -> list[dict]:
    """The record's trace timeline, copied for the journal. EVERY event
    carries the full timeline-so-far (a dozen small dicts at most), so
    replay — recovery, compaction snapshots, and the standby's
    replication stream alike — restores it by plain replacement: no
    merge logic, no duplicate or reordered entries possible."""
    return [dict(e) for e in getattr(record, "timeline", ())]


def ev_admit(record) -> dict:
    event = {"ev": "admit", "job": record.job, "class": record.job_class,
             "seq": record.seq, "wall": record.submitted_wall,
             "timeline": _timeline_of(record)}
    if record.attempts:
        # compaction folds a queued record's dispatch history (it was
        # leased and requeued before the snapshot) into its admit, so
        # replay reproduces queue ORDER by plain appends — replaying
        # lease+requeue pairs would front-insert and reverse the queue
        event.update(attempts=record.attempts, worker=record.worker,
                     queue_wait_s=record.queue_wait_s,
                     placement=record.placement)
    return event


def ev_lease(record) -> dict:
    return {"ev": "lease", "id": record.job_id, "worker": record.worker,
            "attempts": record.attempts, "outcome": record.placement,
            "queue_wait_s": record.queue_wait_s,
            "timeline": _timeline_of(record)}


def ev_settle(record) -> dict:
    return {"ev": "settle", "id": record.job_id,
            "completed_by": record.completed_by,
            "attempts": record.attempts, "result": record.result,
            "timeline": _timeline_of(record)}


def ev_requeue(record) -> dict:  # swarmlint: disable=SW006 -- compaction
    # deliberately never emits requeue: a queued record's dispatch
    # history folds into its admit event (see ev_admit) so replay
    # reproduces queue ORDER by plain appends — replaying lease+requeue
    # pairs would front-insert and reverse the queue
    return {"ev": "requeue", "id": record.job_id, "attempts": record.attempts,
            "timeline": _timeline_of(record)}


def ev_park(record) -> dict:
    return {"ev": "park", "id": record.job_id, "error": record.error,
            "attempts": record.attempts, "timeline": _timeline_of(record)}


def ev_cancel(record) -> dict:
    """A cancel is a first-class lifecycle transition, journaled exactly
    like lease state: replayed on SIGKILL recovery, folded into
    compaction snapshots, and shipped to the standby — a promoted hive
    keeps refusing the cancelled job's dispatch and answers its late
    result with the `cancelled` disposition."""
    return {"ev": "cancel", "id": record.job_id,
            "stage": record.cancel_stage, "worker": record.worker,
            "error": record.error, "attempts": record.attempts,
            "timeline": _timeline_of(record)}


def ev_expire(record) -> dict:
    return {"ev": "expire", "id": record.job_id, "error": record.error,
            "timeline": _timeline_of(record)}


def ev_retire(job_id: str) -> dict:  # swarmlint: disable=SW006 -- a
    # compaction snapshot contains only LIVE records; retirement is
    # expressed by omission, so snapshot_events never emits retire
    return {"ev": "retire", "id": job_id}


def ev_checkpoint(record) -> dict:
    """Mid-pass durability state (ISSUE 18): the record's latest
    checkpoint meta AND its preview list in one event — both are tiny
    (blob bytes live in the spool, addressed by digest), and a single
    event per boundary keeps the WAL cost of a checkpoint at one line.
    Replay restores by replacement, like the timeline."""
    return {"ev": "checkpoint", "id": record.job_id,
            "checkpoint": (dict(record.checkpoint)
                           if record.checkpoint else None),
            "previews": [dict(p) for p in record.previews],
            "timeline": _timeline_of(record)}


def ev_epoch(epoch: int) -> dict:
    """The fencing epoch (bumped on every standby promotion). Persisted
    so a promoted hive that restarts keeps refusing a deposed
    predecessor's stale-epoch traffic."""
    return {"ev": "epoch", "epoch": int(epoch)}


def ev_dag(workflow) -> dict:
    """One workflow graph's FULL state (ISSUE 20): stage list, edges,
    and per-stage states — a few hundred bytes (stage payloads are job
    dicts the admit events carry anyway; artifact blobs live in the
    spool). Appended on submission and on every advancement; replay
    restores by replacement like ev_checkpoint, so the LAST event per
    workflow id wins and graphs survive SIGKILL recovery, compaction,
    and standby promotion."""
    return {"ev": "dag", "id": workflow.workflow_id,
            "workflow": workflow.to_state()}


def snapshot_events(queue: PriorityJobQueue, leases: LeaseTable,
                    epoch: int = 0, dag=None) -> list[dict]:
    """The minimal event sequence reconstructing the current state: the
    fencing epoch (when ever bumped), one admit per live record, plus
    the single event carrying its terminal or leased condition. Queued
    records are emitted LAST and in dispatch order, so replay's enqueue
    order reproduces the queue exactly (requeue-front history included —
    the order IS the state)."""
    events: list[dict] = []
    if epoch:
        events.append(ev_epoch(epoch))
    if dag is not None:
        # workflow graphs first: their restore needs no records (stage
        # states re-derive from the record events that follow, via the
        # server's post-replay reconcile)
        for workflow in dag.workflows.values():
            events.append(ev_dag(workflow))
    queued_ids = set()
    for record in queue.iter_queued():
        queued_ids.add(record.job_id)
    for record in queue.records.values():
        if record.job_id in queued_ids:
            continue
        events.append(ev_admit(record))
        if record.state in ("leased", "settling"):
            events.append(ev_lease(record))
        elif record.state == "done":
            events.append(ev_settle(record))
        elif record.state == "failed":
            events.append(ev_park(record))
        elif record.state == "cancelled":
            events.append(ev_cancel(record))
        elif record.state == "expired":
            events.append(ev_expire(record))
        if record.state in ("leased", "settling") and (
                record.checkpoint or record.previews):
            events.append(ev_checkpoint(record))
    for record in queue.iter_queued():
        events.append(ev_admit(record))
        if record.checkpoint or record.previews:
            # a requeued job awaiting redelivery still holds its
            # mid-pass state — exactly the record a resume offer needs
            events.append(ev_checkpoint(record))
    return events


def apply_events(events: list[dict], queue: PriorityJobQueue,
                 leases: LeaseTable, dag=None) -> dict:
    """Replay a recovered stream into fresh queue/lease tables. Events
    referencing unknown ids (their admit was the torn tail, or the
    record was retired in a compacted-away past) are skipped and
    counted, never fatal. Returns a summary for the recovery log line."""
    skipped = 0
    epoch = 0

    def restore_timeline(record, event) -> None:
        """Adopt the journaled timeline verbatim (replacement, not merge
        — see _timeline_of). A legacy pre-trace event without one leaves
        whatever the replay mutations stamped; the trace degrades to a
        partial timeline rather than failing."""
        timeline = event.get("timeline")
        if isinstance(timeline, list):
            record.timeline = [dict(e) for e in timeline
                               if isinstance(e, dict)]

    for event in events:
        ev = event.get("ev")
        if ev == "epoch":
            try:
                epoch = max(epoch, int(event.get("epoch", 0)))
            except (TypeError, ValueError):
                skipped += 1
                continue
            _REPLAYED.inc()
            continue
        if ev == "admit":
            job = event.get("job")
            if not isinstance(job, dict) or not job.get("id"):
                skipped += 1
                continue
            if str(job["id"]) in queue.records:
                skipped += 1  # duplicate admit (resubmission journaled)
                continue
            restored = queue.restore(job, str(event.get("class", "")),
                                     int(event.get("seq", 0)),
                                     float(event.get("wall", 0.0)))
            if event.get("attempts"):
                # dispatch history folded in by compaction; still queued
                restored.attempts = int(event["attempts"])
                restored.worker = event.get("worker")
                restored.queue_wait_s = event.get("queue_wait_s")
                restored.placement = event.get("placement")
            restore_timeline(restored, event)
            if not restored.timeline:
                # legacy pre-trace WAL: synthesize the admit instant the
                # event already carries so the trace is never empty
                restored.timeline = [{
                    "event": "admit", "wall": restored.submitted_wall,
                    "class": restored.job_class}]
            _REPLAYED.inc()
            continue
        if ev == "dag":
            # workflow graph state (ISSUE 20): no job record to look up —
            # restore-by-replacement into the dag table (skipped, and
            # counted, when this replayer has none: a legacy caller)
            state = event.get("workflow")
            if dag is None or not isinstance(state, dict):
                skipped += 1
                continue
            dag.restore(state)
            _REPLAYED.inc()
            continue
        record = queue.records.get(str(event.get("id", "")))
        if record is None:
            skipped += 1
            continue
        if ev == "lease":
            if record.state != "queued":
                skipped += 1
                continue
            queue.restore_leased(
                record, str(event.get("worker") or "unknown"),
                int(event.get("attempts", 1)), event.get("outcome"),
                event.get("queue_wait_s"))
            leases.restore(record, record.worker)
            restore_timeline(record, event)
        elif ev == "settle":
            leases.settle(record.job_id)
            queue.discard_queued(record)
            record.state = "done"
            record.result = event.get("result")
            record.error = None
            record.completed_by = event.get("completed_by")
            record.attempts = int(event.get("attempts", record.attempts))
            record.done_at = queue.clock.mono()
            restore_timeline(record, event)
            queue.retire(record)
        elif ev == "requeue":
            leases.settle(record.job_id)
            if record.state == "leased":
                record.attempts = int(event.get("attempts", record.attempts))
                queue.requeue_front(record)
            restore_timeline(record, event)
        elif ev == "park":
            leases.settle(record.job_id)
            queue.discard_queued(record)
            record.state = "failed"
            record.error = event.get("error")
            record.attempts = int(event.get("attempts", record.attempts))
            restore_timeline(record, event)
            queue.retire(record)
        elif ev == "cancel":
            # restore directly — never through mark_cancelled, which
            # would re-count the cancel and re-stamp the timeline the
            # event already carries verbatim
            leases.settle(record.job_id)
            queue.discard_queued(record)
            record.state = "cancelled"
            record.cancel_stage = event.get("stage")
            record.error = event.get("error")
            record.attempts = int(event.get("attempts", record.attempts))
            if event.get("worker"):
                record.worker = event.get("worker")
            restore_timeline(record, event)
            queue.retire(record)
        elif ev == "expire":
            queue.discard_queued(record)
            record.state = "expired"
            record.error = event.get("error")
            restore_timeline(record, event)
            queue.retire(record)
        elif ev == "checkpoint":
            # restore by replacement, like the timeline — the event is
            # the record's full mid-pass state at append time
            ck = event.get("checkpoint")
            record.checkpoint = dict(ck) if isinstance(ck, dict) else None
            record.previews = [dict(p) for p in event.get("previews", ())
                               if isinstance(p, dict)]
            restore_timeline(record, event)
        elif ev == "retire":
            queue.forget(record.job_id)
        else:
            skipped += 1
            continue
        _REPLAYED.inc()

    states: dict[str, int] = {}
    for record in queue.records.values():
        states[record.state] = states.get(record.state, 0) + 1
    for state in ("queued", "leased", "done", "failed", "cancelled",
                  "expired"):
        _RECOVERED_JOBS.set(states.get(state, 0), state=state)
    return {"jobs": len(queue.records), "states": states,
            "leases": len(leases), "skipped": skipped, "epoch": epoch}
