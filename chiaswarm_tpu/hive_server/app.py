"""The hive HTTP server: the wire protocol of hive.py, served.

Protocol parity with the client in `chiaswarm_tpu/hive.py` (itself at
parity with reference swarm/hive.py:9-88):

  GET  /api/work?worker_version&worker_name&<capabilities>
       -> 200 {"jobs": [...]} | 400 {"message": ...} (refusal)
  POST /api/results  <- result envelope -> 200 ack JSON (idempotent)
  GET  /api/models   -> {"models": [...], "language_models": [...]}

plus the coordinator's own surface, which the reference hive kept
closed-source:

  POST /api/jobs            submit a job (admission control; 429 on a
                            full queue), returns {"id", "class"}
  POST /api/jobs/{id}/cancel  revoke a queued or leased job (WAL-durable;
                            leased cancels ride the next /work reply's
                            `cancels` piggyback to the lessee)
  GET  /api/jobs/{id}       lifecycle snapshot + spooled result
  GET  /api/usage           per-tenant usage ledger (accounting.py)
  GET  /api/tenants/{t}/usage  one tenant's bucket
  GET  /api/slo             per-class SLO compliance + burn rates (slo.py)
  GET  /api/artifacts/{d}   content-addressed artifact bytes
  GET  /metrics, /healthz   same telemetry registry the worker uses

Auth is the same bearer token workers are provisioned with
(`Settings.sdaas_token`); an empty token disables the check (dev mode).
`GET /api/models` alone is unauthenticated — the reference hive serves
its catalog publicly and the worker's `initialize --download` probe
relies on that (tests/fake_hive.py pins the same exception).
`refuse_with` mirrors tests/fake_hive.py: set it and /work answers 400
with the message — the hive-side drain switch (workers back off and
retry, nothing errors).
"""

from __future__ import annotations

import asyncio
import json
import logging

from aiohttp import web

from .. import faults, telemetry
from ..settings import Settings, get_settings_dir, load_settings, resolve_path
from . import accounting
from .dag import DagTable, WorkflowError
from .dispatch import Dispatcher, WorkerDirectory
from .fleet import FleetStats
from .slo import SLOEngine, parse_slo
from .journal import (
    HiveJournal,
    apply_events,
    ev_admit,
    ev_cancel,
    ev_checkpoint,
    ev_dag,
    ev_expire,
    ev_lease,
    ev_park,
    ev_requeue,
    ev_retire,
    ev_settle,
    snapshot_events,
)
from .leases import LeaseTable
from .queue import (PriorityJobQueue, QueueFull, job_class,
                    parse_shed_watermarks)
from .spool import ArtifactSpool
from .trace import (
    build_shed_trace,
    build_trace,
    envelope_trace,
    wire_trace_context,
)

logger = logging.getLogger(__name__)

_RESULTS = telemetry.counter(
    "swarm_hive_results_total",
    "Result envelopes POSTed to the hive, by disposition "
    "(ok | duplicate | late | unknown | cancelled | expired)",
    ("status",),
)
_CANCEL_REVOCATIONS = telemetry.gauge(
    "swarm_hive_cancel_revocations_pending",
    "Leased-job cancels awaiting delivery to their lessee via the next "
    "/work reply's `cancels` piggyback (lease already revoked hive-side)")
_POLLS = telemetry.counter(
    "swarm_hive_polls_total",
    "GET /work polls answered, by reply (jobs | empty | refused)",
    ("reply",),
)
# registered by leases.py (imported above); same-name counter() returns it
_JOBS_FAILED = telemetry.counter("swarm_hive_jobs_failed_total")
_CHECKPOINTS = telemetry.counter(
    "swarm_hive_checkpoints_total",
    "Mid-pass checkpoint blobs POSTed to the hive (ISSUE 18), by outcome "
    "(stored = spooled + WAL-journaled; superseded = an older checkpoint "
    "blob of the same job dropped; rejected = sender is not the lessee "
    "or the job is not leased)",
    ("outcome",),
)
_PREVIEWS_STORED = telemetry.counter(
    "swarm_hive_previews_total",
    "Progressive preview artifacts POSTed to the hive (ISSUE 18), by "
    "outcome (stored | rejected)",
    ("outcome",),
)
_RESUME_OFFERS = telemetry.counter(
    "swarm_hive_resume_offers_total",
    "Redelivered jobs whose /work reply carried a `resume` offer "
    "(checkpoint href + step + program signature) to a resume-capable "
    "worker (ISSUE 18)",
)
_STALE_EPOCH = telemetry.counter(
    "swarm_hive_stale_epoch_total",
    "Requests refused with 409 because the caller has seen a newer hive "
    "epoch — this hive is a deposed primary (split-brain fencing)",
)
_EPOCH = telemetry.gauge(
    "swarm_hive_epoch",
    "This hive's fencing epoch (bumped by every standby promotion)")
_ROLE = telemetry.gauge(
    "swarm_hive_standby",
    "1 while this hive is a standby replicating from a primary, 0 once "
    "primary (born-primary or promoted)")

# served when no models.json exists under $SDAAS_ROOT — enough for a
# worker's `initialize --download` probe to succeed against a dev hive
_DEFAULT_CATALOG = {
    "models": [{"id": "stabilityai/stable-diffusion-2-1"}],
    "language_models": [],
}


class HiveServer:
    """One coordinator instance; start()/stop() or `async with`."""

    def __init__(self, settings: Settings | None = None,
                 host: str | None = None, port: int | None = None,
                 standby: bool = False):
        self.settings = settings or load_settings()
        g = lambda name, default: getattr(self.settings, name, default)  # noqa: E731
        self.host = host if host is not None else g("hive_host", "127.0.0.1")
        self.port = port if port is not None else int(g("hive_port", 9511))
        self.token = str(g("sdaas_token", ""))
        # standby role (replication.py): refuse dispatch/results/submits
        # with a 409 not-primary until promoted; epoch is the split-brain
        # fence — a request stamped with a NEWER epoch than ours proves a
        # standby was promoted over us, so we answer 409 rather than
        # double-dispatch or double-settle (see _fenced)
        self.standby = bool(standby)
        self.epoch = 0
        # fleet observability plane (ISSUE 11): per-tenant usage is pure
        # derived state over the records (accounting.py); the SLO engine
        # and fleet straggler stats are live-traffic views created here
        # so _new_state (also the replication reset path) can rewire the
        # queue's observation hook into the same engine
        self.tenant_topk = int(g("hive_tenant_topk", 10))
        self.slo = SLOEngine(
            parse_slo(g("hive_slo", "")),
            fast_window_s=float(g("hive_slo_fast_window_s", 60.0)),
            slow_window_s=float(g("hive_slo_slow_window_s", 600.0)))
        self.fleet = FleetStats(factor=float(g("hive_straggler_factor", 2.5)))
        self.queue, self.leases = self._new_state()
        # workflow graphs (ISSUE 20): stage-jobs live in the queue as
        # ordinary records; the dag table only owns the edges between
        # them and the parent aggregation — reset alongside the queue on
        # a replication reset (see replication._reset_state)
        self.dag = self._new_dag()
        self.directory = WorkerDirectory(
            ttl_s=float(g("hive_worker_ttl_s", 45.0)), fleet=self.fleet)
        # flap detection (ISSUE 18): the dispatcher queries the LIVE
        # lease table through self (a standby's replication reset swaps
        # self.leases, and the closure must follow it)
        self.flap_threshold = int(g("hive_flap_threshold", 3))
        self.dispatcher = Dispatcher(
            self.directory,
            affinity_hold_s=float(g("hive_affinity_hold_s", 15.0)),
            max_jobs_per_poll=int(g("hive_max_jobs_per_poll", 4)),
            gang_max=int(g("hive_gang_max", 8)),
            lora_slots=int(g("lora_slots_max", 8)),
            flap_threshold=self.flap_threshold,
            flapping_fn=lambda: self.leases.flapping(self.flap_threshold),
        )
        self.spool = ArtifactSpool(
            resolve_path(g("hive_spool_dir", "hive_spool")))
        self.spool_max_bytes = int(g("hive_spool_max_bytes", 0))
        self.spool_max_age_s = float(g("hive_spool_max_age_s", 0.0))
        self.refuse_with: str | None = None
        # optional health augmentation (replication.py installs the
        # standby's lag view); returns a dict merged into health(),
        # with its "degraded_reasons" list folded into the verdict
        self.extra_health = None
        self.started_at = self.queue.clock.mono()
        self._last_spool_sweep = self.queue.clock.mono()
        self._runner: web.AppRunner | None = None
        self._reaper: asyncio.Task | None = None
        # write-ahead journal: recover the pre-crash queue + lease state
        # BEFORE serving a single request ("" disables — pure in-memory,
        # the pre-WAL behavior). Replay happens here in __init__, not
        # start(), so tests and tools that drive the state machine
        # without a socket get the same durability semantics.
        self.journal: HiveJournal | None = None
        self.recovery: dict | None = None
        wal_dir = str(g("hive_wal_dir", "hive_wal"))
        if wal_dir:
            self.journal = HiveJournal(
                resolve_path(wal_dir),
                fsync=bool(g("hive_wal_fsync", False)),
                compact_every=int(g("hive_wal_compact_every", 512)))
            events = self.journal.recover()
            if events:
                self.recovery = apply_events(
                    events, self.queue, self.leases, dag=self.dag)
                self.epoch = max(
                    self.epoch, int(self.recovery.get("epoch", 0)))
                logger.warning(
                    "hive WAL replayed %d event(s) -> %s (recovered leases "
                    "get a fresh %gs deadline)", len(events), self.recovery,
                    self.leases.deadline_s)
                # repair the graph edges against the replayed records: a
                # crash between a stage settle and its ev_dag append left
                # the workflow behind its own stages — re-derive states
                # and re-admit ready successors (deterministic stage ids
                # make this exactly-once)
                for readmitted in self.dag.reconcile(self.queue):
                    self._journal(ev_admit(readmitted))
            # compact now: the stream shrinks to live state, and a
            # crash-restart-crash loop cannot grow it without bound
            self.journal.compact(
                snapshot_events(self.queue, self.leases, self.epoch,
                                dag=self.dag))
            self.journal.snapshot_fn = (
                lambda: snapshot_events(self.queue, self.leases, self.epoch,
                                        dag=self.dag))
        # leased-job cancels awaiting their lessee's next poll:
        # worker name -> job ids, delivered as the /work reply's
        # `cancels` piggyback. Volatile by design (the durable fact is
        # the record's `cancelled` state) — rebuilt from the records
        # after WAL replay and standby promotion, so a worker mid-denoise
        # across a hive crash still hears about the revocation
        self._cancel_notify: dict[str, set[str]] = {}
        self.rebuild_cancel_notify()
        # the tenant ledger is derived from the records, so a WAL replay
        # (or a fresh start) prices in here — the gauges agree with
        # GET /api/usage from the first scrape
        self.refresh_usage_metrics()
        self.note_role_change()

    def refresh_usage_metrics(self) -> dict:
        """Recompute the per-tenant usage summary from the records and
        re-export the top-K gauges; returns the raw summary (micro-unit
        buckets) for the callers that render it. O(retained history) —
        settles only mark the gauges dirty and the reaper (or the next
        /api/usage read) pays this, never the result hot path."""
        summary = accounting.usage_summary(self.queue.records.values())
        accounting.refresh_tenant_metrics(summary, self.tenant_topk)
        self._usage_dirty = False
        return summary

    def rebuild_cancel_notify(self) -> None:
        """Re-derive the pending-revocation map from record state (WAL
        recovery, standby promotion). A cancelled-while-leased record
        whose lessee never answered is re-notified on that worker's next
        poll; re-notifying a worker that already dropped the job is a
        harmless no-op on its side."""
        self._cancel_notify = {}
        for record in self.queue.records.values():
            if (record.state == "cancelled"
                    and record.cancel_stage == "leased" and record.worker):
                self._cancel_notify.setdefault(
                    record.worker, set()).add(record.job_id)
        self._refresh_cancel_gauge()

    def _refresh_cancel_gauge(self) -> None:
        _CANCEL_REVOCATIONS.set(
            sum(len(ids) for ids in self._cancel_notify.values()))

    def note_role_change(self) -> None:
        """Refresh the role/epoch gauges (called again on promotion)."""
        _EPOCH.set(self.epoch)
        _ROLE.set(1 if self.standby else 0)

    def _new_state(self) -> tuple[PriorityJobQueue, LeaseTable]:
        """Fresh queue + lease tables with this hive's knobs. Split out
        of __init__ because a standby performing a replication RESET
        (its position was compacted away on the primary) rebuilds state
        from the snapshot rather than patching the divergent copy."""
        g = lambda name, default: getattr(self.settings, name, default)  # noqa: E731
        queue = PriorityJobQueue(
            depth_limit=int(g("hive_queue_depth_limit", 256)),
            history_limit=int(g("hive_job_history_limit", 1000)),
            shed_watermarks=parse_shed_watermarks(
                g("hive_shed_watermarks", None)),
            job_ttl_s=float(g("hive_job_ttl_s", 0.0)))
        leases = LeaseTable(
            deadline_s=float(g("hive_lease_deadline_s", 300.0)),
            max_redeliveries=int(g("hive_max_redeliveries", 3)),
        )
        # rewired on every reset so a standby's rebuilt queue keeps
        # feeding the same live SLO windows
        queue.slo = self.slo
        return queue, leases

    def _new_dag(self) -> DagTable:
        g = lambda name, default: getattr(self.settings, name, default)  # noqa: E731
        return DagTable(self.queue.clock,
                        history_limit=int(g("hive_dag_history", 256)))

    # --- lifecycle ---

    @property
    def uri(self) -> str:
        return f"http://{self.host}:{self.port}"

    @property
    def api_uri(self) -> str:
        return f"{self.uri}/api"

    def build_app(self) -> web.Application:
        app = web.Application(client_max_size=256 * 1024 * 1024)
        app.router.add_get("/api/work", self._work)
        app.router.add_post("/api/results", self._results)
        app.router.add_get("/api/models", self._models)
        app.router.add_post("/api/jobs", self._submit)
        app.router.add_post("/api/workflows", self._workflow_submit)
        app.router.add_get("/api/workflows/{workflow_id}",
                           self._workflow_status)
        app.router.add_get("/api/workflows/{workflow_id}/trace",
                           self._workflow_trace)
        app.router.add_post("/api/jobs/{job_id}/cancel", self._cancel)
        app.router.add_post("/api/jobs/{job_id}/checkpoint", self._checkpoint)
        app.router.add_post("/api/jobs/{job_id}/preview", self._preview)
        app.router.add_get("/api/jobs/{job_id}", self._job_status)
        app.router.add_get("/api/jobs/{job_id}/trace", self._job_trace)
        app.router.add_get("/api/usage", self._usage)
        app.router.add_get("/api/tenants/{tenant}/usage", self._tenant_usage)
        app.router.add_get("/api/slo", self._slo)
        app.router.add_get("/api/artifacts/{digest}", self._artifact)
        app.router.add_get("/api/replication/stream", self._replication_stream)
        app.router.add_get("/metrics", self._metrics)
        app.router.add_get("/healthz", self._healthz)
        return app

    async def start(self) -> "HiveServer":
        self._runner = web.AppRunner(self.build_app())
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        # port 0 binds an ephemeral port; report the real one
        self.port = site._server.sockets[0].getsockname()[1]
        self._reaper = asyncio.create_task(
            self._reap_loop(), name="hive_lease_reaper")
        logger.info("hive coordinator on %s (lease %.0fs, queue limit %d)",
                    self.uri, self.leases.deadline_s,
                    self.queue.depth_limit)
        return self

    async def stop(self) -> None:
        if self._reaper is not None:
            self._reaper.cancel()
            await asyncio.gather(self._reaper, return_exceptions=True)
            self._reaper = None
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None
        if self.journal is not None:
            self.journal.close()

    def _journal(self, event: dict) -> None:
        """Append one transition; a journal WRITE failure (full disk,
        bad mount) is logged loudly but never takes serving down — the
        hive degrades to the pre-WAL in-memory semantics it had for five
        PRs rather than refusing jobs it can still run. Injected faults
        (kill_before_journal_sync) DO propagate: they simulate the
        process dying at this exact line."""
        if self.journal is None:
            return
        try:
            self.journal.append(event)
        except OSError:
            logger.exception(
                "hive WAL append failed; this transition is NOT "
                "restart-durable")

    async def __aenter__(self) -> "HiveServer":
        return await self.start()

    async def __aexit__(self, *exc) -> None:
        await self.stop()

    async def _reap_loop(self) -> None:
        """Expire overdue leases on a cadence well inside the deadline,
        so a redelivery waits ~one deadline, not up to two."""
        interval = min(1.0, max(self.leases.deadline_s / 4.0, 0.05))
        while True:
            await asyncio.sleep(interval)
            if self.standby:
                # replicated leases are the PRIMARY's to expire; a
                # standby reaping them would diverge from the stream it
                # is applying (promotion re-grants them fresh instead)
                continue
            try:
                for record in self.leases.reap(self.queue):
                    if record.state == "failed":
                        self._drop_partials(record)
                        self._journal(ev_park(record))
                        for pruned in self.queue.retire(record):
                            self._journal(ev_retire(pruned))
                        self._note_stage_terminal(record, "failed")
                        logger.error("job %s failed: %s",
                                     record.job_id, record.error)
                    else:
                        self._journal(ev_requeue(record))
                        logger.warning(
                            "lease expired for job %s (attempt %d); "
                            "re-queued at the front of class %s",
                            record.job_id, record.attempts,
                            record.job_class)
                self._expire_due()
                self._park_unplaceable()
                self._sweep_spool_if_due()
                # keep the burn-rate gauges fresh between scrapes: the
                # windows slide whether or not anyone polls /api/slo
                self.slo.refresh_metrics()
                if self._usage_dirty:
                    # settles defer the O(history) tenant-gauge refresh
                    # here: once per reaper tick, not once per result
                    self.refresh_usage_metrics()
            except Exception:
                # the reaper is the only thing that frees a dead
                # worker's lease; it must survive any single bad pass
                logger.exception("lease reaper pass failed; continuing")

    def _park_unplaceable(self) -> None:
        """Park queued jobs no live worker can run. A job whose model
        family every live worker advertises as unconverted is skipped by
        dispatch on every poll — it never leases, so the redelivery
        budget never engages, yet it occupies admission depth; enough of
        them wedge the queue at 429 until a restart. Give each one a
        full lease deadline of queue time for a capable worker to show
        up, then fail it with the same parking machinery an exhausted
        lease uses."""
        cutoff = self.queue.clock.mono() - self.leases.deadline_s
        for record in self.queue.iter_queued():
            if record.submitted_at > cutoff:
                continue
            if not self.dispatcher.unplaceable(record):
                continue
            self.queue.discard_queued(record)
            record.state = "failed"
            record.error = (
                "unplaceable: every live worker advertises this job's "
                "model family as unconverted "
                f"(waited {self.leases.deadline_s:g}s)")
            record.timeline.append({
                "event": "park", "wall": self.queue.clock.wall(),
                "reason": "unplaceable"})
            self._drop_partials(record)
            self._journal(ev_park(record))
            for pruned in self.queue.retire(record):
                self._journal(ev_retire(pruned))
            self._note_stage_terminal(record, "failed")
            _JOBS_FAILED.inc()
            logger.error("job %s failed: %s", record.job_id, record.error)

    # artifact-retention cadence: the sweep globs the whole spool tree,
    # so it rides the reaper at most this often, not every pass
    SPOOL_SWEEP_INTERVAL_S = 30.0

    def _sweep_spool_if_due(self) -> None:
        if self.spool_max_bytes <= 0 and self.spool_max_age_s <= 0:
            return
        now = self.queue.clock.mono()
        if now - self._last_spool_sweep < self.SPOOL_SWEEP_INTERVAL_S:
            return
        self._last_spool_sweep = now
        self.sweep_spool()

    def sweep_spool(self) -> int:
        """Age/size-bound the artifact spool. Blobs referenced by a live
        record — any record still answering GET /api/jobs/{id}, i.e. not
        yet pruned from history — are protected: a status poll must keep
        resolving its hrefs. Everything else is fair game — content
        addressing means a re-submitted duplicate simply re-stores the
        blob."""
        protected: set[str] = set()
        for record in self.queue.records.values():
            if not isinstance(record.result, dict):
                continue
            artifacts = record.result.get("artifacts")
            if not isinstance(artifacts, dict):
                continue
            for art in artifacts.values():
                if isinstance(art, dict) and isinstance(
                        art.get("sha256"), str):
                    protected.add(art["sha256"])
        # live mid-pass state (ISSUE 18): a checkpoint awaiting its
        # resume, or previews a poll can still reference, must survive
        # the sweep whatever their age
        protected |= self.queue.partial_digests()
        return self.spool.sweep(self.spool_max_bytes, self.spool_max_age_s,
                                protected)

    # --- auth ---

    def _authorized(self, request: web.Request) -> bool:
        if not self.token:
            return True
        return request.headers.get(
            "Authorization", "") == f"Bearer {self.token}"

    @staticmethod
    def _unauthorized() -> web.Response:
        return web.json_response({"message": "unauthorized"}, status=401)

    # --- replication role + split-brain fencing ---

    def _epoch_headers(self) -> dict[str, str]:
        """Every hive answer advertises the fencing epoch; workers track
        the maximum they have seen and echo it back (X-Hive-Epoch), which
        is what lets a deposed primary discover it was deposed."""
        return {"X-Hive-Epoch": str(self.epoch)}

    def _refuse_not_primary(self) -> web.Response | None:
        if not self.standby:
            return None
        return web.json_response(
            {"message": "not primary: standby replicating "
                        "(fail over to the promoted hive)"},
            status=409, headers=self._epoch_headers())

    def _refuse_stale_epoch(self, request: web.Request) -> web.Response | None:
        """409 any request stamped with a NEWER epoch than ours: the
        caller has talked to a hive promoted over us, so we are a deposed
        primary and our dispatches/ACKs must not count — accepting them
        would double-dispatch the job we think is queued or double-settle
        the one the true primary already owns."""
        raw = request.headers.get("X-Hive-Epoch", "")
        try:
            seen = int(raw)
        except ValueError:
            return None
        if seen <= self.epoch:
            return None
        _STALE_EPOCH.inc()
        logger.error(
            "stale-epoch request refused: caller at epoch %d, this hive "
            "at %d — a standby was promoted over this (deposed) primary",
            seen, self.epoch)
        return web.json_response(
            {"message": f"not primary: stale hive epoch {self.epoch} "
                        f"(the swarm is at epoch {seen}; this hive was "
                        "deposed)"},
            status=409, headers=self._epoch_headers())

    def _refused(self, request: web.Request) -> web.Response | None:
        # explicit None checks: web.Response is a MutableMapping and an
        # empty one is FALSY, so `a or b` would drop a real refusal
        refused = self._refuse_not_primary()
        if refused is not None:
            return refused
        return self._refuse_stale_epoch(request)

    # --- wire-protocol handlers ---

    async def _work(self, request: web.Request) -> web.Response:
        if not self._authorized(request):
            return self._unauthorized()
        refused = self._refused(request)
        if refused is not None:
            _POLLS.inc(reply="refused")
            return refused
        if self.refuse_with is not None:
            _POLLS.inc(reply="refused")
            return web.json_response(
                {"message": self.refuse_with}, status=400)
        query = dict(request.query)
        if not query.get("worker_version"):
            # 400-with-message refusal, reference swarm/hive.py:39-44
            _POLLS.inc(reply="refused")
            return web.json_response(
                {"message": "worker_version is required"}, status=400)
        worker = self.directory.observe(query)
        # park TTL-lapsed queued jobs BEFORE the dispatcher looks: an
        # expired job must never waste this poll's dispatch budget
        self._expire_due()
        if query.get("cancel_only"):
            # heartbeat from a saturated worker (every slice busy): it
            # cannot take work but must still hear about revocations of
            # the leases it is executing — and the observe() above keeps
            # it live in the directory through a long denoise
            handed = []
        else:
            handed = self.dispatcher.select(worker, self.queue)
        for record, outcome, gang in handed:
            # a gang is a dispatch-time grouping, NOT a new lifecycle:
            # each member is taken, leased, and journaled individually —
            # redelivery/settle semantics per job are unchanged, and a
            # lost gang degrades to singles through the normal reaper
            self.queue.take(record, worker.name, outcome, gang=gang)
            self.leases.grant(record, worker.name)
            self._journal(ev_lease(record))
            logger.info("dispatched job %s to %s (%s, attempt %d%s)",
                        record.job_id, worker.name, outcome, record.attempts,
                        f", gang {gang['id']} {gang['index'] + 1}/"
                        f"{gang['size']}" if gang else "")
        # chaos hook: the hive 'dies' after leasing + journaling but
        # before the reply leaves — the worker never sees the jobs, and
        # recovery + lease expiry must redeliver them
        faults.fire("crash_after_lease")
        _POLLS.inc(reply="jobs" if handed else "empty")
        # every handed job carries its trace context on the wire (a copy
        # — the stored job dict stays pristine in the WAL): the worker
        # echoes it back inside the envelope's pipeline_config.trace so
        # its stage spans attach to the right dispatch attempt, and gang
        # members carry trace.gang so they arrive pre-batched. Field
        # set pinned by the protocol-conformance suite.
        jobs_payload = []
        for record, _, gang in handed:
            job = dict(record.job,
                       trace=wire_trace_context(record, gang=gang))
            ck = record.checkpoint
            if (ck and ck.get("sha256") and worker.resume_capable
                    and record.attempts > 1):
                # resume-on-redelivery (ISSUE 18): a redelivered job
                # whose previous lessee shipped a mid-pass checkpoint
                # carries the offer — href to the spooled blob, the
                # step it was cut at, and the program signature the
                # worker validates before rehydrating. Only attached
                # for resume-capable pollers (capability-advertised),
                # so legacy workers see the pre-resume wire shape.
                job["resume"] = {
                    "href": f"/api/artifacts/{ck['sha256']}",
                    "step": int(ck.get("step", 0)),
                    "signature": ck.get("signature"),
                }
                _RESUME_OFFERS.inc()
                record.timeline.append({
                    "event": "resume_offer",
                    "wall": self.queue.clock.wall(),
                    "worker": worker.name,
                    "step": int(ck.get("step", 0))})
            jobs_payload.append(job)
        reply = {"jobs": jobs_payload}
        # piggyback pending lease revocations for THIS worker: the ids
        # of its live leases cancelled since its last poll. Popped on
        # delivery — a reply lost in flight degrades to the job running
        # to completion and its late result earning the `cancelled`
        # disposition (the durable state, not this hint, is the truth).
        # Legacy workers ignore the unknown key; the key is absent when
        # there is nothing to revoke, so the pre-cancel wire shape is
        # byte-identical (conformance-pinned).
        cancels = self._cancel_notify.pop(worker.name, None)
        if cancels:
            reply["cancels"] = sorted(cancels)
            self._refresh_cancel_gauge()
            logger.info("revoking %d cancelled lease(s) from %s: %s",
                        len(cancels), worker.name, sorted(cancels))
        return web.json_response(reply, headers=self._epoch_headers())

    async def _results(self, request: web.Request) -> web.Response:
        if not self._authorized(request):
            return self._unauthorized()
        refused = self._refused(request)
        if refused is not None:
            return refused
        body = await request.read()
        try:
            # a result envelope can be hundreds of MB of base64 blobs
            # (client_max_size above); parsing that on the event loop
            # would stall every other handler and the lease reaper
            result = await asyncio.to_thread(json.loads, body)
        except json.JSONDecodeError:
            return web.json_response(
                {"message": "result envelope is not JSON"}, status=400)
        if not isinstance(result, dict):
            return web.json_response(
                {"message": "result envelope must be a JSON object"},
                status=400)
        job_id = str(result.get("id", ""))
        record = self.queue.records.get(job_id)
        if record is None:
            # a job this hive never issued (e.g. another hive's outbox
            # redelivery): ACK it anyway — a 4xx would make the worker
            # park an envelope the operator may still want
            _RESULTS.inc(status="unknown")
            return web.json_response({"status": "ok", "unknown_job": True})
        if record.state in ("done", "settling"):
            # duplicate submit (outbox redelivery after a lost ACK, or a
            # concurrent POST racing the spool write): idempotent ACK,
            # nothing re-stored
            _RESULTS.inc(status="duplicate")
            return web.json_response({"status": "ok", "duplicate": True})
        if record.state in ("cancelled", "expired"):
            # the cancel/TTL won the race: the result is not stored, but
            # the ACK names the disposition so the worker's outbox can
            # PARK the envelope (reason visible in outbox_inspect)
            # instead of retrying a submission this hive will never
            # accept. The cancel-vs-result race is pinned: whichever
            # settled first wins, this side is an idempotent no-op.
            disposition = record.state
            _RESULTS.inc(status=disposition)
            # only the CURRENT lessee's own envelope proves it knows: a
            # late result from a PREVIOUS lessee (expired lease, job
            # redelivered, then cancelled) must not silence the pending
            # revocation the live lessee still needs to abort its pass
            sender = str(result.get("worker_name") or "") or None
            if record.worker and sender == record.worker:
                pending = self._cancel_notify.get(record.worker)
                if pending and job_id in pending:
                    pending.discard(job_id)
                    if not pending:
                        del self._cancel_notify[record.worker]
                    self._refresh_cancel_gauge()
            return web.json_response(
                {"status": "ok", disposition: True},
                headers=self._epoch_headers())
        # the envelope's own worker_name (stamped by the worker's outbox
        # path; optional on the wire) identifies the true sender — the
        # current lease does NOT: a late result from an expired lessee
        # can arrive while the redelivered copy is leased to someone else
        sender = str(result.get("worker_name") or "") or None
        lease = self.leases.settle(job_id)
        if record.state == "queued":
            # the original lessee answered after expiry, while the
            # redelivered copy was still queued: take the result, cancel
            # the redelivery
            self.queue.discard_queued(record)
            status = "late"
        elif record.state == "failed":
            status = "late"  # better late than parked
        elif sender and lease and sender != lease.worker:
            status = "late"  # an earlier lessee beat the current one
        else:
            status = "ok"
        # "settling" (set with no await point since the state checks
        # above) routes a concurrent duplicate POST to the idempotent
        # ACK; the blob decode/hash/write itself runs in a thread so a
        # multi-MB envelope never stalls /work polls or the lease reaper
        record.state = "settling"
        try:
            stored = await asyncio.to_thread(self.spool.store_result, result)
        except Exception:
            # the spool is an optimization, never a gate on accepting a
            # result: a full/read-only disk keeps the blobs inline rather
            # than wedging the record in "settling" (where the worker's
            # retry would be ACKed as a duplicate and the result lost)
            logger.exception("artifact spool failed for job %s; "
                             "keeping blobs inline", job_id)
            stored = result
        record.result = stored
        record.error = None
        record.done_at = self.queue.clock.mono()
        record.completed_by = (
            sender or (lease.worker if lease else record.worker))
        record.state = "done"
        # the final artifact supersedes every partial (ISSUE 18)
        self._drop_partials(record)
        settle_event = {
            "event": "settle", "wall": self.queue.clock.wall(),
            "worker": record.completed_by, "disposition": status,
        }
        # the worker echoes the wire trace context; its attempt number
        # ties the envelope's stage spans to the dispatch that produced
        # them (a late result names the EARLIER attempt, visibly)
        echoed_attempt = envelope_trace(stored).get("attempt")
        if isinstance(echoed_attempt, int):
            settle_event["attempt"] = echoed_attempt
        record.timeline.append(settle_event)
        self.queue.observe_settle(record)
        self._journal(ev_settle(record))
        for pruned in self.queue.retire(record):
            self._journal(ev_retire(pruned))
        # stage-graph advance (ISSUE 20): a settled stage-job admits its
        # ready successors (with the settled stage's spool artifacts
        # injected as handoff inputs) and may complete the workflow;
        # records journal before the graph so replay never restores a
        # graph pointing at jobs the WAL has not admitted yet. A
        # monolithic job returns (None, []) and journals nothing extra.
        wf, stage_admitted = self.dag.note_settle(record, self.queue)
        if wf is not None:
            for stage_record in stage_admitted:
                self._journal(ev_admit(stage_record))
            self._journal(ev_dag(wf))
        # tenant accounting (accounting.py): bill this settle. An
        # envelope with no usable stage timings (older worker, a parked-
        # then-requeued outbox redelivery) is billed its wall-clock
        # dispatch-to-settle and COUNTED — approximate beats silently
        # absent from the tenant's ledger. Counted live only; replay
        # rebuilds the ledger without re-counting.
        usage = accounting.job_usage(record)
        if usage is not None and usage["fallback"]:
            accounting.note_fallback()
            logger.warning(
                "job %s settled without pipeline_config.timings; tenant "
                "%s billed wall-clock %.3fs (fallback)", job_id,
                usage["tenant"], usage["chip_us"] / 1e6)
        # the gauge refresh re-scans the retained records (O(history));
        # deferring it to the next reaper tick keeps the settle path
        # O(1) however deep the history runs — /api/usage itself always
        # refreshes, so readers never see the deferral
        self._usage_dirty = True
        _RESULTS.inc(status=status)
        return web.json_response(
            {"status": "ok"}, headers=self._epoch_headers())

    async def _cancel(self, request: web.Request) -> web.Response:
        """POST /api/jobs/{id}/cancel: revoke a job. A QUEUED job is
        tombstoned from its class queue (and the gang index) on the spot;
        a LEASED one has its lease revoked hive-side and the lessee is
        told on its next /work poll (`cancels` piggyback) so a chunked
        denoise can abort within one chunk. Races are pinned: whichever
        settles first wins — cancelling a done/settling job is an
        idempotent no-op (cancelled=False, the result stands), and a
        result arriving after a cancel earns the `cancelled` disposition
        (the worker's outbox parks it instead of retrying forever).
        Every real transition is WAL-journaled before the response
        leaves, so a cancel survives SIGKILL recovery and standby
        promotion exactly like lease state."""
        if not self._authorized(request):
            return self._unauthorized()
        refused = self._refused(request)
        if refused is not None:
            return refused
        job_id = request.match_info["job_id"]
        record = self.queue.records.get(job_id)
        if record is None:
            return web.json_response(
                {"message": "unknown job id"}, status=404)

        def reply(cancelled: bool) -> web.Response:
            return web.json_response({
                "id": job_id,
                "status": record.state,
                "cancelled": cancelled,
            }, headers=self._epoch_headers())

        if record.state == "cancelled":
            return reply(True)  # idempotent repeat
        if record.state in ("done", "settling", "failed", "expired"):
            # the other side of the race already settled; no-op
            return reply(False)
        if record.state == "queued":
            self.queue.mark_cancelled(record, "queued")
            self._drop_partials(record)
            self._journal(ev_cancel(record))
            for pruned in self.queue.retire(record):
                self._journal(ev_retire(pruned))
            self._note_stage_terminal(record, "cancelled")
            logger.info("job %s cancelled while queued", job_id)
            return reply(True)
        # leased: revoke the lease (the reaper must not redeliver a job
        # nobody wants) and queue the revocation for the lessee's next
        # poll; the denoise chunk boundary does the actual abort
        self.leases.settle(job_id)
        self.queue.mark_cancelled(record, "leased")
        self._drop_partials(record)
        self._journal(ev_cancel(record))
        for pruned in self.queue.retire(record):
            self._journal(ev_retire(pruned))
        self._note_stage_terminal(record, "cancelled")
        if record.worker:
            self._cancel_notify.setdefault(
                record.worker, set()).add(job_id)
            self._refresh_cancel_gauge()
        logger.warning(
            "job %s cancelled while leased to %s (attempt %d); lease "
            "revoked, worker notified on its next poll",
            job_id, record.worker, record.attempts)
        return reply(True)

    # --- mid-pass durability (ISSUE 18) ---

    def _note_stage_terminal(self, record, outcome: str) -> None:
        """Stage-graph fail-closed (ISSUE 20): a stage-job that ended
        without settling (cancelled / expired / parked failed) fails its
        workflow — blocked descendants never admit, still-queued siblings
        are cancelled and journaled here, and the updated graph state
        rides ONE ev_dag. No-op for monolithic jobs."""
        wf, cascaded = self.dag.note_terminal(record, outcome, self.queue)
        if wf is None:
            return
        for sibling in cascaded:
            self._drop_partials(sibling)
            self._journal(ev_cancel(sibling))
            for pruned in self.queue.retire(sibling):
                self._journal(ev_retire(pruned))
        self._journal(ev_dag(wf))

    def _drop_partials(self, record) -> None:
        """Terminal states keep no mid-pass state: clear the record's
        checkpoint + previews and delete their now-unreferenced spool
        blobs (the final artifact supersedes every partial)."""
        for digest in self.queue.clear_partial(record):
            self.spool.drop(digest)

    async def _partial_body(self, request: web.Request
                            ) -> tuple[dict | None, bytes | None,
                                       web.Response | None]:
        """Shared validation for checkpoint/preview POSTs: the sender
        must be the job's CURRENT lessee and the job must still be
        leased — a blob from an expired lessee (or for a settled job)
        is refused so stale state can never shadow live state. Returns
        (record_meta, blob, error_response)."""
        import base64
        import binascii

        job_id = request.match_info["job_id"]
        record = self.queue.records.get(job_id)
        if record is None:
            return None, None, web.json_response(
                {"message": "unknown job id"}, status=404)
        try:
            body = json.loads(await request.text())
        except json.JSONDecodeError:
            return None, None, web.json_response(
                {"message": "body is not JSON"}, status=400)
        if not isinstance(body, dict) or not isinstance(
                body.get("blob"), str):
            return None, None, web.json_response(
                {"message": "body must carry a base64 `blob`"}, status=400)
        sender = str(body.get("worker_name") or "") or None
        lease = self.leases.get(job_id)
        if record.state != "leased" or lease is None or (
                sender is not None and sender != lease.worker):
            return {"record": record}, None, web.json_response(
                {"message": f"job is {record.state}; only the current "
                            "lessee may ship mid-pass state",
                 "status": record.state},
                status=409, headers=self._epoch_headers())
        try:
            blob = base64.b64decode(body["blob"])
        except (binascii.Error, ValueError):
            return None, None, web.json_response(
                {"message": "blob is not base64"}, status=400)
        return {"record": record, "body": body}, blob, None

    async def _checkpoint(self, request: web.Request) -> web.Response:
        """POST /api/jobs/{id}/checkpoint: the lessee's mid-pass state
        at a chunk boundary. Spooled content-addressed, recorded on the
        job as ONE WAL event (replayed, compacted, replicated), and only
        the newest kept — a superseded blob is dropped on the spot."""
        if not self._authorized(request):
            return self._unauthorized()
        refused = self._refused(request)
        if refused is not None:
            return refused
        meta, blob, error = await self._partial_body(request)
        if error is not None:
            _CHECKPOINTS.inc(outcome="rejected")
            return error
        record, body = meta["record"], meta["body"]
        digest = await asyncio.to_thread(self.spool.put, blob)
        superseded = self.queue.note_checkpoint(record, {
            "step": int(body.get("step", 0)),
            "sha256": digest,
            "signature": str(body.get("signature", "")),
            "bytes": len(blob),
        })
        if superseded:
            self.spool.drop(superseded)
            _CHECKPOINTS.inc(outcome="superseded")
        self._journal(ev_checkpoint(record))
        _CHECKPOINTS.inc(outcome="stored")
        return web.json_response({
            "status": "ok", "step": int(body.get("step", 0)),
            "sha256": digest,
        }, headers=self._epoch_headers())

    async def _preview(self, request: web.Request) -> web.Response:
        """POST /api/jobs/{id}/preview: an intermediate decode of the
        live latents. Appends to the record's `partial` disposition
        (GET /api/jobs/{id}) and rides the same WAL event as the
        checkpoint meta."""
        if not self._authorized(request):
            return self._unauthorized()
        refused = self._refused(request)
        if refused is not None:
            return refused
        meta, blob, error = await self._partial_body(request)
        if error is not None:
            _PREVIEWS_STORED.inc(outcome="rejected")
            return error
        record, body = meta["record"], meta["body"]
        digest = await asyncio.to_thread(self.spool.put, blob)
        self.queue.note_preview(record, {
            "step": int(body.get("step", 0)),
            "sha256": digest,
            "bytes": len(blob),
            "href": f"/api/artifacts/{digest}",
            **({"content_type": str(body["content_type"])}
               if body.get("content_type") else {}),
        })
        self._journal(ev_checkpoint(record))
        _PREVIEWS_STORED.inc(outcome="stored")
        return web.json_response({
            "status": "ok", "step": int(body.get("step", 0)),
            "href": f"/api/artifacts/{digest}",
        }, headers=self._epoch_headers())

    def _expire_due(self) -> None:
        """Park queued jobs whose admission-time TTL lapsed. Runs before
        every dispatch decision (an expired job must not waste a
        dispatch) and on every reaper pass (so expiry fires even with no
        worker polling)."""
        for record in self.queue.expired_queued():
            self.queue.mark_expired(record)
            self._drop_partials(record)
            self._journal(ev_expire(record))
            for pruned in self.queue.retire(record):
                self._journal(ev_retire(pruned))
            self._note_stage_terminal(record, "expired")
            logger.warning("job %s expired after %.0fs queued (TTL)",
                           record.job_id,
                           self.queue.clock.mono() - record.submitted_at)

    async def _models(self, request: web.Request) -> web.Response:
        # deliberately unauthenticated: public catalog, reference parity
        # (see module docstring) — keep job data and metrics off it
        catalog = _DEFAULT_CATALOG
        path = get_settings_dir() / "models.json"
        try:
            # off-loop (read AND parse): an operator-supplied catalog can
            # be arbitrarily large, and this handler shares the loop
            # with dispatch
            data = await asyncio.to_thread(
                lambda: json.loads(path.read_text()))
            if isinstance(data, dict) and "models" in data:
                catalog = {
                    "models": data.get("models", []),
                    "language_models": data.get("language_models", []),
                }
        except (OSError, json.JSONDecodeError):
            pass
        return web.json_response(catalog)

    # --- coordinator surface ---

    async def _submit(self, request: web.Request) -> web.Response:
        if not self._authorized(request):
            return self._unauthorized()
        refused = self._refused(request)
        if refused is not None:
            return refused
        try:
            job = json.loads(await request.text())
        except json.JSONDecodeError:
            return web.json_response(
                {"message": "job is not JSON"}, status=400)
        if not isinstance(job, dict):
            return web.json_response(
                {"message": "job must be a JSON object"}, status=400)
        known = str(job.get("id") or "") in self.queue.records
        try:
            record = self.queue.submit(job)
        except QueueFull as e:
            return web.json_response({"message": str(e)}, status=429)
        if not known:
            self._journal(ev_admit(record))
        return web.json_response({
            "id": record.job_id,
            "class": record.job_class,
            "tenant": record.tenant,
            "status": record.state,
            "depth": self.queue.depth,
        })

    async def _workflow_submit(self, request: web.Request) -> web.Response:
        """POST /api/workflows: expand a multi-stage submission into its
        stage-job DAG (hive_server/dag.py). The ready stages are admitted
        immediately as ordinary records; successors admit as their needs
        settle. WAL order is records-then-graph (ev_admit per stage, then
        ONE ev_dag carrying the whole workflow state) so replay always
        sees the jobs a restored graph refers to; the reconcile pass in
        __init__ repairs a crash that landed between the two."""
        if not self._authorized(request):
            return self._unauthorized()
        refused = self._refused(request)
        if refused is not None:
            return refused
        try:
            payload = json.loads(await request.text())
        except json.JSONDecodeError:
            return web.json_response(
                {"message": "workflow is not JSON"}, status=400)
        if not isinstance(payload, dict):
            return web.json_response(
                {"message": "workflow must be a JSON object"}, status=400)
        try:
            wf, admitted = self.dag.submit(payload, self.queue)
        except WorkflowError as e:
            return web.json_response({"message": str(e)}, status=400)
        except QueueFull as e:
            return web.json_response({"message": str(e)}, status=429)
        for record in admitted:
            self._journal(ev_admit(record))
        # unconditional: an idempotent resubmit re-appends the same graph
        # state, and restore-by-replacement makes that a no-op on replay
        self._journal(ev_dag(wf))
        return web.json_response({
            "id": wf.workflow_id,
            "workflow": wf.job.get("workflow"),
            "class": job_class(wf.job),
            "tenant": wf.tenant,
            "status": wf.state,
            "stages": [{"stage": s["name"], "index": s["index"],
                        "id": s["job_id"], "status": s["state"]}
                       for s in wf.stages],
            "depth": self.queue.depth,
        }, headers=self._epoch_headers())

    async def _workflow_status(self, request: web.Request) -> web.Response:
        """GET /api/workflows/{id}: the parent aggregation — per-stage
        lifecycle + attempts + worker, the pooled usage totals, and (once
        done) the final stage's result envelope."""
        if not self._authorized(request):
            return self._unauthorized()
        wf = self.dag.workflows.get(request.match_info["workflow_id"])
        if wf is None:
            return web.json_response(
                {"message": "unknown workflow id"}, status=404)
        return web.json_response(self.dag.status(wf, self.queue))

    async def _workflow_trace(self, request: web.Request) -> web.Response:
        """GET /api/workflows/{id}/trace: every stage's timeline merged
        on one wall clock, with the settle->admit seams attributed as
        `stage_handoff` — shaped to pass the same trace_missing oracle a
        monolithic trace does."""
        if not self._authorized(request):
            return self._unauthorized()
        wf = self.dag.workflows.get(request.match_info["workflow_id"])
        if wf is None:
            return web.json_response(
                {"message": "unknown workflow id"}, status=404)
        return web.json_response(
            self.dag.build_trace(wf, self.queue, self.queue.clock.wall()))

    async def _job_status(self, request: web.Request) -> web.Response:
        if not self._authorized(request):
            return self._unauthorized()
        record = self.queue.records.get(request.match_info["job_id"])
        if record is None:
            return web.json_response(
                {"message": "unknown job id"}, status=404)
        return web.json_response(record.status())

    async def _job_trace(self, request: web.Request) -> web.Response:
        """One ordered, gap-attributed timeline per job: hive lifecycle
        events (admit/shed/dispatch/lease/redeliver/settle, WAL-durable)
        merged with the worker's stage spans from the settled envelope.
        See hive_server/trace.py for the assembly contract."""
        if not self._authorized(request):
            return self._unauthorized()
        job_id = request.match_info["job_id"]
        record = self.queue.records.get(job_id)
        if record is None:
            shed = self.queue.shed_traces.get(job_id)
            if shed:
                # never admitted, but we watched it being shed: the
                # refusals ARE its timeline so far
                return web.json_response(build_shed_trace(job_id, shed))
            return web.json_response(
                {"message": "unknown job id"}, status=404)
        return web.json_response(
            build_trace(record, self.queue.clock.wall()))

    async def _usage(self, request: web.Request) -> web.Response:
        """GET /api/usage: the per-tenant ledger — chip-seconds, rows,
        coalesce savings, embed-cache hits, artifact bytes, and fallback
        counts per submitter, plus grand totals. Derived on demand from
        the settled records (accounting.py), so it is exactly as
        crash-consistent and replication-consistent as the records
        themselves; standbys answer it like any other read. Window =
        whatever history the hive retains (hive_job_history_limit), the
        same window GET /api/jobs/{id} answers from."""
        if not self._authorized(request):
            return self._unauthorized()
        summary = self.refresh_usage_metrics()
        return web.json_response(
            accounting.render_usage(summary, self.tenant_topk))

    async def _tenant_usage(self, request: web.Request) -> web.Response:
        """GET /api/tenants/{id}/usage: one tenant's bucket (zeroed when
        the retained history holds nothing for it — an unknown tenant is
        indistinguishable from an idle one by design)."""
        if not self._authorized(request):
            return self._unauthorized()
        return web.json_response(accounting.render_tenant_reply(
            accounting.usage_summary(self.queue.records.values()),
            request.match_info["tenant"]))

    async def _slo(self, request: web.Request) -> web.Response:
        """GET /api/slo: per-class objective compliance and fast/slow
        burn rates over the sliding windows (slo.py). Shape is
        conformance-pinned; with no hive_slo configured the reply
        carries enabled=false and an empty classes map."""
        if not self._authorized(request):
            return self._unauthorized()
        return web.json_response(self.slo.refresh_metrics())

    async def _artifact(self, request: web.Request) -> web.Response:
        if not self._authorized(request):
            return self._unauthorized()
        path = self.spool.path_for(request.match_info["digest"])
        if path is None:
            return web.json_response(
                {"message": "unknown artifact"}, status=404)
        # FileResponse streams via sendfile — a multi-hundred-MB blob
        # neither blocks the event loop nor lands in memory whole
        return web.FileResponse(
            path, headers={"Content-Type": "application/octet-stream"})

    # --- replication (hive_server/replication.py tails this) ---

    async def _replication_stream(self, request: web.Request) -> web.Response:
        """WAL event stream for a standby: events past `since`, or the
        full compacted snapshot with `reset` when the requested position
        was compacted away. Served from the journal's in-memory mirror,
        so a torn tail on disk never reaches a replica."""
        if not self._authorized(request):
            return self._unauthorized()
        if self.journal is None:
            return web.json_response(
                {"message": "replication requires a WAL "
                            "(hive_wal_dir is disabled on this hive)"},
                status=400)
        try:
            since = int(request.query.get("since", "0"))
        except ValueError:
            return web.json_response(
                {"message": "since must be an integer replication "
                            "sequence"}, status=400)
        events, reset = self.journal.stream_since(since)
        return web.json_response({
            "events": events,
            "seq": self.journal.last_rs,
            "reset": reset,
            "epoch": self.epoch,
            "standby": self.standby,
        }, headers=self._epoch_headers())

    # --- telemetry ---

    async def _metrics(self, request: web.Request) -> web.Response:
        return web.Response(
            text=telemetry.REGISTRY.render(),
            headers={
                "Content-Type": "text/plain; version=0.0.4; charset=utf-8"},
        )

    def health(self) -> dict:
        states: dict[str, int] = {}
        for record in self.queue.records.values():
            states[record.state] = states.get(record.state, 0) + 1
        reasons = []
        if (self.queue.depth_limit > 0
                and self.queue.depth >= self.queue.depth_limit):
            reasons.append(
                f"queue full ({self.queue.depth}/{self.queue.depth_limit}): "
                "admission refusing new jobs")
        for cls in self.queue.shedding():
            threshold = self.queue.shed_threshold(cls)
            if threshold < self.queue.depth_limit:
                # partial, class-aware degradation; the full queue is
                # already reported above
                reasons.append(
                    f"shedding {cls} jobs ({self.queue.depth} queued >= "
                    f"{cls} watermark {threshold})")
        if self.refuse_with is not None:
            reasons.append(f"draining: refusing workers ({self.refuse_with})")
        # SLO fast-burn breaches are degraded reasons: a class burning
        # its error budget >FAST_BURN_DEGRADED x over the fast window is
        # exactly what an orchestrator probe should react to
        slo_report = self.slo.refresh_metrics()
        reasons.extend(self.slo.degraded_reasons(slo_report))
        extra: dict = {}
        if self.extra_health is not None:
            # replication.py installs its tail-side view here: a standby
            # reports its lag and goes degraded when the stream stalls
            try:
                extra = dict(self.extra_health() or {})
                reasons.extend(extra.pop("degraded_reasons", []))
            except Exception:  # a broken probe must not break /healthz
                logger.exception("extra health probe failed")
        payload = {
            "status": "degraded" if reasons else "ok",
            "degraded_reasons": reasons,
            "role": "standby" if self.standby else "primary",
            "epoch": self.epoch,
            "uptime_s": round(self.queue.clock.mono() - self.started_at, 1),
            "queue_depth": self.queue.depths(),
            "leases_active": len(self.leases),
            "jobs": states,
            # stage-graph serving (ISSUE 20): workflow counts by state +
            # ready-stage depth — the swarm_top `workflows` line
            "workflows": self.dag.summary(),
            "workers": self.directory.snapshot(),
            # fleet observability plane (ISSUE 11): compact SLO verdict
            # per class, straggler flags per live reporter, and the
            # top-K tenant cut — the swarm_top frames read these
            "slo": {
                cls: {"fast_burn": view["fast_burn"],
                      "slow_burn": view["slow_burn"],
                      "compliance": view["compliance"],
                      "breaching": view["breaching"]}
                for cls, view in slo_report["classes"].items()
            },
            "stragglers": self.fleet.snapshot(self.directory.live_names()),
            # flap detection (ISSUE 18): workers currently preferred-
            # against for fresh seeds (consecutive lease expiries >=
            # hive_flap_threshold), plus the raw streaks behind them
            "flapping": sorted(self.leases.flapping(self.flap_threshold)),
            "flap_streaks": dict(self.leases.flaps),
        }
        if self.journal is not None:
            payload["wal"] = {
                "dir": str(self.journal.root),
                "appends_since_compact": self.journal.appends_since_compact,
                "replayed_events": self.journal.replayed_events,
                "torn_lines": self.journal.torn_lines,
                "recovery": self.recovery,
            }
        payload.update(extra)
        return payload

    async def _healthz(self, request: web.Request) -> web.Response:
        payload = self.health()
        status = 200 if payload.get("status") == "ok" else 503
        return web.json_response(payload, status=status)


async def serve(settings: Settings | None = None, host: str | None = None,
                port: int | None = None) -> None:
    """Run a hive until SIGTERM/SIGINT (tools/hive_serve.py and
    `python -m chiaswarm_tpu.hive_server`). With `hive_standby_of` /
    CHIASWARM_HIVE_STANDBY_OF set, runs as a WAL-shipped STANDBY of that
    primary instead: replicating, health-checking, and self-promoting
    after `hive_failover_grace_s` of primary silence."""
    import signal

    settings = settings or load_settings()
    standby_of = str(getattr(settings, "hive_standby_of", "") or "")
    if standby_of:
        from .replication import StandbyHive

        server = await StandbyHive(
            settings, primary_uri=standby_of, host=host, port=port).start()
        print(f"hive STANDBY on {server.uri} replicating from {standby_of} "
              f"(auto-promotes after "
              f"{getattr(settings, 'hive_failover_grace_s', 10.0)}s of "
              "primary silence)")
    else:
        server = await HiveServer(settings, host=host, port=port).start()
        print(f"hive coordinator listening on {server.uri} "
              f"(workers poll {server.api_uri}/work)")
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except (NotImplementedError, RuntimeError, ValueError):
            pass
    try:
        await stop.wait()
    finally:
        await server.stop()
