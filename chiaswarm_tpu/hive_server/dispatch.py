"""Residency-aware dispatch: place jobs where the weights are warm.

chips/allocator.py routes work items to the chip slice whose HBM already
holds the model (affinity / cold / steal). This module is the same
policy one level up — across WORKERS instead of slices — using only what
each worker volunteers in its /work query: `resident_models` (the
registry's warm set), `chips`/`hbm_gb`, live load, and the
`unconverted_families` honesty key. SwiftDiffusion (arXiv 2407.02031)
and LegoDiffusion (arXiv 2604.08123) both put the next serving win
exactly here: a request placed on a cold worker pays the full weight
load + compile; placed on the warm one it pays neither.

Outcomes (counted in `swarm_hive_dispatch_total{outcome}`):

- affinity  the polling worker already holds the job's model;
- cold      no live worker holds it — whoever polls first loads it;
- steal     a warm worker exists but the job has waited past
            `affinity_hold_s`, so the cold poller takes it rather than
            letting latency pile up behind a busy home;
- hold      the job was SKIPPED this poll (a warm worker is live and the
            hold window hasn't lapsed) — deferred, not dispatched.
"""

from __future__ import annotations

import dataclasses

from .. import telemetry
from ..batching import placement_model
from .clock import CLOCK
from .queue import JobRecord, PriorityJobQueue

_DISPATCH = telemetry.counter(
    "swarm_hive_dispatch_total",
    "Hive /work dispatch decisions by placement outcome "
    "(affinity | cold | steal | hold)",
    ("outcome",),
)
_WORKERS_LIVE = telemetry.gauge(
    "swarm_hive_workers_live",
    "Distinct workers seen polling within the liveness window")


def _split_csv(value: str | None) -> frozenset[str]:
    return frozenset(
        part.strip() for part in (value or "").split(",") if part.strip())


def _to_int(value, default: int = 0) -> int:
    try:
        return int(float(value))
    except (TypeError, ValueError):
        return default


@dataclasses.dataclass
class WorkerInfo:
    """One worker's latest self-advertisement, parsed from /work query
    params (everything arrives stringified — hive.py ask_for_work)."""

    name: str
    version: str = ""
    resident: frozenset[str] = frozenset()
    unconverted: frozenset[str] = frozenset()
    chips: int = 0
    hbm_gb: int = 0
    slices: int = 1
    busy_slices: int = 0
    queue_depth: int = 0
    last_seen: float = 0.0

    @property
    def free_slices(self) -> int:
        return max(self.slices - self.busy_slices, 0)

    def can_run(self, model: str | None) -> bool:
        """Capability gate from the honesty key: never hand a worker a
        model family it advertised as unconverted (it can only fail)."""
        if not model:
            return True
        lowered = model.lower()
        return not any(k and k in lowered for k in self.unconverted)

    def snapshot(self) -> dict:
        return {
            "name": self.name,
            "version": self.version,
            "chips": self.chips,
            "hbm_gb": self.hbm_gb,
            "slices": self.slices,
            "busy_slices": self.busy_slices,
            "queue_depth": self.queue_depth,
            "resident_models": sorted(self.resident),
        }


class WorkerDirectory:
    """Who is alive and what is warm where. Entries refresh on every
    /work poll and age out after `ttl_s` — a dead worker's stale
    residency claim must not hold jobs hostage (see live_holders)."""

    def __init__(self, ttl_s: float):
        self.ttl_s = max(float(ttl_s), 0.0)
        self._workers: dict[str, WorkerInfo] = {}

    def observe(self, query: dict) -> WorkerInfo:
        name = str(query.get("worker_name") or "anonymous")
        info = WorkerInfo(
            name=name,
            version=str(query.get("worker_version", "")),
            resident=_split_csv(query.get("resident_models")),
            # keywords are substring-matched against model.lower() in
            # can_run — lowercase them here or a capitalized keyword
            # fails open and the job dispatches to a worker that can
            # only fail it
            unconverted=_split_csv(
                (query.get("unconverted_families") or "").lower()),
            chips=_to_int(query.get("chips")),
            hbm_gb=_to_int(query.get("hbm_gb")),
            slices=max(_to_int(query.get("slices"), 1), 1),
            busy_slices=_to_int(query.get("busy_slices")),
            queue_depth=_to_int(query.get("queue_depth")),
            last_seen=CLOCK.mono(),
        )
        self._workers[name] = info
        # drop aged-out entries here rather than letting the dict grow
        # with every worker name ever seen (ephemeral/autoscaled fleets
        # register a fresh name per restart) — live() then scans only
        # names that could actually matter
        cutoff = CLOCK.mono() - self.ttl_s
        for stale in [n for n, w in self._workers.items()
                      if w.last_seen < cutoff]:
            del self._workers[stale]
        _WORKERS_LIVE.set(len(self.live()))
        return info

    def live(self) -> list[WorkerInfo]:
        cutoff = CLOCK.mono() - self.ttl_s
        return [w for w in self._workers.values() if w.last_seen >= cutoff]

    def live_holders(self, model: str | None,
                     exclude: str | None = None) -> list[WorkerInfo]:
        """Live workers (other than `exclude`) advertising `model` warm."""
        if not model:
            return []
        return [
            w for w in self.live()
            if w.name != exclude and model in w.resident
        ]

    def snapshot(self) -> list[dict]:
        return [w.snapshot() for w in sorted(
            self.live(), key=lambda w: w.name)]


class Dispatcher:
    """The placement decision for one /work poll."""

    def __init__(self, directory: WorkerDirectory, affinity_hold_s: float,
                 max_jobs_per_poll: int):
        self.directory = directory
        self.affinity_hold_s = max(float(affinity_hold_s), 0.0)
        self.max_jobs_per_poll = max(int(max_jobs_per_poll), 1)

    def _budget(self, worker: WorkerInfo) -> int:
        """Jobs to hand this poll: the worker's advertised free capacity,
        capped by the per-poll knob. A worker already sitting on a local
        queue gets that counted against it — depth it reported is work
        it has not started — and one advertising no net capacity gets
        NOTHING: its poll is a heartbeat, and handing it a job anyway
        would bury it while an idle worker's next poll could have taken
        the job immediately. Workers that advertise no load fields at
        all default to slices=1/busy=0/depth=0, i.e. budget 1."""
        free = worker.free_slices - worker.queue_depth
        return max(0, min(self.max_jobs_per_poll, free))

    def unplaceable(self, record: JobRecord) -> bool:
        """True when every LIVE worker has declared itself incapable of
        the job's model family. Such a job is skipped by select() on
        every poll, so it never leases — and therefore never reaches the
        redelivery/failed machinery — while still counting against
        admission depth. The reaper parks it (see HiveServer._reap_loop)
        rather than letting it clog the queue forever. An empty
        directory is NOT unplaceable: with nobody polling, the job
        simply waits for a worker to arrive."""
        live = self.directory.live()
        if not live:
            return False
        model = placement_model(record.job)
        return all(not w.can_run(model) for w in live)

    def select(self, worker: WorkerInfo,
               queue: PriorityJobQueue) -> list[tuple[JobRecord, str]]:
        """Pick (record, outcome) pairs for this worker, class order
        first, residency second. Jobs a warm OTHER worker should take
        are held back ("hold") until `affinity_hold_s` lapses; jobs this
        worker cannot run at all (unconverted family) are skipped
        silently for it."""
        handed: list[tuple[JobRecord, str]] = []
        budget = self._budget(worker)
        now = CLOCK.mono()
        for record in queue.iter_queued():
            if len(handed) >= budget:
                break
            # placement_model maps tiny-flagged jobs to the stand-in
            # name the worker's registry (and therefore its advertised
            # resident_models) actually knows them by
            model = placement_model(record.job)
            if not worker.can_run(model):
                continue
            if model and model in worker.resident:
                outcome = "affinity"
            else:
                holders = self.directory.live_holders(model, exclude=worker.name)
                if not holders:
                    outcome = "cold"
                elif now - record.submitted_at >= self.affinity_hold_s:
                    outcome = "steal"
                else:
                    _DISPATCH.inc(outcome="hold")
                    # first hold only: the job's trace shows WHEN the
                    # affinity window started costing it latency without
                    # one event per skipped poll. Advisory until the next
                    # journaled transition carries the timeline forward.
                    if not any(e.get("event") == "hold"
                               for e in record.timeline):
                        # the queue's clock, not the module CLOCK: every
                        # other timeline stamp uses the injected clock,
                        # and mixing timebases would scramble the sorted
                        # trace under a test-injected wall clock
                        record.timeline.append({
                            "event": "hold", "wall": queue.clock.wall(),
                            "worker": worker.name,
                            "warm_on": sorted(h.name for h in holders)})
                    continue
            _DISPATCH.inc(outcome=outcome)
            handed.append((record, outcome))
        return handed
