"""Residency-aware dispatch: place jobs where the weights are warm.

chips/allocator.py routes work items to the chip slice whose HBM already
holds the model (affinity / cold / steal). This module is the same
policy one level up — across WORKERS instead of slices — using only what
each worker volunteers in its /work query: `resident_models` (the
registry's warm set), `chips`/`hbm_gb`, live load, and the
`unconverted_families` honesty key. SwiftDiffusion (arXiv 2407.02031)
and LegoDiffusion (arXiv 2604.08123) both put the next serving win
exactly here: a request placed on a cold worker pays the full weight
load + compile; placed on the warm one it pays neither.

Outcomes (counted in `swarm_hive_dispatch_total{outcome}`):

- affinity  the polling worker already holds the job's model;
- adapter_affinity  the polling worker holds the job's model AND
            advertises the job's adapter operands resident
            (`resident_adapters`, ISSUE 16) — the zero-upload placement;
            a model-warm poller WITHOUT the adapter defers (counted as
            `hold`) while an adapter-warm peer is live and the job is
            inside `affinity_hold_s`: operands prefer, never starve;
- cold      no live worker holds it — whoever polls first loads it;
- steal     a warm worker exists but the job has waited past
            `affinity_hold_s`, so the cold poller takes it rather than
            letting latency pile up behind a busy home;
- hold      the job was SKIPPED this poll (a warm worker is live and the
            hold window hasn't lapsed) — deferred, not dispatched;
- gang      the job rode along as a gang MEMBER behind a seed job with
            the same coalesce key (ISSUE 9): same-key queued batchmates
            leave in ONE /work reply, pre-batched, so the worker's
            linger window is no longer the only coalescing opportunity;
- straggler_hold  an INTERACTIVE job was withheld from a poller the
            fleet stats flag as a straggler (fleet.py) while a healthy
            capable worker is live — bounded by the same hold window as
            affinity, so stragglers degrade latency-sensitive placement,
            never availability.
- shard_hold  an INTERACTIVE job was withheld from a poller that cannot
            run it as one sharded multi-chip program (`shard_capable`,
            ISSUE 12) while a shard-capable worker is live — same hold
            window bound: geometry prefers, never starves.
- flap_hold a FRESH seed (never dispatched) was withheld from a poller
            whose leases have expired `hive_flap_threshold` consecutive
            times (ISSUE 18) while a healthy capable worker is live —
            same hold window bound, and one settled result clears the
            streak: flap detection prefers, never starves.

Gang scheduling: when the picked job is coalesce-compatible
(coalesce.py — the exact key the worker's BatchScheduler groups by) and
the worker advertised a per-slice row appetite (`gang_rows`, its
max_coalesce), the dispatcher pulls queued same-class same-key
batchmates up to min(advertised rows, hive_gang_max, per-poll job cap).
A gang is a dispatch-time grouping, not a new lifecycle: each member is
leased and journaled individually, redelivery may degrade it to
singles, and the only wire evidence is `trace.gang = {id, size, index}`
stamped into each member's trace context. The seed keeps its placement
outcome (so affinity still prefers the worker whose slice holds the
model — the whole gang follows the seed's placement), members count as
`gang`, and `swarm_hive_gang_size` histograms the grouping.
"""

from __future__ import annotations

import dataclasses
import math
import uuid

from .. import telemetry
from ..coalesce import (CHIP_STAGES, adapter_ref, canonical_adapter_ref,
                        job_rows, placement_model, stage_of)
from .clock import CLOCK
from .fleet import parse_stats
from .queue import JobRecord, PriorityJobQueue

_DISPATCH = telemetry.counter(
    "swarm_hive_dispatch_total",
    "Hive /work dispatch decisions by placement outcome "
    "(affinity | adapter_affinity | cold | steal | hold | gang | "
    "straggler_hold | shard_hold | flap_hold)",
    ("outcome",),
)
_GANG_SIZE = telemetry.histogram(
    "swarm_hive_gang_size",
    "Jobs per gang-scheduled /work group (observed once per gang; "
    "solo dispatches are not observed)",
    buckets=(2, 3, 4, 6, 8, 12, 16),
)
_WORKERS_LIVE = telemetry.gauge(
    "swarm_hive_workers_live",
    "Distinct workers seen polling within the liveness window")


def _split_csv(value: str | None) -> frozenset[str]:
    return frozenset(
        part.strip() for part in (value or "").split(",") if part.strip())


def _to_int(value, default: int = 0) -> int:
    try:
        return int(float(value))
    except (TypeError, ValueError):
        return default


@dataclasses.dataclass
class WorkerInfo:
    """One worker's latest self-advertisement, parsed from /work query
    params (everything arrives stringified — hive.py ask_for_work)."""

    name: str
    version: str = ""
    resident: frozenset[str] = frozenset()
    unconverted: frozenset[str] = frozenset()
    chips: int = 0
    hbm_gb: int = 0
    slices: int = 1
    busy_slices: int = 0
    queue_depth: int = 0
    # per-slice coalescing appetite in image rows (the worker's
    # max_coalesce — a JOB cap, so multi-image jobs make this a
    # conservative under-estimate of the slice's true row capacity:
    # gangs under-fill rather than oversubscribe); 1 = no appetite
    gang_rows: int = 1
    # whether the poll advertised gang_rows at all: a gang-aware worker
    # also reports queue_depth in ROWS incl. executing (ISSUE 9); a
    # legacy poller keeps the pre-gang budget contract
    gang_aware: bool = False
    # per-stage EWMA stats blob from the `stats` poll param (fleet.py):
    # {stage: (ewma_seconds, samples)}; empty for legacy pollers
    stats: dict = dataclasses.field(default_factory=dict)
    # slice-geometry advertisement (ISSUE 12): chips one job slice spans,
    # and whether the worker runs interactive jobs as ONE sharded program
    # over them (shard_interactive on a multi-chip slice). The dispatcher
    # prefers a shard-capable worker for interactive seeds.
    chips_per_slice: int = 0
    shard_capable: bool = False
    # adapter-operand residency (ISSUE 16): canonical adapter refs whose
    # stacked device operands are warm on this worker (lora_operands.py)
    # — the dispatcher routes a repeat adapter gang back to them so the
    # steady state re-uploads nothing
    resident_adapters: frozenset[str] = frozenset()
    # preemption tolerance (ISSUE 18): the worker runs a chunked,
    # checkpoint-armed denoise and can rehydrate a checkpoint blob —
    # only these pollers get `resume` offers on redelivered jobs
    resume_capable: bool = False
    # stage-typed placement (ISSUE 20): the stage names this poller will
    # serve (`stages` csv param — a jax-free host advertises only the
    # CPU set). `stage_aware` records whether the param was present at
    # all: a legacy poller never sees stage-jobs, in either direction.
    stages: frozenset[str] = frozenset()
    stage_aware: bool = False
    last_seen: float = 0.0

    @property
    def free_slices(self) -> int:
        return max(self.slices - self.busy_slices, 0)

    def can_run(self, model: str | None) -> bool:
        """Capability gate from the honesty key: never hand a worker a
        model family it advertised as unconverted (it can only fail)."""
        if not model:
            return True
        lowered = model.lower()
        return not any(k and k in lowered for k in self.unconverted)

    def snapshot(self) -> dict:
        return {
            "name": self.name,
            "version": self.version,
            "chips": self.chips,
            "hbm_gb": self.hbm_gb,
            "slices": self.slices,
            "busy_slices": self.busy_slices,
            "queue_depth": self.queue_depth,
            "gang_rows": self.gang_rows,
            "chips_per_slice": self.chips_per_slice,
            "shard_capable": self.shard_capable,
            "resume_capable": self.resume_capable,
            "resident_models": sorted(self.resident),
            "resident_adapters": sorted(self.resident_adapters),
            "stages": sorted(self.stages),
        }


class WorkerDirectory:
    """Who is alive and what is warm where. Entries refresh on every
    /work poll and age out after `ttl_s` — a dead worker's stale
    residency claim must not hold jobs hostage (see live_holders)."""

    def __init__(self, ttl_s: float, fleet=None):
        self.ttl_s = max(float(ttl_s), 0.0)
        # FleetStats (fleet.py): fed the per-stage EWMA blobs workers
        # piggyback on their polls, pruned in lockstep with liveness so
        # a departed worker's stats can't skew the straggler medians
        self.fleet = fleet
        self._workers: dict[str, WorkerInfo] = {}

    def observe(self, query: dict) -> WorkerInfo:
        name = str(query.get("worker_name") or "anonymous")
        info = WorkerInfo(
            name=name,
            version=str(query.get("worker_version", "")),
            resident=_split_csv(query.get("resident_models")),
            # keywords are substring-matched against model.lower() in
            # can_run — lowercase them here or a capitalized keyword
            # fails open and the job dispatches to a worker that can
            # only fail it
            unconverted=_split_csv(
                (query.get("unconverted_families") or "").lower()),
            chips=_to_int(query.get("chips")),
            hbm_gb=_to_int(query.get("hbm_gb")),
            slices=max(_to_int(query.get("slices"), 1), 1),
            busy_slices=_to_int(query.get("busy_slices")),
            queue_depth=_to_int(query.get("queue_depth")),
            gang_rows=max(_to_int(query.get("gang_rows"), 1), 1),
            gang_aware="gang_rows" in query,
            stats=parse_stats(query.get("stats")),
            chips_per_slice=_to_int(query.get("chips_per_slice")),
            shard_capable=_to_int(query.get("shard_capable")) > 0,
            resident_adapters=_split_csv(query.get("resident_adapters")),
            resume_capable=_to_int(query.get("resume_capable")) > 0,
            stages=_split_csv(query.get("stages")),
            stage_aware="stages" in query,
            last_seen=CLOCK.mono(),
        )
        self._workers[name] = info
        # drop aged-out entries here rather than letting the dict grow
        # with every worker name ever seen (ephemeral/autoscaled fleets
        # register a fresh name per restart) — live() then scans only
        # names that could actually matter
        cutoff = CLOCK.mono() - self.ttl_s
        for stale in [n for n, w in self._workers.items()
                      if w.last_seen < cutoff]:
            del self._workers[stale]
            if self.fleet is not None:
                self.fleet.forget(stale)
        if self.fleet is not None:
            self.fleet.note(name, info.stats)
            self.fleet.refresh_metrics(self.live_names())
        _WORKERS_LIVE.set(len(self.live()))
        return info

    def live_names(self) -> list[str]:
        return [w.name for w in self.live()]

    def live(self) -> list[WorkerInfo]:
        cutoff = CLOCK.mono() - self.ttl_s
        return [w for w in self._workers.values() if w.last_seen >= cutoff]

    def live_holders(self, model: str | None,
                     exclude: str | None = None) -> list[WorkerInfo]:
        """Live workers (other than `exclude`) advertising `model` warm."""
        if not model:
            return []
        return [
            w for w in self.live()
            if w.name != exclude and model in w.resident
        ]

    def snapshot(self) -> list[dict]:
        return [w.snapshot() for w in sorted(
            self.live(), key=lambda w: w.name)]


class Dispatcher:
    """The placement decision for one /work poll."""

    def __init__(self, directory: WorkerDirectory, affinity_hold_s: float,
                 max_jobs_per_poll: int, gang_max: int = 8,
                 lora_slots: int = 8, flap_threshold: int = 0,
                 flapping_fn=None):
        self.directory = directory
        self.affinity_hold_s = max(float(affinity_hold_s), 0.0)
        self.max_jobs_per_poll = max(int(max_jobs_per_poll), 1)
        # flap detection (ISSUE 18): `flapping_fn` returns the worker
        # names whose leases have expired `flap_threshold` consecutive
        # times (LeaseTable.flapping) — derived live state, queried once
        # per select() call
        self.flap_threshold = max(int(flap_threshold), 0)
        self.flapping_fn = flapping_fn
        # most jobs one GANG may hold (Settings.hive_gang_max); <= 1
        # disables gang scheduling hive-side entirely
        self.gang_max = max(int(gang_max), 1)
        # most DISTINCT adapters one gang may carry (ISSUE 13,
        # Settings.lora_slots_max): the worker's stacked-factor program
        # has that many slots, so a gang past the cap would only fall
        # apart into solo fallbacks at the slice
        self.lora_slots = max(int(lora_slots), 1)

    def _budget(self, worker: WorkerInfo) -> tuple[int, int]:
        """(work items, image rows) to hand this poll.

        Gang-aware workers (they sent `gang_rows`): work items are
        slice-grained — each solo job or gang lands on ONE slice, so at
        most `free_slices` of them leave per poll — and rows are the
        worker's total advertised appetite (slices x gang_rows) minus
        `queue_depth`, which for these workers counts lingering + ready
        + EXECUTING rows (ISSUE 9), so a slice mid-coalesce is already
        accounted and a gang reply can't oversubscribe it.

        Legacy pollers (no `gang_rows`) keep the EXACT pre-gang
        contract: `free_slices - queue_depth` jobs, one row each —
        their depth excludes executing work (busy_slices covers it), and
        mixing it into the rows formula would hand a job to a worker
        whose free slice is already spoken for by a queued one. Either
        way a worker advertising no net capacity gets NOTHING: its poll
        is a heartbeat, and handing it work anyway would bury it while
        an idle worker's next poll could have taken the work
        immediately."""
        if not worker.gang_aware:
            free = max(worker.free_slices - worker.queue_depth, 0)
            return free, free
        per_slice = max(worker.gang_rows, 1)
        free_rows = max(worker.slices * per_slice - worker.queue_depth, 0)
        items = min(worker.free_slices, math.ceil(free_rows / per_slice))
        return max(items, 0), free_rows

    def unplaceable(self, record: JobRecord) -> bool:
        """True when every LIVE worker has declared itself incapable of
        the job's model family. Such a job is skipped by select() on
        every poll, so it never leases — and therefore never reaches the
        redelivery/failed machinery — while still counting against
        admission depth. The reaper parks it (see HiveServer._reap_loop)
        rather than letting it clog the queue forever. An empty
        directory is NOT unplaceable: with nobody polling, the job
        simply waits for a worker to arrive."""
        live = self.directory.live()
        if not live:
            return False
        model = placement_model(record.job)
        return all(not w.can_run(model) for w in live)

    def select(self, worker: WorkerInfo, queue: PriorityJobQueue
               ) -> list[tuple[JobRecord, str, dict | None]]:
        """Pick (record, outcome, gang) triples for this worker, class
        order first, residency second. Jobs a warm OTHER worker should
        take are held back ("hold") until `affinity_hold_s` lapses; jobs
        this worker cannot run at all (unconverted family) are skipped
        silently for it.

        When a picked SEED job is coalesce-compatible and the worker
        advertised gang capacity, its queued same-class same-key
        batchmates leave in the same reply as one gang — never split
        across the per-poll budget (the stamped gang size is exactly
        what this reply carries) and never pulled across priority
        classes (the peers index is per-class). `gang` is
        {id, size, index} for gang members, None for solo dispatches."""
        handed: list[tuple[JobRecord, str, dict | None]] = []
        items, free_rows = self._budget(worker)
        now = CLOCK.mono()
        taken: set[str] = set()
        # straggler + shard-capability view for this poll: ONE live
        # snapshot (directory.live() filters the whole map per call, so
        # per-record rebuilds would make select() O(jobs x workers))
        fleet = self.directory.fleet
        live = self.directory.live()
        live_names = [w.name for w in live]
        poller_is_straggler = (
            fleet is not None and fleet.is_outlier(worker.name, live_names))
        flapping: set[str] = set()
        if self.flap_threshold > 0 and self.flapping_fn is not None:
            flapping = set(self.flapping_fn() or ())
        for record in queue.iter_queued():
            if (items <= 0 or free_rows <= 0
                    or len(handed) >= self.max_jobs_per_poll):
                break
            if record.job_id in taken:
                continue  # already left as a gang member this reply
            # placement_model maps tiny-flagged jobs to the stand-in
            # name the worker's registry (and therefore its advertised
            # resident_models) actually knows them by
            model = placement_model(record.job)
            if not worker.can_run(model):
                continue
            stage = stage_of(record.job)
            if stage is not None and (
                    not worker.stage_aware or stage not in worker.stages
                    or (stage in CHIP_STAGES and worker.chips <= 0)):
                # stage-typed placement (ISSUE 20): a stage-job only
                # leaves with a poller that advertised its stage —
                # legacy pollers (no `stages` param) never see graph
                # work — and chip-path stages (denoise/upscale/video)
                # additionally require a chip host, whatever it claims
                continue
            cpu_stage = stage is not None and stage not in CHIP_STAGES
            if (not cpu_stage and poller_is_straggler
                    and record.job_class == "interactive"
                    and now - record.submitted_at < self.affinity_hold_s
                    and any(w.name != worker.name and w.can_run(model)
                            and not fleet.is_outlier(w.name, live_names)
                            for w in live)):
                # observability feeding placement: a latency-sensitive
                # seed is withheld from a fleet straggler while a
                # healthy capable worker is live — but only inside the
                # placement-hold window, so a fleet of stragglers (or a
                # healthy worker that stopped polling) degrades to the
                # slow dispatch, never to starvation
                _DISPATCH.inc(outcome="straggler_hold")
                continue
            if (worker.name in flapping
                    and record.attempts == 0
                    and now - record.submitted_at < self.affinity_hold_s
                    and any(w.name != worker.name and w.can_run(model)
                            and w.name not in flapping
                            for w in live)):
                # flap detection (ISSUE 18): a worker losing lease after
                # lease is probably dying repeatedly (OOM loop, flaky
                # host) — withhold FRESH seeds from it while a healthy
                # capable worker is live, inside the same hold window as
                # every other preference. Redeliveries are exempt (they
                # already waited a full deadline), and a settled result
                # resets the streak: flapping degrades placement, never
                # availability.
                _DISPATCH.inc(outcome="flap_hold")
                continue
            if (not cpu_stage
                    and record.job_class == "interactive"
                    and not worker.shard_capable
                    and now - record.submitted_at < self.affinity_hold_s
                    and any(w.name != worker.name and w.shard_capable
                            and w.can_run(model)
                            and (fleet is None or not fleet.is_outlier(
                                w.name, live_names))
                            for w in live)):
                # slice-geometry preference (ISSUE 12): an interactive
                # seed waits (inside the same hold window as affinity)
                # for a worker that will fan the single image over every
                # chip of its slice — the sharded pass is the latency
                # win the class exists for. Bounded exactly like
                # affinity/straggler holds: once the window lapses, or
                # when no shard-capable worker is live, any poller takes
                # it — geometry prefers, never starves. A straggler-
                # flagged shard-capable worker does NOT count as a
                # target: straggler_hold withholds the seed from it, so
                # counting it here would make the two rules defer to
                # each other and park the seed for the whole window.
                _DISPATCH.inc(outcome="shard_hold")
                continue
            if cpu_stage:
                # host-path stages (encode/decode/postprocess) have no
                # warm-weight economics: no affinity hold applies, the
                # first capable poller drains them immediately — which
                # is exactly what lets a jax-free encode host keep the
                # chip fleet fed without ever touching a chip itself
                outcome = "cold"
            elif model and model in worker.resident:
                aref = canonical_adapter_ref(record.job)
                if aref is not None and aref in worker.resident_adapters:
                    # model AND stacked adapter operands warm here: the
                    # zero-upload placement (ISSUE 16). Gang riders
                    # follow the seed as ever, so the whole repeat gang
                    # lands where its operand cache entry lives.
                    outcome = "adapter_affinity"
                elif (aref is not None
                        and now - record.submitted_at < self.affinity_hold_s
                        and any(aref in w.resident_adapters
                                for w in self.directory.live_holders(
                                    model, exclude=worker.name))):
                    # model warm here but the adapter's operands are warm
                    # on ANOTHER model-warm worker: defer inside the same
                    # hold window affinity uses. Operand residency
                    # PREFERS, never starves — once the window lapses (or
                    # the operand-warm peer goes dark) this poller takes
                    # the job as plain affinity.
                    _DISPATCH.inc(outcome="hold")
                    continue
                else:
                    outcome = "affinity"
            else:
                holders = self.directory.live_holders(model, exclude=worker.name)
                if not holders:
                    outcome = "cold"
                elif now - record.submitted_at >= self.affinity_hold_s:
                    outcome = "steal"
                else:
                    _DISPATCH.inc(outcome="hold")
                    # first hold only: the job's trace shows WHEN the
                    # affinity window started costing it latency without
                    # one event per skipped poll. Advisory until the next
                    # journaled transition carries the timeline forward.
                    # Held seeds hold their whole gang implicitly: the
                    # peers stay queued for the warm worker's next poll —
                    # affinity places the GANG, not just the seed.
                    if not any(e.get("event") == "hold"
                               for e in record.timeline):
                        # the queue's clock, not the module CLOCK: every
                        # other timeline stamp uses the injected clock,
                        # and mixing timebases would scramble the sorted
                        # trace under a test-injected wall clock
                        record.timeline.append({
                            "event": "hold", "wall": queue.clock.wall(),
                            "worker": worker.name,
                            "warm_on": sorted(h.name for h in holders)})
                    continue
            members = [record]
            # a legacy poller's budget is in JOBS (its depth never knew
            # rows); only gang-aware workers get row-denominated math —
            # a 4-image job must not eat 4 of a legacy worker's job slots
            rows = job_rows(record.job) if worker.gang_aware else 1
            if (record.coalesce is not None and self.gang_max > 1
                    and worker.gang_rows > 1):
                # one gang = one slice pass: its rows must fit the
                # per-slice appetite AND the poll's remaining row budget
                cap_jobs = min(self.gang_max,
                               self.max_jobs_per_poll - len(handed))
                cap_rows = min(worker.gang_rows, free_rows)
                # adapter-aware gangs (ISSUE 13): mixed-adapter members
                # share one pass as stacked per-row deltas, capped at
                # lora_slots DISTINCT adapters (the worker program's
                # factor-slot dimension)
                adapters = {a for a in (adapter_ref(record.job),)
                            if a is not None}
                for peer in queue.queued_peers(record):
                    if len(members) >= cap_jobs:
                        break
                    if peer.job_id in taken:
                        # already left with an EARLIER gang this reply;
                        # it stays queue-live until app.py takes it
                        # after select() returns, so the index alone
                        # cannot know
                        continue
                    peer_rows = job_rows(peer.job)
                    if rows + peer_rows > cap_rows:
                        # stop rather than skip ahead: pulling a later
                        # smaller peer over this one would reorder the
                        # class FIFO
                        break
                    peer_adapter = adapter_ref(peer.job)
                    if (peer_adapter is not None
                            and peer_adapter not in adapters
                            and len(adapters) >= self.lora_slots):
                        # same stop-don't-skip rule as rows: a later
                        # same-adapter peer must not overtake this one
                        break
                    members.append(peer)
                    rows += peer_rows
                    if peer_adapter is not None:
                        adapters.add(peer_adapter)
            items -= 1
            free_rows -= rows
            taken.update(m.job_id for m in members)
            if len(members) > 1:
                gang_id = uuid.uuid4().hex[:12]
                _GANG_SIZE.observe(len(members))
                for i, member in enumerate(members):
                    # the seed keeps its placement outcome; riders are
                    # the gang win the counter exists to measure
                    member_outcome = outcome if i == 0 else "gang"
                    _DISPATCH.inc(outcome=member_outcome)
                    handed.append((member, member_outcome, {
                        "id": gang_id, "size": len(members), "index": i}))
            else:
                _DISPATCH.inc(outcome=outcome)
                handed.append((record, outcome, None))
        return handed
