"""Worker runtime: poll the hive, fan jobs out to chip slices, upload results.

Loop-shape parity with reference swarm/worker.py:38-196 — 11 s poll cadence,
121 s backoff on poll errors, bounded work queue, per-slice consumer tasks,
a result-upload task, and the same error policy (transient exceptions become
error-image artifacts and the job "succeeds"; ValueError/TypeError mark the
envelope `fatal_error` so the hive won't resubmit; bad input args take the
fatal path before execution, swarm/worker.py:105-115).

Differences by design:
- `Worker` is a class with injected settings/allocator, so tests run it
  against an in-process fake hive (the reference used module globals and was
  untestable without a live hive).
- The GPU semaphore is replaced by the SliceAllocator; capability
  advertisement aggregates the whole pool (fixing swarm/worker.py:45-62
  which advertised only the last device).
- Jobs execute in a thread pool sized to the slice count, so one slice's
  denoise loop never blocks another slice's or the event loop.
- Between the poll loop and the slice workers sits a BatchScheduler
  (batching.py): compatible txt2img/img2img jobs for the same model and
  shape bucket coalesce — after a short linger window — into ONE padded
  denoise+decode pass per slice, each job keeping its own id, seed, and
  result envelope. Anything the batched program can't express dispatches
  solo, exactly as before. Jobs that arrive pre-batched from a
  gang-scheduling hive (trace.gang on the /work reply, ISSUE 9) skip
  the linger window entirely and flush as one group immediately.
- Released work items land on the scheduler's dispatch board and are
  matched to slices by MODEL RESIDENCY (batching.BatchScheduler.claim +
  chips/allocator residency map): groups route to the slice whose HBM
  and program cache are already warm (affinity), first loads prefer
  unclaimed slices (cold), and an idle slice steals a busy home's group
  rather than idling (cross-slice batch stealing). Outcomes land in
  swarm_placement_total and each envelope's pipeline_config.placement.
- The job lifecycle is fault-tolerant end to end: result envelopes go
  through a durable disk outbox (outbox.py — spooled before upload,
  retried with backoff, redelivered after a restart, unlinked only on
  hive ACK), a per-pass watchdog deadline quarantines-and-probes a slice
  whose execution hangs instead of pinning it forever, SIGTERM drains
  (finish in-flight slices, flush the outbox) instead of cancelling
  mid-denoise, and every failure path is deterministically injectable
  via faults.py.
"""

from __future__ import annotations

import asyncio
import base64
import json
import logging
import os
import random
import signal
import time
from concurrent.futures import ThreadPoolExecutor

from . import __version__, faults, telemetry
from . import cancel as cancel_mod
from . import outbox as outbox_mod
from .batching import BatchScheduler
from .cancel import JobCancelled
from .chips.allocator import SliceAllocator
from .faults import FaultInjected
from .hive import HiveClient, HiveError, hive_endpoints
from .job_arguments import format_args
from .log_setup import setup_logging
from .outbox import Outbox, OutboxEntry
from .post_processors.output_processor import (
    exception_image,
    exception_message,
    fatal_exception_response,
)
from .settings import Settings, load_settings, resolve_path
from .telemetry import observe_stage, trace_job

logger = logging.getLogger(__name__)

# reference cadence is 11 s; the env knob exists for worker SUBPROCESSES
# driven by the bench/e2e harness, which cannot monkeypatch the module
# the way the in-process tests do


def _env_poll_seconds() -> float:
    raw = os.environ.get("CHIASWARM_POLL_SECONDS", "")
    try:
        value = float(raw)
    except ValueError:
        if raw:
            logger.warning(
                "CHIASWARM_POLL_SECONDS=%r is not a number; using 11", raw)
        return 11.0
    if value <= 0:  # a zero/negative cadence would busy-loop the hive
        logger.warning(
            "CHIASWARM_POLL_SECONDS=%r must be positive; using 11", raw)
        return 11.0
    return value


POLL_SECONDS = _env_poll_seconds()
ERROR_BACKOFF_SECONDS = 121


def _next_backoff(prev: float) -> float:
    """Poll-error backoff with decorrelated jitter (sleep ~ U(cadence,
    3*prev), capped): repeated failures walk up toward the cap instead of
    hammering the hive at the 11 s cadence, and a fleet that lost the
    hive together does not re-poll in lockstep when it returns."""
    base = float(POLL_SECONDS)
    prev = max(float(prev), base)
    return min(float(ERROR_BACKOFF_SECONDS), random.uniform(base, prev * 3))

_JOBS_POLLED = telemetry.counter(
    "swarm_jobs_polled_total", "Jobs received from hive /work polls")
_POLL_ERRORS = telemetry.counter(
    "swarm_poll_errors_total", "ask_for_work calls that raised")
_JOBS_COMPLETED = telemetry.counter(
    "swarm_jobs_completed_total",
    "Result envelopes produced, by outcome (ok | error | fatal)",
    ("outcome",),
)
_LAST_POLL = telemetry.gauge(
    "swarm_last_poll_unixtime",
    "Wall-clock time of the last successful hive poll")
_SLICES_TOTAL = telemetry.gauge(
    "swarm_slices_total", "Chip slices this worker serves jobs on")
_SLICES_BUSY = telemetry.gauge(
    "swarm_slices_busy", "Chip slices currently executing a job")
_JOBS_IN_FLIGHT = telemetry.gauge(
    "swarm_jobs_in_flight",
    "Jobs accepted from the hive and not yet uploaded")
_QUEUE_DEPTH = telemetry.gauge(
    "swarm_queue_depth",
    "Jobs per internal queue (lingering = open coalescing groups, "
    "ready = released to slice workers, results = awaiting upload)",
    ("queue",),
)
_WATCHDOG_EXPIRED = telemetry.counter(
    "swarm_watchdog_expired_total",
    "Jobs whose execution exceeded the slice watchdog deadline",
    ("kind",),
)
_WATCHDOG_PROBES = telemetry.counter(
    "swarm_watchdog_probe_total",
    "Quarantined-slice smoke probes, by outcome (ok | failed | wedged)",
    ("outcome",),
)
_SLICE_STATE = telemetry.gauge(
    "swarm_slice_state",
    "Chip slices by lifecycle state (active | quarantined)",
    ("state",),
)
_CHECKPOINTS = telemetry.counter(
    "swarm_checkpoints_total",
    "Mid-pass checkpoints cut at denoise chunk boundaries, by outcome "
    "(shipped = the hive stored it; oversize = bigger than "
    "checkpoint_max_bytes, skipped; error = pack or upload failed)",
    ("outcome",),
)
_PREVIEWS = telemetry.counter(
    "swarm_previews_total",
    "Progressive preview frames decoded at denoise chunk boundaries, "
    "by outcome (shipped | error)",
    ("outcome",),
)
_RESUMES = telemetry.counter(
    "swarm_resume_total",
    "Redelivered jobs that arrived with a resume offer, by outcome "
    "(resumed = checkpoint fetched+unpacked and handed to the pipeline; "
    "fetch_failed | unpack_failed degrade to a full pass)",
    ("outcome",),
)
_JOBS_CANCELLED = telemetry.counter(
    "swarm_jobs_cancelled_total",
    "Hive-revoked jobs this worker dropped, by where the cancel caught "
    "them (held = still lingering/on the dispatch board, no envelope "
    "ever produced; executing = aborted or row-dropped mid-denoise at a "
    "chunk boundary; unknown = already delivered or never held)",
    ("stage",),
)


def _deadline_cap_of(job: dict) -> float:
    """The job's own watchdog cap from its `deadline_s` field; 0 = none.
    `deadline_s` is submitter-controlled and forwarded un-validated by
    the hive (its own TTL parse is just as tolerant), so garbage must
    degrade to "no cap", never kill the slice worker task."""
    try:
        cap = float(job.get("deadline_s") or 0.0)
    except (TypeError, ValueError):
        return 0.0
    return cap if cap > 0 else 0.0


class Worker:
    def __init__(
        self,
        settings: Settings | None = None,
        allocator: SliceAllocator | None = None,
        hive_uri: str | None = None,
    ):
        self.settings = settings or load_settings()
        # hive_uri (str or list) pins the endpoints explicitly (tests,
        # LocalSwarm); otherwise Settings decides — sdaas_uris names the
        # primary+standby set for client-side failover, sdaas_uri the
        # classic single hive
        self.hive_uri = (
            hive_uri if hive_uri is not None
            else hive_endpoints(self.settings))
        if isinstance(self.hive_uri, list) and len(self.hive_uri) == 1:
            self.hive_uri = self.hive_uri[0]
        self.allocator = allocator or SliceAllocator(
            chips_per_job=self.settings.chips_per_job,
            tensor_parallelism=self.settings.tensor_parallelism,
            sequence_parallelism=self.settings.sequence_parallelism,
        )
        self.hive = HiveClient(self.settings, self.hive_uri)
        coalesce = max(int(getattr(self.settings, "max_coalesce", 8)), 1)
        self.batcher = BatchScheduler(
            linger_s=float(getattr(self.settings, "batch_linger_ms", 50.0))
            / 1000.0,
            max_coalesce=coalesce,
            # released (ready) work keeps the round-5 work-queue bound, so
            # unbatchable traffic never hoards jobs other workers could
            # take; only jobs lingering toward a coalesced pass get the
            # extra in-flight allowance
            maxsize=len(self.allocator) * coalesce,
            ready_maxsize=len(self.allocator),
            rows_limit=self._coalesce_rows_limit,
            # interactive preemption probe: other lingering groups flush
            # when an interactive dispatch finds slices contended
            free_slices=lambda: self.allocator.free_count,
            # distinct-adapter cap per coalesced group (ISSUE 13) — the
            # stacked-factor slot dimension run_batched enforces
            lora_slots=int(getattr(self.settings, "lora_slots_max", 8) or 8),
        )
        # a slice returning to the free pool re-runs the placement match,
        # so a board entry blocked on "no slice free" dispatches the
        # moment release()/reinstate() happens
        self.allocator.add_free_listener(self.batcher.notify)
        self.result_queue: asyncio.Queue = asyncio.Queue()
        # durable result spool: envelopes land here BEFORE the first
        # upload attempt and are unlinked only on hive ACK (outbox.py)
        self.outbox = Outbox(
            resolve_path(getattr(self.settings, "outbox_dir", "outbox")),
            max_entries=int(getattr(self.settings, "outbox_max_entries", 512)),
        )
        if getattr(self.settings, "fault_injection", ""):
            faults.configure(self.settings.fault_injection)
        self._executor = ThreadPoolExecutor(
            max_workers=len(self.allocator), thread_name_prefix="chipslice"
        )
        self._stopping = asyncio.Event()
        self._draining = asyncio.Event()
        self._probe_tasks: set[asyncio.Task] = set()
        self._delivering = 0  # entries popped from result_queue, not yet acked
        # job ids currently claimed by a slice (the cancel router's
        # "executing" test: a hive revocation for one of these marks the
        # process-wide cancel registry the chunked denoise probes)
        self._executing_ids: set[str] = set()
        # host-path stage lane (ISSUE 20): encode/decode/postprocess
        # stage-jobs bypass the BatchScheduler and the slice allocator —
        # they run on the default executor, so the decode of pass N
        # overlaps the denoise of pass N+1 instead of holding its slice
        self._stage_queue: asyncio.Queue = asyncio.Queue()
        self._stage_inflight = 0
        self._stage_queued_ids: set[str] = set()
        self._stage_cancelled: set[str] = set()
        self._metrics_runner = None
        self._profiling = False  # one on-demand profiler capture at a time
        # per-stage EWMA of this worker's OWN envelope stage timings
        # (stage -> [ewma_seconds, samples]), piggybacked on every /work
        # poll as the `stats` query param so the hive's fleet view
        # (hive_server/fleet.py) can spot a straggler slice that looks
        # healthy in isolation. Per-instance state, fed from the settled
        # envelopes in _finish_result — deliberately NOT the process-
        # global stage histogram, so in-process multi-worker harnesses
        # report per-worker truth.
        self._stage_stats: dict[str, list] = {}
        self._stats_alpha = min(max(float(getattr(
            self.settings, "hive_stats_ewma_alpha", 0.2) or 0.2), 0.01), 1.0)
        # monotonic time of the last SUCCESSFUL hive poll (healthz age)
        self._last_poll_monotonic: float | None = None
        self._poll_backoff_s = float(POLL_SECONDS)

    # --- lifecycle ---

    async def run(self) -> None:
        self.startup()
        await self._start_metrics_server()
        loop = asyncio.get_running_loop()
        sigterm_installed = False
        try:
            # rolling restarts send SIGTERM: drain instead of dropping work
            loop.add_signal_handler(signal.SIGTERM, self.stop, True)
            sigterm_installed = True
        except (NotImplementedError, RuntimeError, ValueError):
            pass  # non-unix / nested loop: stop(drain=True) still works
        # redeliver envelopes a previous process spooled but never got
        # ACKed (outbox contract: at-least-once across restarts)
        recovered = self.outbox.recover()
        for entry in recovered:
            self.result_queue.put_nowait(entry)
        if recovered:
            logger.warning(
                "outbox: redelivering %d spooled result(s) from a previous run",
                len(recovered))
        tasks = [
            asyncio.create_task(self.slice_worker(), name=f"slice_worker_{i}")
            for i in range(len(self.allocator))
        ]
        for i in range(int(getattr(self.settings, "stage_workers", 2) or 0)):
            tasks.append(asyncio.create_task(
                self.stage_worker(), name=f"stage_worker_{i}"))
        tasks.append(asyncio.create_task(self.result_worker(), name="result_worker"))
        tasks.append(asyncio.create_task(self.poll_loop(), name="poll_loop"))
        tasks.append(asyncio.create_task(self._drain_watcher(), name="drain_watcher"))
        try:
            await self._stopping.wait()
        finally:
            if sigterm_installed:
                try:
                    loop.remove_signal_handler(signal.SIGTERM)
                except (NotImplementedError, RuntimeError, ValueError):
                    pass
            for t in [*tasks, *self._probe_tasks]:
                t.cancel()
            await asyncio.gather(
                *tasks, *self._probe_tasks, return_exceptions=True)
            await self.hive.close()
            if self._metrics_runner is not None:
                await self._metrics_runner.cleanup()
                self._metrics_runner = None
            self._executor.shutdown(wait=False, cancel_futures=True)

    def stop(self, drain: bool = False) -> None:
        """Stop the worker. drain=False (default) cancels immediately —
        spooled envelopes survive on disk for the next start. drain=True
        (the SIGTERM path) stops polling, finishes in-flight slices, and
        flushes the outbox up to Settings.drain_deadline_s first, so a
        rolling restart loses zero work."""
        if drain:
            self._draining.set()
        else:
            self._stopping.set()

    async def _drain_watcher(self) -> None:
        await self._draining.wait()
        deadline = time.monotonic() + max(
            float(getattr(self.settings, "drain_deadline_s", 120.0)), 0.0)
        logger.warning(
            "drain: polls stopped; flushing %d in-flight job(s) and the outbox",
            self.batcher.outstanding_jobs)
        # lingering coalescing groups dispatch now; nothing new lingers
        self.batcher.close()
        while time.monotonic() < deadline:
            # deliverable work = executing jobs + queued/in-flight uploads;
            # NOT outbox.depth, which also counts parked (permanently
            # refused) envelopes that only a restart may retry
            if (self.batcher.outstanding_jobs == 0
                    and self._stage_queue.qsize() == 0
                    and self._stage_inflight == 0
                    and self.result_queue.qsize() == 0
                    and self._delivering == 0):
                logger.warning("drain complete: no in-flight work remains")
                break
            await asyncio.sleep(0.05)
        else:
            logger.error(
                "drain deadline hit with %d job(s) in flight and %d spooled "
                "envelope(s); exiting — spooled results redeliver on restart",
                self.batcher.outstanding_jobs, self.outbox.depth)
        self._stopping.set()

    def startup(self) -> None:
        setup_logging(
            resolve_path(self.settings.log_filename),
            self.settings.log_level,
            getattr(self.settings, "log_format", "plain"),
        )
        logger.info("chiaSWARM-TPU worker %s", __version__)
        caps = self.allocator.capabilities()
        print(
            f"Found {caps['chips']} chips ({caps['topology']}), "
            f"{len(self.allocator)} job slice(s)"
        )
        _SLICES_TOTAL.set(len(self.allocator))
        self._enable_compilation_cache()
        self._start_profiler_server()

    async def _start_metrics_server(self) -> None:
        """Local telemetry endpoint (telemetry.py): GET /metrics in
        Prometheus text format, GET /healthz with last-poll age, resident
        models, and per-slice busy state. Sits next to the jax.profiler
        server; Settings.metrics_port / CHIASWARM_METRICS_PORT picks the
        port, 0 disables. Never fatal — a busy port costs the scrape, not
        the worker."""
        port = int(getattr(self.settings, "metrics_port", 0) or 0)
        if not port:
            return
        try:
            from . import memory_census, programs
            from .telemetry import start_metrics_server

            self._metrics_runner = await start_metrics_server(
                port,
                health=self._health,
                host=getattr(self.settings, "metrics_host", "127.0.0.1"),
                profile=self._capture_profile,
                # the profile hook mutates; it requires the same bearer
                # token the worker itself is provisioned with
                token=str(getattr(self.settings, "sdaas_token", "")),
                # ISSUE 17 cost plane: the compiled-program ledger and the
                # fleet byte census, both read-only snapshots
                programs=programs.snapshot,
                memory=memory_census.census,
            )
            logger.info("metrics server on :%d", port)
        except Exception as e:  # observability is an add-on, never fatal
            logger.warning("metrics server unavailable: %s", e)

    async def _capture_profile(self, seconds: float) -> dict:
        """On-demand jax.profiler capture (POST /debug/profile?seconds=N
        on the metrics app): traces this process for `seconds` and writes
        a perfetto/TensorBoard trace bundle under $SDAAS_ROOT/profiles/.
        Gated by Settings.profiler_capture (off by default — a profile
        exposes prompts and timings, so arming it is an operator
        decision), and serialized: jax keeps one global tracer, so a
        second concurrent capture answers 409 instead of corrupting the
        first."""
        if not bool(getattr(self.settings, "profiler_capture", False)):
            raise PermissionError(
                "profiler capture is disabled; set profiler_capture=true "
                "(CHIASWARM_PROFILER_CAPTURE=1) to arm it")
        if self._profiling:
            raise RuntimeError("a profiler capture is already running")
        import jax.profiler

        # nanosecond suffix: two captures starting in the same wall-clock
        # second must not interleave their bundles in one directory
        out_dir = resolve_path("profiles") / (
            time.strftime("trace_%Y%m%d_%H%M%S")
            + f"_{time.time_ns() % 1_000_000_000:09d}")
        self._profiling = True
        try:
            def run() -> None:
                with jax.profiler.trace(str(out_dir)):
                    time.sleep(seconds)

            # off-loop: the capture sleeps for the whole window and the
            # metrics app must keep answering scrapes meanwhile
            await asyncio.get_running_loop().run_in_executor(None, run)
        finally:
            self._profiling = False
        logger.warning("profiler capture (%.1fs) written under %s",
                       seconds, out_dir)
        return {"path": str(out_dir), "seconds": seconds}

    def _health(self) -> dict:
        """/healthz snapshot: is this worker polling, what is resident,
        which slices serve. Reports `degraded` (telemetry.py answers 503)
        when polling has stalled, a slice is quarantined, or the outbox is
        saturated — so an orchestrator can act instead of trusting an
        unconditional "ok"."""
        from .registry import resident_models

        age = None
        if self._last_poll_monotonic is not None:
            age = round(time.monotonic() - self._last_poll_monotonic, 1)
        reasons = []
        # a stale poll only means trouble when the worker SHOULD be
        # polling — the loop intentionally pauses while draining, while
        # every slice is busy, or while the batcher is full, and a worker
        # mid-denoise must not probe as unhealthy
        expects_polls = (not self._draining.is_set()
                         and self.allocator.has_free_slice()
                         and not self.batcher.full())
        if expects_polls and age is not None and age > 3 * POLL_SECONDS:
            reasons.append(
                f"last successful poll {age:.0f}s ago "
                f"(cadence {POLL_SECONDS}s)")
        quarantined = self.allocator.quarantined_count
        if quarantined:
            reasons.append(f"{quarantined} slice(s) quarantined")
        if self.outbox.saturated:
            reasons.append(
                f"outbox saturated ({self.outbox.depth} spooled envelopes)")
        # ISSUE 17: HBM squeeze probe. Opt-in (threshold 0 = off) because
        # a healthy steady state legitimately keeps HBM near-full on some
        # fleets; CPU smoke reports no bytes_limit -> headroom None ->
        # never fires
        headroom = None
        threshold = float(
            getattr(self.settings, "memory_headroom_degraded", 0.0) or 0.0)
        if threshold > 0:
            from . import memory_census

            headroom = memory_census.device_headroom()
            if headroom is not None and headroom < threshold:
                reasons.append(
                    f"device HBM headroom {headroom:.1%} below "
                    f"{threshold:.1%}")
        oldest = self.outbox.oldest_age_s()
        return {
            "status": "degraded" if reasons else "ok",
            "degraded_reasons": reasons,
            "worker_version": __version__,
            "last_poll_age_s": age,
            "memory_headroom_ratio": headroom,
            "draining": self._draining.is_set(),
            "jobs_in_flight": self.batcher.outstanding_jobs,
            "results_pending": self.result_queue.qsize(),
            # host-path stage lane (ISSUE 20)
            "stage_lane": {
                "queued": self._stage_queue.qsize(),
                "inflight": self._stage_inflight,
                "workers": int(getattr(
                    self.settings, "stage_workers", 2) or 0),
            },
            "outbox": {
                "depth": self.outbox.depth,
                "oldest_age_s": round(oldest, 1) if oldest else 0,
                "saturated": self.outbox.saturated,
            },
            # multi-hive failover view (hive.py): which endpoint this
            # worker is pinned to, and how often it has had to move
            "hive": {
                "active_endpoint": self.hive.hive_uri,
                "endpoints": list(self.hive.endpoints),
                "failovers": self.hive.failovers,
                "epoch": self.hive.epoch,
            },
            "resident_models": resident_models(),
            "slices": [
                {
                    "slice_id": s.slice_id,
                    "chips": s.chip_count(),
                    "busy": s.busy,
                    "state": ("quarantined"
                              if self.allocator.is_quarantined(s)
                              else "active"),
                    # per-slice warm models (the placement layer's view):
                    # which slice the dispatch board would route each
                    # model's next group to
                    "resident": s.resident_models(),
                    # the mesh view of the slice's most recent pass
                    # (ISSUE 12): data-parallel for coalesced batch
                    # traffic, tensor/seq-sharded for interactive solos
                    "geometry": s.geometry_str(),
                }
                for s in self.allocator.slices
            ],
        }

    def _update_queue_gauges(self) -> None:
        _JOBS_IN_FLIGHT.set(self.batcher.outstanding_jobs)
        _SLICES_BUSY.set(len(self.allocator) - self.allocator.free_count)
        _QUEUE_DEPTH.set(self.batcher.pending_jobs, queue="lingering")
        _QUEUE_DEPTH.set(self.batcher.ready_jobs, queue="ready")
        _QUEUE_DEPTH.set(self.result_queue.qsize(), queue="results")
        _QUEUE_DEPTH.set(
            self._stage_queue.qsize() + self._stage_inflight, queue="stage")
        quarantined = self.allocator.quarantined_count
        _SLICE_STATE.set(len(self.allocator) - quarantined, state="active")
        _SLICE_STATE.set(quarantined, state="quarantined")
        self.outbox.refresh_gauges()

    def _start_profiler_server(self) -> None:
        """jax.profiler trace endpoint (SURVEY §5 'tracing/profiling:
        absent' in the reference — rebuilt as a first-class worker
        capability). Connect with TensorBoard's profile plugin or
        `jax.profiler.trace_function` tooling against localhost:PORT;
        0 disables."""
        port = int(getattr(self.settings, "profiler_port", 0) or 0)
        if not port:
            return
        try:
            import jax.profiler

            jax.profiler.start_server(port)
            logger.info("jax profiler server on :%d", port)
        except Exception as e:  # profiling is an optimization, never fatal
            logger.warning("profiler server unavailable: %s", e)

    def _enable_compilation_cache(self) -> None:
        """Persistent XLA compilation cache — the TPU analog of the reference's
        warm HF model cache (SURVEY §5 'checkpoint/resume'). The knob,
        the unwritable-dir fallback, and the disabled fast path live in
        compile_cache.enable_compile_cache (shared with bench.py)."""
        try:
            from .compile_cache import enable_compile_cache

            path = enable_compile_cache(self.settings)
            if path is not None:
                logger.info("persistent compile cache at %s", path)
        except Exception as e:  # cache is an optimization, never fatal
            logger.warning("compilation cache unavailable: %s", e)

    def _capabilities(self) -> dict:
        """Chip capabilities plus the model-layer honesty key: families
        with no real-weight conversion path are advertised as unconverted
        so a capability-aware hive stops scheduling jobs this worker can
        only fail (VERDICT r03 weak #7); legacy hives ignore the key."""
        from .chips.requirements import flux_admissible, min_chips
        from .weights import UNCONVERTED_FAMILY_KEYWORDS

        caps = dict(self.allocator.capabilities())
        caps["unconverted_families"] = ",".join(UNCONVERTED_FAMILY_KEYWORDS)
        # flux cannot fit one 16 GB chip resident (VERDICT r03 item 4), but
        # weight streaming serves it there anyway (VERDICT r04 missing #2).
        # flux_admissible IS the job gate (check_capacity routes flux
        # through it), evaluated on an actual job slice, so the hive's
        # placement decision matches admission exactly.
        flux = "black-forest-labs/FLUX.1-dev"
        job_slice = self.allocator.slices[0]
        allowed, _ = flux_admissible(job_slice, 1, 1024, model_name=flux)
        caps["flux_runnable"] = int(bool(allowed))
        if job_slice.platform == "tpu":
            per_chip = job_slice.hbm_bytes() / (1 << 30) / max(
                job_slice.chip_count(), 1
            )
            # chips a slice would need at full TP — the remediation the
            # hive/operator can act on when flux_runnable is 0
            caps["flux_min_chips"] = min_chips(flux, max(per_chip, 1e-6))
        # slice geometry advertisement (ISSUE 12): how many chips one job
        # slice spans, and whether this worker will run an interactive
        # job as ONE sharded program over them (shard_interactive AND a
        # multi-chip slice). A geometry-aware hive prefers a
        # shard-capable worker for interactive seeds; legacy hives
        # ignore both keys.
        caps["chips_per_slice"] = job_slice.chip_count()
        caps["shard_capable"] = int(
            bool(getattr(self.settings, "shard_interactive", False))
            and job_slice.shard_capable)
        # live-load snapshot riding the heartbeat: a capability-aware hive
        # can place by actual occupancy instead of round-robin (legacy
        # hives ignore unknown query params)
        caps["jobs_in_flight"] = self.batcher.outstanding_jobs
        caps["busy_slices"] = len(self.allocator) - self.allocator.free_count
        # in-flight IMAGE ROWS (lingering + ready + executing; ISSUE 9):
        # the hive's gang budget is row-denominated — counting jobs, or
        # skipping executing work, would let a gang reply oversubscribe
        # a slice that is mid-coalesce. Versioning note: a pre-gang hive
        # reads this with the old jobs-excl-executing semantics and
        # under-feeds this worker while a coalesced batch executes —
        # transient, conservative (never oversubscribes), and gone once
        # the coordinator is upgraded (it keys the new arithmetic off
        # the gang_rows param below)
        caps["queue_depth"] = self.batcher.outstanding_rows
        # per-slice coalescing appetite: how many rows this worker will
        # merge into ONE pass (the hive sizes gangs by it; 1 = solo-only).
        # max_coalesce is a JOB cap, so for multi-image jobs this
        # under-states the slice's true row capacity — deliberately
        # conservative: gangs under-fill rather than oversubscribe, and
        # put_gang re-chunks anything that still doesn't fit
        caps["gang_rows"] = max(self.batcher.max_coalesce, 1)
        # preemption tolerance (ISSUE 18): a chunked, checkpoint-armed
        # worker can rehydrate a redelivered job from a hive-held
        # checkpoint; the hive attaches `resume` offers only to workers
        # advertising this (legacy hives ignore the key)
        caps["resume_capable"] = int(
            int(getattr(self.settings, "denoise_chunk_steps", 0) or 0) > 0
            and int(getattr(
                self.settings, "checkpoint_every_chunks", 0) or 0) > 0)
        # stage-typed placement (ISSUE 20): the stage names this worker
        # serves. A stage-graph hive gates stage-job hand-outs on this;
        # omitting the key entirely (stage_roles="none") keeps the
        # legacy wire shape — such a worker sees only monolithic jobs.
        stages = self._stage_roles()
        if stages is not None:
            caps["stages"] = ",".join(sorted(stages))
        caps["jobs_completed"] = int(_JOBS_COMPLETED.total())
        if self._last_poll_monotonic is not None:
            caps["last_poll_age_s"] = round(
                time.monotonic() - self._last_poll_monotonic, 1)
        # compact per-stage EWMA blob for the hive's straggler detector
        # (hive_server/fleet.py): {"a": alpha, "s": {stage: [ewma, n]}}.
        # Sent only once samples exist; legacy hives ignore the key.
        if self._stage_stats:
            caps["stats"] = json.dumps(
                {"a": self._stats_alpha,
                 "s": {stage: [round(ewma, 4), n]
                       for stage, (ewma, n) in self._stage_stats.items()}},
                separators=(",", ":"))
        return caps

    def _stage_roles(self) -> frozenset[str] | None:
        """Stage names to advertise on /work, or None for the legacy
        (no `stages` param) shape. "auto": a chip-bearing worker serves
        every stage; the host (CPU) stages are advertised only while the
        stage lane has consumers. An explicit csv passes through, minus
        the CPU stages when the lane is disabled — advertising a stage
        no coroutine will ever pop would strand its jobs until lease
        expiry."""
        from .coalesce import CHIP_STAGES, CPU_STAGES

        raw = str(getattr(self.settings, "stage_roles", "auto")
                  or "auto").strip()
        if raw.lower() == "none":
            return None
        host_ok = int(getattr(self.settings, "stage_workers", 2) or 0) > 0
        if raw.lower() == "auto":
            roles = set(CHIP_STAGES)
            if host_ok:
                roles |= CPU_STAGES
            return frozenset(roles)
        roles = {s.strip() for s in raw.split(",") if s.strip()}
        if not host_ok:
            roles -= CPU_STAGES
        return frozenset(roles)

    def _note_stage_stats(self, timings: dict) -> None:
        """Fold one PASS's stage spans into the per-stage EWMAs the
        `stats` poll param advertises. Called once per physical pass
        (a coalesced group's envelopes share copied timings — folding
        each would fake the hive's min-samples confidence gate with one
        observation), and waiting stages are excluded: queue_wait
        measures THIS worker's backlog, which is load, not slowness —
        folding it in would let the hive's own uneven dispatch
        manufacture a 'straggler'."""
        for key, value in timings.items():
            if not (isinstance(key, str) and key.endswith("_s")):
                continue
            if key == "queue_wait_s":
                continue
            try:
                v = float(value)
            except (TypeError, ValueError):
                continue
            if v < 0:
                continue
            stage = key[:-2]
            entry = self._stage_stats.get(stage)
            if entry is None:
                self._stage_stats[stage] = [v, 1]
            else:
                entry[0] += self._stats_alpha * (v - entry[0])
                entry[1] += 1

    # --- producer: poll the hive ---

    async def poll_loop(self) -> None:
        sleep_seconds = POLL_SECONDS
        while True:
            can_take = (not self._draining.is_set() and not self.batcher.full()
                        and self.allocator.has_free_slice())
            # cancel-only heartbeat (ISSUE 10): a worker whose every
            # slice is busy used to go silent for the whole denoise —
            # exactly the window in which a cancel matters most. It now
            # keeps polling with `cancel_only=1`: the hive skips dispatch
            # (and a legacy hive that hands jobs anyway just feeds the
            # batcher early), keeps the worker live in its directory,
            # and piggybacks lease revocations for the executing slices.
            heartbeat = (not can_take and not self._draining.is_set()
                         and self.batcher.outstanding_jobs > 0)
            if can_take or heartbeat:
                try:
                    caps = self._capabilities()
                    if heartbeat:
                        caps["cancel_only"] = 1
                    jobs = await self.hive.ask_for_work(caps)
                    self._last_poll_monotonic = time.monotonic()
                    _LAST_POLL.set(time.time())
                    # a gang-scheduling hive groups same-key jobs in one
                    # reply and marks them with trace.gang; same-gang
                    # jobs enter the BatchScheduler as ONE pre-formed
                    # group (immediate flush, no linger — the hive
                    # already did the waiting). Everything else takes
                    # the classic per-job put() path.
                    gangs: dict[str, list[dict]] = {}
                    intake: list[tuple[str, object]] = []
                    for job in jobs:
                        print(f"Got job {job['id']}")
                        _JOBS_POLLED.inc()
                        # queue_wait stage starts here; the slice worker
                        # pops the stamp when it picks the job up
                        job["_telemetry_enqueued"] = time.monotonic()
                        # hive-stamped trace context (hive_server wire
                        # contract): note the receipt instant so the
                        # settled timeline can place the worker handoff;
                        # a legacy hive sends none and nothing is added
                        gang_id = None
                        if isinstance(job.get("trace"), dict):
                            job["trace"].setdefault(
                                "received_wall", round(time.time(), 3))
                            gang = job["trace"].get("gang")
                            if isinstance(gang, dict) and gang.get("id"):
                                gang_id = str(gang["id"])
                        # stage-jobs (ISSUE 20): hydrate the predecessor
                        # handoff artifacts through the authed client,
                        # then route host stages to the stage lane — they
                        # never touch the batcher or claim a chip slice
                        if isinstance(job.get("stage"), dict):
                            await self._resolve_stage_inputs(job)
                        if self._is_host_stage(job):
                            intake.append(("stage", job))
                        elif gang_id is None:
                            intake.append(("job", job))
                        else:
                            if gang_id not in gangs:
                                intake.append(("gang", gang_id))
                            gangs.setdefault(gang_id, []).append(job)
                    for kind, item in intake:
                        if kind == "gang":
                            await self.batcher.put_gang(gangs[item])
                        elif kind == "stage":
                            self._stage_queued_ids.add(str(item.get("id")))
                            self._stage_queue.put_nowait(item)
                        else:
                            await self.batcher.put(item)
                    # lease revocations piggybacked on this reply: route
                    # each to wherever the job currently lives (batcher
                    # -> dropped outright; executing slice -> cancel
                    # token probed at the next denoise chunk boundary)
                    for job_id in self.hive.last_cancels:
                        self._cancel_job(job_id)
                    sleep_seconds = POLL_SECONDS
                except asyncio.TimeoutError:
                    # a timeout IS a poll failure: back off like one (the
                    # round-6 branch forgot, re-polling a struggling hive
                    # at the full cadence)
                    logger.warning("hive poll timeout")
                    _POLL_ERRORS.inc()
                    sleep_seconds = _next_backoff(sleep_seconds)
                except Exception as e:
                    logger.exception("ask_for_work error")
                    print(f"ask_for_work error {e}")
                    _POLL_ERRORS.inc()
                    sleep_seconds = _next_backoff(sleep_seconds)
            self._poll_backoff_s = sleep_seconds
            self._update_queue_gauges()
            await asyncio.sleep(sleep_seconds)

    def _cancel_job(self, job_id: str) -> None:
        """Route one hive-revoked job id. Held (lingering / on the
        board): dropped outright, no envelope ever produced. Executing:
        the cancel registry is marked and the chunked denoise aborts the
        row (or the whole pass) at its next chunk boundary. Anything
        else — already delivered, or never ours — is a no-op; a late
        result earns the hive's `cancelled` disposition and parks."""
        job_id = str(job_id)
        if self.batcher.cancel(job_id):
            stage = "held"
        elif job_id in self._executing_ids:
            cancel_mod.cancel(job_id)
            stage = "executing"
            logger.warning(
                "hive cancelled executing job %s; the slice aborts at "
                "its next denoise chunk boundary", job_id)
        elif job_id in self._stage_queued_ids:
            # sitting in the stage lane: tombstone it — the consumer
            # drops it on pickup, no envelope is ever produced
            self._stage_cancelled.add(job_id)
            stage = "held"
        else:
            stage = "unknown"
        _JOBS_CANCELLED.inc(stage=stage)
        self._update_queue_gauges()

    # --- host-path stage lane (ISSUE 20) ---

    @staticmethod
    def _is_host_stage(job: dict) -> bool:
        """True for a stage-job whose stage name is host work (encode/
        decode/postprocess/...): it runs on the stage lane, jax-free,
        and never claims a chip slice."""
        from .coalesce import CPU_STAGES, stage_of

        return stage_of(job) in CPU_STAGES

    async def _resolve_stage_inputs(self, job: dict) -> None:
        """Hydrate a stage-job's handoff: predecessors' outputs arrive
        as content-addressed spool references ({sha256, bytes, href});
        fetch each blob through the AUTHED artifact client and stamp it
        back as base64 so the stage callback works from bytes. Fetch
        failures degrade — the callback reports the missing input as a
        fatal envelope instead of the worker dying here."""
        stage = job.get("stage")
        if not isinstance(stage, dict):
            return
        for entry in stage.get("inputs") or []:
            artifacts = (entry.get("artifacts")
                         if isinstance(entry, dict) else None)
            if not isinstance(artifacts, dict):
                continue
            for art in artifacts.values():
                if not isinstance(art, dict) or art.get("blob"):
                    continue
                href = art.get("href")
                if not href:
                    continue
                blob = await self.hive.fetch_artifact(str(href))
                if blob is not None:
                    art["blob"] = base64.b64encode(blob).decode("ascii")

    async def stage_worker(self) -> None:
        """One consumer of the stage lane: pops a host stage-job, runs
        its callback on the default executor (device "cpu" — no slice,
        no jax), and ships the envelope through the same finish/outbox
        path a slice pass uses. N of these run concurrently
        (Settings.stage_workers), so decode of pass N overlaps denoise
        of pass N+1 on the chip slices."""
        while True:
            job = await self._stage_queue.get()
            picked_up = time.monotonic()
            job_id = str(job.get("id"))
            self._stage_queued_ids.discard(job_id)
            if job_id in self._stage_cancelled:
                self._stage_cancelled.discard(job_id)
                self._stage_queue.task_done()
                continue
            self._stage_inflight += 1
            self._executing_ids.add(job_id)
            enqueued = job.pop("_telemetry_enqueued", None)
            trace = job.pop("trace", None)
            job.pop("resume", None)
            stage_name = str((job.get("stage") or {}).get("name", ""))
            queue_wait = ({job.get("id"): picked_up - enqueued}
                          if enqueued is not None else {})
            traces = ({job.get("id"): trace}
                      if isinstance(trace, dict) else {})
            self._update_queue_gauges()
            try:
                worker_function, kwargs = await self.get_args(job, "cpu")
                if worker_function is not None:
                    result = await asyncio.get_running_loop().run_in_executor(
                        None, self.synchronous_do_work,
                        _HostLane(stage_name), worker_function, kwargs)
                    if result is not None:
                        self._finish_result(result, queue_wait, "cold", traces)
                        self._note_stage_stats(
                            result["pipeline_config"].get("timings") or {})
                        await self._enqueue_result(result)
            except Exception as e:
                logger.exception("stage_worker error")
                print(f"stage_worker {e}")
            finally:
                self._stage_inflight -= 1
                self._executing_ids.discard(job_id)
                cancel_mod.discard(job_id)
                self._stage_queue.task_done()
                self._update_queue_gauges()

    # --- consumers: one logical worker per chip slice ---

    def _coalesce_rows_limit(self, job: dict) -> int | None:
        """Advisory image budget for one coalesced group (BatchScheduler
        rows_limit): the representative slice's capacity for this job's
        model at its canvas, so groups arrive already admissible."""
        from .chips.requirements import coalesce_rows_limit, default_canvas

        model = job.get("model_name", "")
        params = job.get("parameters") or {}
        height = job.get("height", params.get("default_height"))
        width = job.get("width", params.get("default_width"))
        height = int(height or default_canvas(model))
        width = int(width or height)
        return coalesce_rows_limit(self.allocator.slices[0], model, height, width)

    async def slice_worker(self) -> None:
        while True:
            # placement-aware dispatch (batching.py board): the work item
            # and the slice are matched by model residency — affinity to
            # the warm slice, stealing by an idle one when the warm slice
            # is busy — and the chipset arrives already acquired
            batch, chipset, outcome = await self.batcher.claim(self.allocator)
            # queue_wait: hive handoff -> a slice actually starting the work
            picked_up = time.monotonic()
            # whole-pass slice occupancy feeds the "pass" stage EWMA for
            # the hive's straggler detector: unlike the envelope's
            # job_s, this wall clock covers EVERYTHING that holds the
            # slice (arg formatting, a wedged busy lock, an injected
            # hang) — exactly the time a silently sick slice inflates
            pass_started = picked_up
            queue_wait = {}
            traces = {}
            resume_offers = {}
            batch_ids = [str(job["id"]) for job in batch if "id" in job]
            self._executing_ids.update(batch_ids)
            # a job-level deadline (`deadline_s`, the hive TTL's per-job
            # override) caps the slice watchdog for its pass: the
            # submitter's promise outranks the worker-side default. A
            # COALESCED pass is capped only when EVERY member opted in,
            # and then by the loosest promise — a watchdog expiry kills
            # the whole pass, and one job's tight deadline must never
            # cost its batchmates their denoise (observed: a 0.5s
            # deadline ganged with a normal job quarantined the slice)
            caps_by_id = {str(job.get("id")): _deadline_cap_of(job)
                          for job in batch}
            caps = list(caps_by_id.values())
            batch_cap = max(caps) if caps and all(
                c > 0 for c in caps) else None
            for job in batch:
                enqueued = job.pop("_telemetry_enqueued", None)
                if enqueued is not None and "id" in job:
                    queue_wait[job["id"]] = picked_up - enqueued
                # hive trace context comes OFF the job before formatting
                # and rides the envelope back (pipeline_config.trace) so
                # the hive attaches this worker's stage spans to the
                # right dispatch attempt
                trace = job.pop("trace", None)
                if isinstance(trace, dict) and "id" in job:
                    traces[job["id"]] = trace
                # a redelivery's resume offer (ISSUE 18) comes off the
                # job the same way — it is dispatch metadata, not a
                # pipeline argument; the solo path rehydrates from it
                offer = job.pop("resume", None)
                if isinstance(offer, dict) and "id" in job:
                    resume_offers[str(job["id"])] = offer
            self._update_queue_gauges()
            try:
                prepared = []
                for job in batch:
                    worker_function, kwargs = await self.get_args(
                        job, chipset.identifier()
                    )
                    if worker_function is not None:
                        prepared.append((worker_function, kwargs))
                if len(prepared) > 1 and self._batchable(prepared):
                    results = await self.do_batched_work(
                        chipset, prepared, batch_cap)
                    stats_folded = False
                    for result in results:
                        # a cancelled member's slot comes back as None:
                        # no envelope exists and none is delivered — the
                        # hive tombstoned the job, batchmates unharmed
                        if result is None:
                            continue
                        self._finish_result(
                            result, queue_wait, outcome, traces)
                        if not stats_folded:
                            # ONE coalesced pass = one stats sample; the
                            # envelopes all carry the same copied timings
                            self._note_stage_stats(
                                result["pipeline_config"].get(
                                    "timings") or {})
                            stats_folded = True
                        await self._enqueue_result(result)
                else:
                    jobs_by_id = {str(j.get("id")): j for j in batch
                                  if "id" in j}
                    for worker_function, kwargs in prepared:
                        solo_cap = caps_by_id.get(
                            str(kwargs.get("id"))) or None
                        # class-aware geometry (ISSUE 12): an interactive
                        # solo on a multi-chip slice fans ONE image over
                        # every chip as a sharded program; batch solos
                        # (and every coalesced pass) keep the default
                        # data-parallel view
                        self._apply_shard_geometry(
                            jobs_by_id.get(str(kwargs.get("id"))),
                            worker_function, kwargs, chipset)
                        # mid-pass durability (ISSUE 18): arm the solo
                        # pass with checkpoint/preview callbacks and,
                        # for a redelivery carrying an offer, the
                        # rehydrated resume state
                        await self._apply_checkpointing(
                            worker_function, kwargs,
                            resume_offers.get(str(kwargs.get("id"))))
                        result = await self.do_work(
                            chipset, worker_function, kwargs, solo_cap
                        )
                        if result is None:  # pass aborted by a cancel
                            continue
                        self._finish_result(
                            result, queue_wait, outcome, traces)
                        self._note_stage_stats(
                            result["pipeline_config"].get("timings") or {})
                        await self._enqueue_result(result)
            except Exception as e:
                logger.exception("slice_worker error")
                print(f"slice_worker {e}")
            finally:
                self.allocator.release(chipset)
                self._note_stage_stats(
                    {"pass_s": round(time.monotonic() - pass_started, 4)})
                for job in batch:
                    # pass the job so the row accounting (advertised
                    # queue_depth) subtracts its true image count
                    self.batcher.task_done(job)
                for job_id in batch_ids:
                    # tokens die with the pass: a later resubmission of
                    # the same id must start with a clean slate
                    self._executing_ids.discard(job_id)
                    cancel_mod.discard(job_id)
                self._update_queue_gauges()

    def _finish_result(self, result: dict, queue_wait: dict,
                       placement: str | None = None,
                       traces: dict | None = None) -> None:
        """Stamp worker-side stage timings (and the placement outcome that
        routed the work item to its slice) into the envelope and count the
        job by outcome — ONE place, so solo, coalesced, and fallback paths
        all report identically. (The `stats` EWMAs are fed separately,
        once per physical pass — see _note_stage_stats.)"""
        cfg = result.setdefault("pipeline_config", {})
        if placement is not None:
            cfg["placement"] = placement
        trace = (traces or {}).get(result.get("id"))
        if isinstance(trace, dict):
            # echo the hive's trace context (attempt, dispatch instant,
            # plus our receipt instant) back through the envelope
            cfg["trace"] = trace
        timings = cfg.setdefault("timings", {})
        wait = queue_wait.get(result.get("id"))
        if wait is not None:
            observe_stage("queue_wait", wait)
            timings["queue_wait_s"] = round(wait, 3)
        if result.get("fatal_error"):
            outcome = "fatal"
        elif "error" in cfg:
            outcome = "error"
        else:
            outcome = "ok"
        _JOBS_COMPLETED.inc(outcome=outcome)

    # --- priority-aware multi-chip sharding (ISSUE 12) ---

    def _shard_geometry(self, chipset) -> tuple[int, int] | None:
        """The (tensor, seq) view an interactive solo should run under on
        `chipset`, or None when sharding is off / impossible / identical
        to the slice's default view. shard_tensor=0 resolves to the
        chipset's auto degree (largest power-of-two leaving a data axis
        for the CFG pair)."""
        s = self.settings
        if not getattr(s, "shard_interactive", False):
            return None
        if not getattr(chipset, "shard_capable", False):
            return None
        geo = chipset.resolve_geometry(
            int(getattr(s, "shard_tensor", 0) or 0),
            int(getattr(s, "shard_seq", 1) or 1))
        if geo is None or geo == (chipset.tensor, chipset.seq):
            return None
        return geo

    def _apply_shard_geometry(self, job, worker_function, kwargs,
                              chipset) -> None:
        """Attach the sharded mesh view (and the chunk-seam re-shard
        probe) to one interactive solo's kwargs. Only the SD-family
        callback understands the keys; everything else runs untouched."""
        from .batching import is_interactive
        from .workflows.diffusion import diffusion_callback

        if job is None or not is_interactive(job):
            return
        if worker_function is not diffusion_callback:
            return
        geo = self._shard_geometry(chipset)
        if geo is None:
            return
        kwargs["geometry"] = {"tensor": geo[0], "seq": geo[1]}
        kwargs["reshard_probe"] = self._reshard_probe(chipset)
        logger.info(
            "interactive job %s shards over slice %s as tensor=%d seq=%d",
            job.get("id"), chipset.slice_id, geo[0], geo[1])

    def _reshard_probe(self, chipset):
        """Chunk-boundary migration policy for a sharded interactive
        pass: when the queue shifts — released work is waiting on the
        dispatch board and no slice is free — the pass migrates back to
        the slice's default data-parallel view, so its remaining chunks
        run the programs and resident weights every queued coalesced
        pass will reuse (zero geometry churn between back-to-back
        passes). An empty board keeps the latency-optimal sharded view.
        Runs on the executor thread; reads of the asyncio-side counters
        are GIL-atomic ints, same discipline as the cancel registry."""
        default = {"tensor": chipset.tensor, "seq": chipset.seq}

        def probe():
            if (self.batcher.ready_jobs > 0
                    and not self.allocator.has_free_slice()):
                return default
            return None

        return probe

    # --- preemption-tolerant denoise (ISSUE 18) ---

    async def _apply_checkpointing(self, worker_function, kwargs,
                                   offer: dict | None) -> None:
        """Arm one solo diffusion pass with the mid-pass durability seam:
        checkpoint/preview callbacks cut at the knobbed chunk cadence,
        plus — for a redelivery that arrived with a `resume` offer — the
        checkpointed state rehydrated from the hive's spool. Only the
        SD-family callback understands the keys (workflows gate them on
        `supports_checkpoint`); coalesced passes never checkpoint by
        design — a batch member's padded row is not a job's worth of
        resumable state."""
        from .workflows.diffusion import diffusion_callback

        if worker_function is not diffusion_callback:
            return
        s = self.settings
        if int(getattr(s, "denoise_chunk_steps", 0) or 0) <= 0:
            return  # fused pass: no boundaries to checkpoint at
        job_id = str(kwargs.get("id"))
        if isinstance(offer, dict) and offer.get("href"):
            state = await self._fetch_resume_state(job_id, offer)
            if state is not None:
                kwargs["resume"] = state
        loop = asyncio.get_running_loop()
        ckpt_every = int(getattr(s, "checkpoint_every_chunks", 0) or 0)
        if ckpt_every > 0:
            kwargs["checkpoint_every_chunks"] = ckpt_every
            kwargs["checkpoint_cb"] = self._checkpoint_shipper(job_id, loop)
        preview_every = int(getattr(s, "preview_every_chunks", 0) or 0)
        if preview_every > 0:
            kwargs["preview_every_chunks"] = preview_every
            kwargs["preview_cb"] = self._preview_shipper(
                job_id, loop, str(kwargs.get("content_type", "image/jpeg")))

    async def _fetch_resume_state(self, job_id: str,
                                  offer: dict) -> dict | None:
        """Fetch and unpack one resume offer's checkpoint blob. Every
        failure degrades to the full pass (counted, logged), never to a
        job error — resume is an optimization, not a dependency."""
        blob = await self.hive.fetch_artifact(str(offer["href"]))
        if blob is None:
            _RESUMES.inc(outcome="fetch_failed")
            logger.warning(
                "resume offer for %s: checkpoint fetch failed; "
                "running the full pass", job_id)
            return None
        try:
            from . import checkpoint as ckpt

            state = await asyncio.get_running_loop().run_in_executor(
                None, ckpt.unpack, blob)
        except Exception as e:
            _RESUMES.inc(outcome="unpack_failed")
            logger.warning(
                "resume offer for %s: checkpoint unpack failed (%s); "
                "running the full pass", job_id, e)
            return None
        _RESUMES.inc(outcome="resumed")
        logger.info("job %s rehydrates from checkpointed step %s",
                    job_id, state.get("step"))
        return state

    def _checkpoint_shipper(self, job_id: str, loop):
        """The checkpoint callback for one pass. Runs on the executor
        thread at chunk boundaries: packs the live state there (the
        arrays are already host-side numpy), then hands the upload to
        the event loop fire-and-forget — the denoise never waits on the
        hive, and a failed upload costs the checkpoint, not the pass."""
        max_bytes = int(getattr(
            self.settings, "checkpoint_max_bytes", 0) or 0)

        def ship(step, latents, state_leaves, signature):
            try:
                from . import checkpoint as ckpt

                blob = ckpt.pack(step, latents, state_leaves, signature)
            except Exception:
                _CHECKPOINTS.inc(outcome="error")
                logger.exception("checkpoint pack failed for %s", job_id)
                return
            if max_bytes > 0 and len(blob) > max_bytes:
                _CHECKPOINTS.inc(outcome="oversize")
                logger.warning(
                    "checkpoint for %s at step %d is %d bytes "
                    "(checkpoint_max_bytes %d); skipped",
                    job_id, step, len(blob), max_bytes)
                return
            payload = {
                "step": int(step),
                "signature": signature,
                "worker_name": self.settings.worker_name,
                "blob": base64.b64encode(blob).decode("ascii"),
            }
            coro = self._ship_partial("checkpoint", job_id, payload)
            try:
                asyncio.run_coroutine_threadsafe(coro, loop)
            except RuntimeError:  # loop gone: the worker died mid-pass
                coro.close()
                _CHECKPOINTS.inc(outcome="error")
                return
            # chaos seam (tools/chaos_smoke.py resume_after_worker_kill):
            # the worker dies HERE — mid-denoise, past a shipped
            # checkpoint — and a second worker must finish from it
            faults.hang("hang_after_checkpoint")

        return ship

    def _preview_shipper(self, job_id: str, loop, content_type: str):
        """The preview callback for one pass: VAE-decoded boundary pixels
        arrive on the executor thread, are encoded there, and ship to
        the hive's preview endpoint fire-and-forget."""
        if not content_type.startswith("image/"):
            content_type = "image/jpeg"

        def ship(step, pixels):
            try:
                from .pipelines.stable_diffusion import _to_pil
                from .post_processors.output_processor import image_to_buffer

                image = _to_pil(pixels)[0]
                payload = {
                    "step": int(step),
                    "content_type": content_type,
                    "worker_name": self.settings.worker_name,
                    "blob": base64.b64encode(
                        image_to_buffer(image, content_type).getvalue()
                    ).decode("ascii"),
                }
            except Exception:
                _PREVIEWS.inc(outcome="error")
                logger.exception("preview encode failed for %s", job_id)
                return
            coro = self._ship_partial("preview", job_id, payload)
            try:
                asyncio.run_coroutine_threadsafe(coro, loop)
            except RuntimeError:  # loop gone: the worker died mid-pass
                coro.close()
                _PREVIEWS.inc(outcome="error")

        return ship

    async def _ship_partial(self, kind: str, job_id: str,
                            payload: dict) -> None:
        """Upload one mid-pass partial; the pass never learns whether it
        landed (post_partial already absorbs refusals and transport
        errors into None)."""
        counter = _CHECKPOINTS if kind == "checkpoint" else _PREVIEWS
        try:
            ack = await self.hive.post_partial(kind, job_id, payload)
        except Exception as e:  # belt and braces: never kill the loop
            ack = None
            logger.warning("%s upload for %s raised: %s", kind, job_id, e)
        counter.inc(outcome="shipped" if ack else "error")

    @staticmethod
    def _batchable(prepared: list) -> bool:
        """A group executes as one pass only when every member formatted to
        the plain diffusion callback — anything else (a mid-flight
        fallback, a mixed group from a future scheduler) runs solo."""
        from .workflows.diffusion import diffusion_callback

        return all(fn is diffusion_callback for fn, _ in prepared)

    async def get_args(self, job: dict, device_identifier: str):
        try:
            return await format_args(job, self.settings, device_identifier)
        except Exception as e:
            # input args are wrong somehow: not recoverable, don't resubmit
            # (reference swarm/worker.py:105-115)
            logger.exception("format_args failed for job %s", job.get("id"))
            result = fatal_exception_response(e, job["id"], job)
            self._finish_result(result, {})
            await self._enqueue_result(result)
        return None, None

    # --- slice watchdog ---

    def _job_deadline(self, model_name, chipset=None,
                      cap_s: float | None = None) -> float | None:
        """Execution deadline for one pass; None = watchdog off. A model
        that is not yet resident ON THIS SLICE gets the first-compile
        allowance — big programs legitimately take minutes to compile
        once, and a STOLEN group pays that on the stealing slice even
        when the model is warm elsewhere in the process. `cap_s` (the
        job's own `deadline_s`, ISSUE 10) is a hard ceiling: the
        watchdog treats the submitter's deadline as its cap, compile
        allowance included — and it arms the watchdog even when the
        worker-wide knob is off."""
        base = float(getattr(self.settings, "job_deadline_s", 0.0) or 0.0)
        deadline: float | None = None
        if base > 0:
            scale = 1.0
            try:
                from .registry import resident_models

                slice_id = getattr(chipset, "slice_id", None)
                if model_name and model_name not in resident_models(slice_id):
                    scale = max(float(getattr(
                        self.settings, "job_deadline_compile_scale", 4.0)),
                        1.0)
            except Exception:  # residency probe must never block execution
                pass
            deadline = base * scale
        if cap_s is not None and cap_s > 0:
            deadline = cap_s if deadline is None else min(deadline, cap_s)
        return deadline

    def _expire_pass(self, chipset, fut, jobs_meta: list[dict],
                     deadline: float, kind: str) -> list[dict]:
        """A pass blew its watchdog deadline: quarantine the slice, hand
        every member job the existing transient-error envelope (the hive
        may resubmit elsewhere), and let the wedged thread finish or rot
        in the background — the probe decides if the slice returns."""
        _WATCHDOG_EXPIRED.inc(len(jobs_meta), kind=kind)
        logger.error(
            "watchdog: %s pass on slice %s exceeded its %.1fs deadline "
            "(jobs %s); quarantining the slice",
            kind, chipset.slice_id, deadline,
            [m.get("id") for m in jobs_meta])
        # the orphaned executor future may still raise much later; consume
        # it so asyncio doesn't log an unretrieved exception
        fut.add_done_callback(
            lambda f: f.cancelled() or f.exception())
        self.allocator.quarantine(chipset)
        self._update_queue_gauges()
        probe = asyncio.create_task(
            self._quarantine_probe(chipset),
            name=f"quarantine_probe_{chipset.slice_id}")
        self._probe_tasks.add(probe)
        probe.add_done_callback(self._probe_tasks.discard)

        results = []
        for meta in jobs_meta:
            err = TimeoutError(
                f"job execution exceeded the {deadline:g}s watchdog "
                "deadline; the slice was quarantined and the job may be "
                "resubmitted")
            content_type = meta.get("content_type") or "image/jpeg"
            if content_type.startswith("image/"):
                artifacts, pipeline_config = exception_image(err, content_type)
            else:
                artifacts, pipeline_config = exception_message(err)
            results.append({
                "id": meta.get("id"),
                "artifacts": artifacts,
                "nsfw": False,
                "worker_version": __version__,
                "pipeline_config": pipeline_config,
            })
        return results

    async def _quarantine_probe(self, chipset) -> None:
        """Wait (bounded) for the wedged pass to release the slice, then
        run the tiny smoke program. Pass -> the slice returns to the
        allocator without a worker restart; fail/wedged -> it stays out
        and advertised capacity stays shrunk."""
        grace = max(float(getattr(
            self.settings, "quarantine_probe_grace_s", 30.0)), 0.0)
        deadline = time.monotonic() + grace
        while chipset.busy and time.monotonic() < deadline:
            await asyncio.sleep(0.05)
        if chipset.busy:
            _WATCHDOG_PROBES.inc(outcome="wedged")
            logger.error(
                "slice %s still wedged %.0fs after its watchdog expiry; "
                "leaving it quarantined (capacity stays shrunk)",
                chipset.slice_id, grace)
            self._update_queue_gauges()
            return
        # the default executor, not the slice pool — a wedged slice thread
        # must not be able to starve its own recovery probe
        ok = await asyncio.get_running_loop().run_in_executor(
            None, chipset.smoke_probe)
        if ok:
            self.allocator.reinstate(chipset)
            _WATCHDOG_PROBES.inc(outcome="ok")
            logger.warning(
                "slice %s passed the smoke probe; returned to service",
                chipset.slice_id)
        else:
            _WATCHDOG_PROBES.inc(outcome="failed")
            logger.error(
                "slice %s failed the smoke probe; leaving it quarantined",
                chipset.slice_id)
        self._update_queue_gauges()

    async def do_work(self, chipset, worker_function, kwargs,
                      deadline_cap_s: float | None = None) -> dict | None:
        loop = asyncio.get_running_loop()
        # captured BEFORE dispatch: the executor thread mutates kwargs
        meta = [{"id": kwargs.get("id"),
                 "content_type": kwargs.get("content_type", "image/jpeg")}]
        deadline = self._job_deadline(
            kwargs.get("model_name"), chipset, deadline_cap_s)
        fut = loop.run_in_executor(
            self._executor, self.synchronous_do_work, chipset, worker_function, kwargs
        )
        if deadline is None:
            return await fut
        try:
            return await asyncio.wait_for(asyncio.shield(fut), deadline)
        except asyncio.TimeoutError:
            return self._expire_pass(chipset, fut, meta, deadline, "solo")[0]

    async def do_batched_work(self, chipset, prepared: list,
                              deadline_cap_s: float | None = None
                              ) -> list[dict | None]:
        loop = asyncio.get_running_loop()
        meta = [{"id": kw.get("id"),
                 "content_type": kw.get("content_type", "image/jpeg")}
                for _, kw in prepared]
        deadline = self._job_deadline(prepared[0][1].get("model_name"), chipset)
        if deadline is not None:
            # budget the WORST case of this executor call: the coalesced
            # pass fails and synchronous_do_batch reruns every member
            # sequentially through the solo path — a legitimate full-group
            # fallback must not read as a hang and cost the slice
            deadline *= max(len(prepared), 1)
        if deadline_cap_s is not None and deadline_cap_s > 0:
            # the job-level deadline is an absolute promise; it caps the
            # final budget AFTER the fallback allowance, never scales
            deadline = (deadline_cap_s if deadline is None
                        else min(deadline, deadline_cap_s))
        fut = loop.run_in_executor(
            self._executor, self.synchronous_do_batch, chipset, prepared
        )
        if deadline is None:
            return await fut
        try:
            return await asyncio.wait_for(asyncio.shield(fut), deadline)
        except asyncio.TimeoutError:
            return self._expire_pass(chipset, fut, meta, deadline, "batched")

    def synchronous_do_batch(self, chipset, prepared: list) -> list[dict]:
        """One coalesced pass for a compatible group; on ANY failure, fall
        back to the single-job path per member — which reproduces the
        error with the existing fatal/transient attribution, so batching
        never changes what the hive sees beyond latency. The one typed
        exception is DeltaIneligibleError: a member whose adapter the
        runtime delta cannot express (conv/LoCon, over-rank) goes solo
        through the merged-tree path while its batchmates RE-BATCH —
        one slow adapter must not serialize the whole gang."""
        from .pipelines.lora_runtime import DeltaIneligibleError
        from .workflows.diffusion import diffusion_batched_callback

        # pristine copies for the fallback: the batched path pops/injects
        # keys (seed, rng, chipset) destructively
        singles = [(fn, dict(kwargs)) for fn, kwargs in prepared]
        requests = [kwargs for _, kwargs in prepared]
        # ids stay IN the request kwargs: the batched pipeline path needs
        # them for its per-row cancel tokens (chunked denoise); the
        # callbacks read only the keys they know, so the extra key rides
        # along harmlessly
        ids = [kwargs.get("id") for kwargs in requests]
        print(
            f"Processing batch of {len(ids)} jobs {ids} "
            f"on {chipset.descriptor()}"
        )
        try:
            with trace_job(",".join(str(i) for i in ids)):
                outs = chipset.run_batched(diffusion_batched_callback, requests)
            return [
                None if pipeline_config.get("cancelled") else {
                    "id": job_id,
                    "artifacts": artifacts,
                    "nsfw": pipeline_config.get("nsfw", False),
                    "worker_version": __version__,
                    "pipeline_config": pipeline_config,
                }
                for job_id, (artifacts, pipeline_config) in zip(ids, outs)
            ]
        except JobCancelled as e:
            # every live member was cancelled: the pass aborted at a
            # chunk boundary, the slice is free, and NO envelope exists —
            # the hive tombstoned these jobs and wants nothing back
            logger.warning("coalesced pass aborted by cancellation: %s",
                           e.job_ids)
            return [None] * len(ids)
        except DeltaIneligibleError as e:
            bad = set(e.job_ids)
            eligible = [(fn, dict(kw)) for fn, kw in singles
                        if kw.get("id") not in bad]
            if not (bad & set(ids)) or len(eligible) < 2:
                # no per-member identity or nothing left worth
                # re-batching: classic whole-group solo fallback
                logger.info("coalesced pass for %s: %s", ids, e)
                return [self.synchronous_do_work(chipset, fn, dict(kw))
                        for fn, kw in singles]
            logger.info(
                "coalesced pass for %s: members %s are not delta-eligible; "
                "re-batching the %d eligible member(s)",
                ids, sorted(bad), len(eligible))
            by_id = dict(zip([kw.get("id") for _, kw in eligible],
                             self.synchronous_do_batch(chipset, eligible)))
            for fn, kw in singles:
                if kw.get("id") in bad:
                    by_id[kw.get("id")] = self.synchronous_do_work(
                        chipset, fn, dict(kw))
            return [by_id[i] for i in ids]
        except Exception as e:
            logger.exception(
                "coalesced pass for %s failed; retrying jobs individually", ids
            )
            print(f"batched pass failed ({e}); falling back to single jobs")
            return [
                self.synchronous_do_work(chipset, fn, kwargs)
                for fn, kwargs in singles
            ]

    def synchronous_do_work(self, chipset, worker_function, kwargs) -> dict:
        job_id = kwargs.pop("id")
        print(f"Processing {job_id} on {chipset.descriptor()}")

        # trace_job pins the job id on this executor thread so every log
        # line (and span) emitted during execution carries it (JSON logs)
        try:
            with trace_job(job_id):
                artifacts, pipeline_config = chipset(worker_function, **kwargs)
        except JobCancelled:
            # aborted at a denoise chunk boundary: the hive revoked this
            # job mid-flight. No envelope — the slice frees within one
            # chunk and the hive's tombstone is the terminal truth
            logger.warning("job %s cancelled mid-denoise; pass aborted",
                           job_id)
            return None
        except (ValueError, TypeError) as e:
            # non-recoverable (e.g. incompatible adapter): fatal envelope
            return fatal_exception_response(e, job_id, kwargs)
        except Exception as e:
            # transient: render the error as the artifact, job still "succeeds"
            logger.exception("job %s failed", job_id)
            content_type = kwargs.get("content_type", "image/jpeg")
            if content_type.startswith("image/"):
                artifacts, pipeline_config = exception_image(e, content_type)
            else:
                artifacts, pipeline_config = exception_message(e)

        return {
            "id": job_id,
            "artifacts": artifacts,
            "nsfw": pipeline_config.get("nsfw", False),
            "worker_version": __version__,
            "pipeline_config": pipeline_config,
        }

    # --- uploader (durable outbox, outbox.py) ---

    async def _enqueue_result(self, result: dict) -> None:
        """Spool the envelope to disk, then queue it for delivery — the
        write-ahead half of the outbox contract. From this point the job
        cannot be silently lost: only a hive ACK unlinks the file. The
        write runs off-loop: a multi-MB artifact envelope on a slow disk
        must not stall timers, polls, or the drain watcher."""
        # the sender's identity rides the envelope (legacy hives ignore
        # unknown keys): a lease-tracking hive needs it to attribute a
        # LATE result to the worker that actually produced it, not to
        # whoever holds the redelivered lease at arrival time
        result.setdefault("worker_name", self.settings.worker_name)
        entry = await asyncio.get_running_loop().run_in_executor(
            None, self.outbox.spool, result)
        await self.result_queue.put(entry)

    async def result_worker(self) -> None:
        while True:
            entry = await self.result_queue.get()
            self._delivering += 1
            try:
                await self._deliver(entry)
            except FaultInjected:
                # fault harness only: a simulated crash after upload,
                # before ACK — the envelope stays spooled for redelivery
                logger.error(
                    "injected crash before ack for %s", entry.job_id)
                raise
            except Exception as e:
                logger.exception("result_worker error")
                print(f"result_worker {e}")
            finally:
                self._delivering -= 1
                self.result_queue.task_done()
                self._update_queue_gauges()

    async def _deliver(self, entry: OutboxEntry) -> None:
        """Upload one spooled envelope until the hive ACKs (capped
        exponential backoff + jitter between attempts). A permanent 4xx
        refusal parks the entry on disk instead — retried next restart,
        never dropped."""
        while True:
            err: Exception
            try:
                t0 = time.perf_counter()
                ack = await self.hive.submit_result(entry.result)
                # stage "submit": successful upload latency (failures are
                # counted per-endpoint by hive.py)
                observe_stage("submit", time.perf_counter() - t0)
                faults.fire("kill_before_ack")
                # disposition ACKs (ISSUE 10): the hive took the POST but
                # will never store this result — the job was cancelled,
                # expired, or retired ("gone"). PARK the envelope with
                # the reason instead of unlinking: the artifacts cost a
                # full denoise pass and stay on disk for the operator
                # (tools/outbox_inspect.py shows the reason; --requeue
                # retries them if a hive will take them later). Before
                # this, a 200 ACK always unlinked and a non-200 for a
                # gone job retried on the transient path forever.
                reason = None
                if isinstance(ack, dict):
                    if ack.get("cancelled"):
                        reason = "cancelled: hive revoked this job"
                    elif ack.get("expired"):
                        reason = "expired: job TTL lapsed at the hive"
                    elif ack.get("unknown_job"):
                        reason = "gone: hive no longer knows this job id"
                if reason is not None:
                    logger.warning(
                        "hive acknowledged but discarded result %s (%s); "
                        "parking the envelope", entry.job_id, reason)
                    await asyncio.get_running_loop().run_in_executor(
                        None, self.outbox.park, entry, reason)
                    return
                self.outbox.delivered(entry)
                return
            except FaultInjected:
                raise
            except asyncio.TimeoutError as e:
                err = e
            except HiveError as e:
                if e.permanent:
                    logger.error(
                        "hive permanently refused result %s (%s); parking "
                        "the envelope on disk", entry.job_id, e)
                    # park() rewrites the full envelope with its delivery
                    # history — off-loop, like spool(): a multi-MB
                    # artifact payload must not stall polls or timers
                    await asyncio.get_running_loop().run_in_executor(
                        None, self.outbox.park, entry, str(e))
                    return
                err = e
            except Exception as e:  # unexpected: still never drop work
                err = e
            entry.retries += 1
            self.outbox.note_retry()
            delay = outbox_mod.backoff_delay(entry.retries)
            logger.warning(
                "submit failed for %s (attempt %d: %s); retrying in %.1fs",
                entry.job_id, entry.retries, err, delay)
            await asyncio.sleep(delay)


class _HostLane:
    """Chipset stand-in for the stage lane (ISSUE 20): satisfies the
    synchronous_do_work contract — descriptor for logging, __call__
    running the callback — without touching a slice, the busy lock, or
    jax. Host stage callbacks (encode/decode/postprocess) are
    deterministic CPU work, so no seed/RNG is drawn."""

    def __init__(self, stage: str):
        self._stage = stage or "stage"

    def descriptor(self) -> str:
        return f"host:{self._stage}"

    def identifier(self) -> str:
        return "cpu"

    def __call__(self, func, **kwargs):
        model_name = kwargs.pop("model_name", "")
        kwargs.pop("seed", None)
        started = time.perf_counter()
        artifacts, pipeline_config = func("cpu", model_name, **kwargs)
        pipeline_config.setdefault("timings", {})["job_s"] = round(
            time.perf_counter() - started, 3)
        return artifacts, pipeline_config


async def run_worker() -> None:
    await Worker().run()


def main() -> None:
    """Console entry point (`chiaswarm-tpu-worker`)."""
    try:
        asyncio.run(run_worker())
    except KeyboardInterrupt:
        print("done")


if __name__ == "__main__":
    main()
