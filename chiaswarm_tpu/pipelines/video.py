"""Video diffusion pipelines: txt2vid, img2vid, vid2vid.

Reference swarm/video/* rebuilt TPU-first:
- txt2vid (tx2vid.py:15-81): motion-module UNet, whole clip denoised in ONE
  jitted scan (frames ride the batch dim), VAE-decoded per frame, exported
  mp4/webm/gif.
- img2vid (img2vid.py:14-38): owned by pipelines/svd.py (SVD) and
  pipelines/i2vgen.py (I2VGenXL, the workflow default).
- vid2vid (pix2pix.py:14-191): the reference edits frames one at a time in
  a Python loop (up to 100 sequential pipeline calls, :47-68); here frames
  batch through the image pipeline's jitted program in fixed-size chunks.
"""

from __future__ import annotations

import logging
import os
import time
import zlib
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np
from PIL import Image

from ..models import configs as cfgs
from ..models.clip import CLIPTextEncoder
from ..models.tokenizer import load_tokenizer
from ..models.unet2d import UNet2DConfig
from ..models.vae import AutoencoderKL
from ..models.video_unet import VideoUNet, VideoUNetConfig
from ..post_processors.output_processor import make_result
from ..registry import register_family
from ..schedulers import get_scheduler
from ..toolbox.video_helpers import (
    download_video,
    export_frames,
    first_frame_thumbnail,
    split_video_frames,
)

logger = logging.getLogger(__name__)

DEFAULT_FPS = 8
VID2VID_CHUNK = 8  # frames per batched img2img program call

from ..weights import DEFAULT_MOTION_ADAPTER  # noqa: F401  (job default)


def _model_dir(model_name: str):
    from ..weights import model_dir_for

    return model_dir_for(model_name)


def _load_converted_video(model_name: str, motion_adapter: str | None,
                          model_dir=None):
    """-> {"unet","text","vae","model_dir"} or None. AnimateDiff's
    composition: an SD1.5-family spatial UNet checkpoint overlaid with a
    MotionAdapter's temporal modules, plus the checkpoint's CLIP/VAE —
    all-or-nothing (spatial weights with random temporal modules are a
    no-op video model; the reverse hallucinates)."""
    name = model_name.lower()
    if "tiny" in name or name.startswith("test/"):
        return None
    d = model_dir if model_dir is not None else _model_dir(model_name)
    adapter_dir = _model_dir(motion_adapter or DEFAULT_MOTION_ADAPTER)
    if d is None:
        return None
    from ..models.conversion import (
        convert_clip,
        convert_vae,
        convert_video_unet,
        load_torch_state_dict,
    )
    from ..weights import MissingWeightsError

    try:
        unet_state = load_torch_state_dict(d, "unet")
        if any("temp_convs" in k for k in unet_state):
            # zeroscope / modelscope text-to-video: a native
            # UNet3DConditionModel checkpoint (temporal convs +
            # frame-attention), geometry inferred from the state dict
            import json

            from ..models.conversion import (
                convert_unet3d,
                infer_unet3d_config,
            )

            from ..models.clip import CLIPTextConfig
            from ..models.conversion import infer_vae_config

            def read_json(sub):
                p = d / sub / "config.json"
                return json.loads(p.read_text()) if p.is_file() else {}

            unet3d_cfg = infer_unet3d_config(unet_state, read_json("unet"))
            # zeroscope's text tower is CLIP ViT-H (1024), not the SD1.5
            # default — geometry from the checkpoint's own config.json
            tj = read_json("text_encoder")
            base = CLIPTextConfig()
            clip_cfg = CLIPTextConfig(
                vocab_size=int(tj.get("vocab_size", base.vocab_size)),
                hidden_size=int(tj.get("hidden_size", base.hidden_size)),
                num_layers=int(
                    tj.get("num_hidden_layers", base.num_layers)
                ),
                num_heads=int(
                    tj.get("num_attention_heads", base.num_heads)
                ),
                max_positions=int(
                    tj.get("max_position_embeddings", base.max_positions)
                ),
                hidden_act=str(tj.get("hidden_act", base.hidden_act)),
            )
            vae_state = load_torch_state_dict(d, "vae")
            return {
                "unet3d": convert_unet3d(unet_state),
                "unet3d_cfg": unet3d_cfg,
                "clip_cfg": clip_cfg,
                "vae_cfg": infer_vae_config(vae_state, read_json("vae")),
                "text": convert_clip(load_torch_state_dict(d, "text_encoder")),
                "vae": convert_vae(vae_state),
                "model_dir": d,
            }
        if adapter_dir is None:
            raise FileNotFoundError(
                f"motion adapter {motion_adapter or DEFAULT_MOTION_ADAPTER} "
                "not downloaded"
            )
        unet = convert_video_unet(
            unet_state,
            load_torch_state_dict(adapter_dir),
        )
        text = convert_clip(load_torch_state_dict(d, "text_encoder"))
        vae = convert_vae(load_torch_state_dict(d, "vae"))
    except (FileNotFoundError, OSError):
        return None
    except Exception as e:
        raise MissingWeightsError(
            f"checkpoint under {d} could not be converted for "
            f"'{model_name}': {e}"
        ) from e
    return {"unet": unet, "text": text, "vae": vae, "model_dir": d}


def _replace(cfg: UNet2DConfig, **kw) -> UNet2DConfig:
    import dataclasses

    return dataclasses.replace(cfg, **kw)


def _video_configs(model_name: str):
    name = model_name.lower()
    if "tiny" in name or name.startswith("test/"):
        return (
            VideoUNetConfig(base=cfgs.TINY_UNET, num_frames=8),
            cfgs.TINY_CLIP,
            cfgs.TINY_VAE,
            64,
        )
    # AnimateDiff / zeroscope / damo / SVD ride SD1.5-geometry UNets
    return (
        VideoUNetConfig(base=cfgs.SD15_UNET, num_frames=16),
        cfgs.SD15_CLIP,
        cfgs.SD_VAE,
        512,
    )


class VideoPipeline:
    """Resident motion-module pipeline; serves txt2vid and img2vid."""

    def __init__(self, model_name: str, chipset=None,
                 allow_random_init: bool = False, motion_adapter=None):
        from ..weights import require_weights_present

        self.model_name = model_name
        self.chipset = chipset
        # txt2vid serves real AnimateDiff weights (spatial SD1.5 checkpoint
        # + motion adapter) or a native UNet3D checkpoint; img2vid is owned
        # by pipelines/svd.py and pipelines/i2vgen.py
        self._loaded_adapter = motion_adapter or DEFAULT_MOTION_ADAPTER
        self._converted = _load_converted_video(model_name, motion_adapter)
        if self._converted is None:
            require_weights_present(
                model_name, None, allow_random_init,
                component="video model",
                hint="Video weights were not found under the model root; "
                     "AnimateDiff serving needs the base SD checkpoint AND "
                     "the motion adapter downloaded (initialize --download).",
            )
        video_cfg, clip_cfg, vae_cfg, self.default_size = _video_configs(model_name)
        if self._converted and "clip_cfg" in self._converted:
            # native UNet3D checkpoints carry their own tower geometry
            clip_cfg = self._converted["clip_cfg"]
            vae_cfg = self._converted["vae_cfg"]
        self.config = video_cfg
        self.latent_factor = 2 ** (len(vae_cfg.block_out_channels) - 1)

        on_tpu = jax.default_backend() == "tpu"
        self.dtype = jnp.bfloat16 if on_tpu else jnp.float32
        self.unet3d = bool(self._converted) and "unet3d" in self._converted
        if self.unet3d:
            # native zeroscope/modelscope UNet3D checkpoint: motion-adapter
            # and motion-LoRA overlays do not apply to this graph
            from ..models.unet3d import UNet3DConditionModel

            self.unet = UNet3DConditionModel(
                self._converted["unet3d_cfg"], dtype=self.dtype
            )
        else:
            self.unet = VideoUNet(video_cfg, dtype=self.dtype)
        self.text_encoder = CLIPTextEncoder(clip_cfg, dtype=self.dtype)
        self.vae = AutoencoderKL(vae_cfg, dtype=self.dtype)
        self.tokenizer = load_tokenizer(
            self._converted["model_dir"] if self._converted else None,
            vocab_size=clip_cfg.vocab_size,
        )

        t0 = time.perf_counter()
        self.params = self._init_params()
        logger.info(
            "%s video pipeline resident in %.1fs", model_name,
            time.perf_counter() - t0,
        )
        # insertion-ordered so the program_cache_max bound below can evict
        # least-recently-used first (SW007; same knob as the SD family)
        self._programs: OrderedDict = OrderedDict()
        # param trees with motion-LoRAs merged, keyed by (ref, scale);
        # bounded — each entry pins a full UNet copy
        from collections import OrderedDict

        self._lora_cache: OrderedDict[tuple, dict] = OrderedDict()

    def _adapter_params(self, params: dict, motion_adapter) -> dict:
        """Params with the REQUESTED adapter's temporal modules overlaid
        (jobs may pin e.g. AnimateLCM instead of the resident default)."""
        name = (
            motion_adapter.get("model_name")
            if isinstance(motion_adapter, dict)
            else str(motion_adapter)
        )
        if not name or name == self._loaded_adapter:
            return params
        key = ("adapter", name)
        if key in self._lora_cache:
            self._lora_cache.move_to_end(key)
            return self._lora_cache[key]
        from ..models.conversion import (
            convert_motion_adapter,
            load_torch_state_dict,
        )
        from ..weights import MissingWeightsError

        d = _model_dir(name)
        if d is None:
            raise MissingWeightsError(
                f"motion adapter '{name}' is not downloaded; run "
                f"initialize --download"
            )
        motion = convert_motion_adapter(load_torch_state_dict(d))
        cast = lambda x: jnp.asarray(x, self.dtype)
        unet = dict(params["unet"])
        for k, sub in motion.items():
            unet[k] = jax.tree_util.tree_map(cast, sub)
        out = dict(params)
        out["unet"] = unet
        self._lora_cache[key] = out
        while len(self._lora_cache) > 2:
            self._lora_cache.popitem(last=False)
        return out

    def _lora_params(self, base_params: dict, lora: dict, scale: float) -> dict:
        """Base params with a motion-LoRA merged into the video UNet
        (reference tx2vid.py:26-48 loads AnimateDiff motion adapters /
        LoRA adapter weights per job; here the merge happens once and the
        merged tree stays resident)."""
        key = (lora.get("lora"), lora.get("weight_name"),
               lora.get("subfolder"), round(scale, 4))
        if key in self._lora_cache:
            self._lora_cache.move_to_end(key)
            return self._lora_cache[key]
        from ..models.lora import resolve_and_merge

        merged_unet = resolve_and_merge(
            base_params["unet"], lora, scale, self.model_name
        )
        cast = lambda x: jnp.asarray(x, self.dtype)
        out = dict(base_params)
        out["unet"] = jax.tree_util.tree_map(cast, merged_unet)
        self._lora_cache[key] = out
        while len(self._lora_cache) > 2:
            self._lora_cache.popitem(last=False)
        return out

    def _init_params(self):
        rng = jax.random.key(zlib.crc32(self.model_name.encode()))
        k1, k2, k3 = jax.random.split(rng, 3)
        frames = self.config.num_frames
        hw = 2 ** max(len(self.config.base.block_out_channels), 3)
        unet_args = (
            jnp.zeros((frames, hw, hw, self.config.base.in_channels)),
            jnp.zeros((frames,)),
            jnp.zeros((frames, 77, self.config.base.cross_attention_dim)),
        )
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            if self._converted is not None:
                from ..models.conversion import checked_converted as _checked_converted

                if self.unet3d:
                    import functools

                    from ..models.conversion import (
                        assert_tree_shapes_match,
                    )
                    from ..weights import MissingWeightsError

                    cfg3d = self._converted["unet3d_cfg"]
                    # num_frames is a STATIC python int (reshape factor):
                    # partial it so eval_shape never traces it
                    expected = jax.eval_shape(
                        functools.partial(self.unet.init, num_frames=frames),
                        k1,
                        jnp.zeros((frames, hw, hw, cfg3d.in_channels)),
                        jnp.zeros((frames,)),
                        jnp.zeros((frames, 77, cfg3d.cross_attention_dim)),
                    )["params"]
                    try:
                        assert_tree_shapes_match(
                            self._converted["unet3d"], expected, prefix="unet"
                        )
                    except ValueError as e:
                        raise MissingWeightsError(str(e)) from None
                    unet_params = self._converted["unet3d"]
                else:
                    unet_params = _checked_converted(
                        self.unet, unet_args, self._converted["unet"],
                        "unet", k1,
                    )
                text_params = _checked_converted(
                    self.text_encoder, (jnp.zeros((1, 77), jnp.int32),),
                    self._converted["text"], "text", k2,
                )
                vae_params = _checked_converted(
                    self.vae,
                    (jnp.zeros((1, hw * self.latent_factor,
                                hw * self.latent_factor, 3)),),
                    self._converted["vae"], "vae", k3,
                )
                logger.info(
                    "loaded converted AnimateDiff weights for %s",
                    self.model_name,
                )
            else:
                unet_params = self.unet.init(k1, *unet_args)["params"]
                text_params = self.text_encoder.init(
                    k2, jnp.zeros((1, 77), jnp.int32)
                )["params"]
                vae_params = self.vae.init(
                    k3,
                    jnp.zeros(
                        (1, hw * self.latent_factor, hw * self.latent_factor, 3)
                    ),
                )["params"]
        cast = lambda x: jnp.asarray(x, self.dtype)
        return jax.tree_util.tree_map(
            cast, {"unet": unet_params, "text": text_params, "vae": vae_params}
        )

    def release(self):
        self.params = None
        self._programs.clear()
        self._lora_cache.clear()

    def _program(self, key):
        if key in self._programs:
            self._programs.move_to_end(key)
            return self._programs[key]
        lh, lw, frames, steps, sched_name = key
        scheduler = get_scheduler(sched_name)
        schedule = scheduler.schedule(steps)

        def run(params, latents, context, guidance_scale, rng):
            """latents [F, lh, lw, 4]; context [2, 77, D] = (uncond, cond)."""
            latents = latents * jnp.asarray(schedule.init_noise_sigma, latents.dtype)
            state = scheduler.init_state(latents.shape, latents.dtype)
            f = latents.shape[0]
            ctx2 = jnp.concatenate(
                [
                    jnp.broadcast_to(context[:1], (f,) + context.shape[1:]),
                    jnp.broadcast_to(context[1:2], (f,) + context.shape[1:]),
                ],
                axis=0,
            ).astype(self.dtype)

            def body(carry, i):
                latents, state = carry
                inp = scheduler.scale_model_input(schedule, latents, i)
                model_in = jnp.concatenate([inp, inp], axis=0).astype(self.dtype)
                t = jnp.broadcast_to(
                    jnp.asarray(schedule.timesteps)[i], (model_in.shape[0],)
                )
                out = self.unet.apply(
                    {"params": params["unet"]}, model_in, t, ctx2,
                    num_frames=f,
                ).astype(jnp.float32)
                out_u, out_c = jnp.split(out, 2, axis=0)
                out = out_u + guidance_scale * (out_c - out_u)
                noise = jax.random.normal(
                    jax.random.fold_in(rng, i), latents.shape, jnp.float32
                )
                state, latents = scheduler.step(schedule, state, i, latents, out, noise)
                return (latents, state), ()

            (latents, _), _ = jax.lax.scan(
                body, (latents.astype(jnp.float32), state), jnp.arange(steps)
            )
            return self.vae.apply(
                {"params": params["vae"]}, latents.astype(self.dtype),
                method=self.vae.decode,
            ).astype(jnp.float32)

        program = jax.jit(run)
        self._programs[key] = program
        from .common import PROGRAM_EVICTED, program_cache_cap

        cap = program_cache_cap()
        while cap and len(self._programs) > cap:
            self._programs.popitem(last=False)
            PROGRAM_EVICTED.inc(kind="program")
        return program

    def run(self, prompt="", negative_prompt="", image=None, **kwargs):
        # snapshot once: a concurrent registry eviction nulls self.params
        params = self.params
        if params is None:
            raise Exception(f"pipeline {self.model_name} was evicted; resubmit")
        timings = {}
        # requested AnimateDiff/LCM motion adapter (reference tx2vid.py:26-36
        # loads it onto the torch UNet per job). With converted weights the
        # requested adapter's temporal modules overlay the resident tree;
        # tiny/random pipelines record the request for observability.
        motion_adapter = kwargs.pop("motion_adapter", None)
        ignored_adapters = []
        if motion_adapter is not None and self._converted is not None:
            if self.unet3d:
                # a native UNet3D graph has no motion modules to overlay —
                # surface the ignored request instead of silently echoing
                # it as applied
                ignored_adapters.append(
                    f"motion_adapter:{motion_adapter}"
                )
                motion_adapter = None
            else:
                params = self._adapter_params(params, motion_adapter)
        lora = kwargs.pop("lora", None)
        xattn_kwargs = kwargs.pop("cross_attention_kwargs", {}) or {}
        lora_scale = float(
            kwargs.pop("lora_scale", xattn_kwargs.get("scale", 1.0))
        )
        if lora is not None:
            if self.unet3d:
                ignored_adapters.append(f"motion_lora:{lora}")
            else:
                params = self._lora_params(params, lora, lora_scale)
        steps = int(kwargs.pop("num_inference_steps", 25))
        guidance_scale = float(kwargs.pop("guidance_scale", 7.5))
        # AnimateDiff's positional table caps the clip length; the native
        # UNet3D graph has no positional embedding — its bound is memory,
        # budgeted generously here
        max_frames = 48 if self.unet3d else self.config.num_frames
        requested_frames = int(
            kwargs.pop("num_frames", 24 if self.unet3d
                       else self.config.num_frames)
        )
        frames = min(requested_frames, max_frames)
        frames_truncated = frames < requested_frames
        fps = int(kwargs.pop("fps", DEFAULT_FPS))
        scheduler_type = kwargs.pop(
            "scheduler_type", "EulerAncestralDiscreteScheduler"
        )
        rng = kwargs.pop("rng", None)
        if rng is None:
            rng = jax.random.key(0)
        height = int(kwargs.pop("height", None) or self.default_size)
        width = int(kwargs.pop("width", None) or self.default_size)
        height, width = (max(64, (d // 64) * 64) for d in (height, width))
        lh, lw = height // self.latent_factor, width // self.latent_factor

        ids = jnp.asarray(self.tokenizer([negative_prompt, prompt]))
        context = self.text_encoder.apply(
            {"params": params["text"]}, ids
        )["hidden_states"]

        rng, init_rng, step_rng = jax.random.split(rng, 3)
        noise = jax.random.normal(init_rng, (frames, lh, lw, 4), jnp.float32)

        key = (lh, lw, frames, steps, scheduler_type)
        t0 = time.perf_counter()
        program = self._program(key)
        from ..ops.attention import sequence_parallel_scope

        mesh = self.chipset.mesh() if self.chipset is not None else None
        with sequence_parallel_scope(mesh):
            pixels = jax.block_until_ready(
                program(params, noise, context, jnp.float32(guidance_scale),
                        step_rng)
            )
        timings["denoise_decode_s"] = round(time.perf_counter() - t0, 3)

        arr = np.clip(np.asarray(pixels, np.float32) * 0.5 + 0.5, 0, 1)
        pil_frames = [
            Image.fromarray((f * 255).round().astype(np.uint8)) for f in arr
        ]
        config = {
            "model": self.model_name,
            "frames": frames,
            "fps": fps,
            "steps": steps,
            "size": [width, height],
            "scheduler": scheduler_type,
            **(
                {"motion_adapter": str(motion_adapter)}
                if motion_adapter is not None
                else {}
            ),
            **({"ignored_adapters": ignored_adapters}
               if ignored_adapters else {}),
            **({"frames_truncated": True} if frames_truncated else {}),
            "timings": timings,
        }
        return pil_frames, config


@register_family("animatediff")
def _build_animatediff(model_name, chipset, **variant):
    return VideoPipeline(model_name, chipset, **variant)


# "svd" is owned by pipelines/svd.py and "i2vgenxl" by pipelines/i2vgen.py
# (true architectures with conversion).


def _frames_artifact(frames, fps, content_type):
    buffer, actual_type = export_frames(frames, content_type, fps)
    return make_result(buffer, first_frame_thumbnail(frames), actual_type)


def run_txt2vid(device_identifier: str, model_name: str, **kwargs):
    """txt2vid job -> video artifact (reference swarm/video/tx2vid.py:15-81)."""
    from ..registry import get_pipeline

    content_type = kwargs.pop("content_type", "video/mp4")
    kwargs.pop("outputs", None)
    if kwargs.pop("test_tiny_model", False):
        model_name = "test/tiny-video"
    # hive txt2vid jobs often say "DiffusionPipeline" (reference resolved it
    # reflectively); the workflow itself pins the video family
    from ..registry import PIPELINE_FAMILIES

    ptype = kwargs.pop("pipeline_type", "AnimateDiffPipeline")
    if PIPELINE_FAMILIES.get(ptype) != "animatediff":
        ptype = "AnimateDiffPipeline"
    chipset = kwargs.pop("chipset", None)
    pipeline = get_pipeline(model_name, pipeline_type=ptype, chipset=chipset)

    # motion-LoRA refs may ride parameters as bare strings — resolve them
    # through the same path resolver job-level loras use
    lora = kwargs.pop("lora", None)
    if isinstance(lora, str):
        from ..loras import Loras
        from ..settings import load_settings

        lora = Loras(load_settings().lora_root_dir).resolve_lora(lora)
    if lora is not None:
        kwargs["lora"] = lora

    # zeroscope-style upscale pass (reference tx2vid.py:66-76 chains
    # zeroscope_v2_XL over the produced clip): the learned 2x upscaler runs
    # over the frames; resolved BEFORE the denoise so missing weights fail
    # fast
    upscaler = None
    if kwargs.pop("upscale", False):
        from .upscale import upscaler_name_for

        upscaler = get_pipeline(
            upscaler_name_for(model_name),
            pipeline_type="StableDiffusionLatentUpscalePipeline",
            chipset=chipset,
        )

    prompt = kwargs.get("prompt", "")
    frames, config = pipeline.run(**kwargs)
    if upscaler is not None:
        t0 = time.perf_counter()
        frames = upscaler.upscale(frames, prompt=prompt)
        config.setdefault("timings", {})["upscale_s"] = round(
            time.perf_counter() - t0, 3
        )
        config["upscaled"] = True
        config["output_size"] = [frames[0].width, frames[0].height]
    return {"primary": _frames_artifact(frames, config["fps"], content_type)}, config


def run_img2vid(device_identifier: str, model_name: str, **kwargs):
    """img2vid job (reference swarm/video/img2vid.py:14-38)."""
    from ..registry import get_pipeline

    content_type = kwargs.pop("content_type", "video/mp4")
    kwargs.pop("outputs", None)
    if kwargs.pop("test_tiny_model", False):
        model_name = "test/tiny-video-svd"
    pipeline = get_pipeline(
        model_name,
        pipeline_type=kwargs.pop("pipeline_type", "I2VGenXLPipeline"),
        chipset=kwargs.pop("chipset", None),
    )
    # decode_chunk_size is a CUDA-memory knob with no TPU analog (the whole
    # decode is one program); SVD's micro-conditioning keys pass through
    kwargs.pop("decode_chunk_size", None)
    if not getattr(pipeline, "accepts_micro_conditioning", False):
        for drop in ("motion_bucket_id", "noise_aug_strength"):
            kwargs.pop(drop, None)
    frames, config = pipeline.run(**kwargs)
    return {"primary": _frames_artifact(frames, config["fps"], content_type)}, config


def run_vid2vid(device_identifier: str, model_name: str, **kwargs):
    """vid2vid: chunked-batch frame editing (reference swarm/video/pix2pix.py).

    The reference's hot loop — one full pipeline invocation per frame — runs
    as batched img2img: VID2VID_CHUNK frames per jitted call, one compile.
    """
    from ..registry import get_pipeline

    content_type = kwargs.pop("content_type", "video/mp4")
    kwargs.pop("outputs", None)
    video_uri = kwargs.pop("video_uri", None)
    if video_uri is None:
        raise ValueError("vid2vid requires a video_uri. None provided")
    if kwargs.pop("test_tiny_model", False):
        model_name = "test/tiny-sd"

    path = download_video(video_uri)
    try:
        frames, fps = split_video_frames(path)
    finally:
        os.unlink(path)

    pipeline = get_pipeline(
        model_name,
        pipeline_type=kwargs.pop(
            "pipeline_type", "StableDiffusionInstructPix2PixPipeline"
        ),
        chipset=kwargs.pop("chipset", None),
    )
    rng = kwargs.pop("rng", None)
    if rng is None:
        rng = jax.random.key(0)
    prompt = kwargs.pop("prompt", "")
    steps = int(kwargs.pop("num_inference_steps", 25))
    strength = float(kwargs.pop("strength", 0.6))
    # edit-tuned checkpoints consume dual-guidance strength; non-pix2pix
    # models ignore it and fall back to strength-based img2img (recorded as
    # approximated_as in the per-chunk config)
    image_guidance = kwargs.pop("image_guidance_scale", None)

    # size-normalize all frames so every chunk hits the same program bucket
    w, h = frames[0].size
    frames = [f if f.size == (w, h) else f.resize((w, h)) for f in frames]

    out_frames = []
    edit_mode = None
    t0 = time.perf_counter()
    for start in range(0, len(frames), VID2VID_CHUNK):
        chunk = frames[start : start + VID2VID_CHUNK]
        pad = VID2VID_CHUNK - len(chunk)
        run_kw = dict(
            prompt=prompt,
            image=chunk + [chunk[-1]] * pad,  # pad partial chunk, slice below
            strength=strength,
            num_inference_steps=steps,
            rng=jax.random.fold_in(rng, start),
        )
        if image_guidance is not None:
            run_kw["image_guidance_scale"] = image_guidance
        images, chunk_cfg = pipeline.run(**run_kw)
        edit_mode = chunk_cfg.get("approximated_as", chunk_cfg.get("mode"))
        out_frames.extend(images[: len(chunk)])
    config = {
        "model": model_name,
        "frames": len(frames),
        "fps": fps,
        "mode": edit_mode,
        # reference cost metric (swarm/video/pix2pix.py:79)
        "compute_cost": 512 * 512 * steps * len(frames),
        "timings": {"edit_s": round(time.perf_counter() - t0, 3)},
    }
    return {"primary": _frames_artifact(out_frames, int(fps), content_type)}, config
