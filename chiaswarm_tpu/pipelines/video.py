"""Video diffusion pipelines (reference swarm/video/*)."""

from __future__ import annotations


def run_txt2vid(device_identifier: str, model_name: str, **kwargs):
    raise Exception(
        f"txt2vid is not yet available on this worker (model {model_name})."
    )


def run_img2vid(device_identifier: str, model_name: str, **kwargs):
    raise Exception(
        f"img2vid is not yet available on this worker (model {model_name})."
    )


def run_vid2vid(device_identifier: str, model_name: str, **kwargs):
    raise Exception(
        f"vid2vid is not yet available on this worker (model {model_name})."
    )
