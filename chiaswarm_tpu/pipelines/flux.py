"""Resident Flux pipeline: rectified-flow txt2img on the MMDiT transformer.

Reference behavior replaced: FluxPipeline jobs at bf16 with *sequential CPU
offload* to fit CUDA VRAM (swarm/test.py:244-290, job_arguments large-model
branches) — a per-job `from_pretrained` plus layer-by-layer host<->device
shuffling. TPU design: weights are resident, the whole sampling loop is one
jitted `lax.scan` (flow-matching Euler over resolution-shifted sigmas), and
memory scaling comes from mesh sharding, not offload.

Flux-dev carries distilled guidance as an *embedding input* — there is no
CFG batch doubling, so batch = N images (half the UNet-family cost per
image at the same step count). Schnell ignores guidance entirely.
"""

from __future__ import annotations

import logging
import threading
import time
import zlib
from collections import OrderedDict
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

from ..models import configs as cfgs
from ..models.clip import CLIPTextEncoder
from ..models.flux import (
    FINAL_KEYS,
    HEAD_KEYS,
    TINY_FLUX,
    DoubleStreamBlock,
    FluxConfig,
    FluxFinal,
    FluxHead,
    FluxTransformer,
    SingleStreamBlock,
    patchify,
    rope_frequencies,
    unpatchify,
)
from ..models.t5 import TINY_T5, T5Config, T5Encoder
from ..models.tokenizer import load_tokenizer
from ..models.vae import AutoencoderKL
from ..parallel.mesh import batch_sharding, make_mesh, replicated
from ..registry import register_family
from ..schedulers import FlowMatchEulerScheduler
from ..schedulers.common import SchedulerConfig
from ..settings import load_settings
from ..weights import require_weights_present

logger = logging.getLogger(__name__)


def _flux_configs(model_name: str):
    """(flux_cfg, t5_cfg, clip_cfg, vae_cfg, default_size, default_steps,
    dynamic_shift). schnell is distilled on UNSHIFTED sigmas (shift=1);
    dev uses resolution-dependent dynamic shifting (see _sigma_shift)."""
    import dataclasses

    name = model_name.lower()
    schnell = "schnell" in name
    if "tiny" in name or name.startswith("test/"):
        flux = TINY_FLUX
        if schnell:
            flux = dataclasses.replace(flux, guidance_embed=False)
        return flux, TINY_T5, cfgs.TINY_CLIP, cfgs.TINY_VAE, 64, 4, not schnell
    if schnell:
        return (
            dataclasses.replace(FluxConfig(), guidance_embed=False),
            T5Config(), cfgs.SD15_CLIP, cfgs.FLUX_VAE, 1024, 4, False,
        )
    return FluxConfig(), T5Config(), cfgs.SD15_CLIP, cfgs.FLUX_VAE, 1024, 28, True


def _sigma_shift(image_seq_len: int, dynamic: bool) -> float:
    """Flow-matching sigma shift for the sampling schedule.

    Dev-family checkpoints use dynamic shifting: mu interpolates linearly
    with the image token count between (256, 0.5) and (4096, 1.15), and the
    trained time warp is t' = exp(mu)*t / (1 + (exp(mu)-1)*t) — exactly our
    scheduler's `shift` parameter with shift = exp(mu). Schnell is distilled
    on the unshifted schedule (shift = 1).
    """
    if not dynamic:
        return 1.0
    import math

    m = (1.15 - 0.5) / (4096 - 256)
    mu = 0.5 + m * (image_seq_len - 256)
    return math.exp(mu)


class FluxPipeline:
    """One resident Flux bundle per (model, slice)."""

    def __init__(self, model_name: str, chipset=None, dtype=None,
                 allow_random_init: bool = False,
                 streaming: bool | None = None):
        self.model_name = model_name
        self.chipset = chipset
        (self.config, t5_cfg, clip_cfg, vae_cfg, self.default_size,
         self.default_steps, self.dynamic_shift) = _flux_configs(model_name)
        if dtype is None:
            dtype = jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
        self.dtype = dtype

        self.transformer = FluxTransformer(self.config, dtype=dtype)
        self.t5 = T5Encoder(t5_cfg, dtype=dtype)
        self.clip = CLIPTextEncoder(clip_cfg, dtype=dtype)
        self.vae = AutoencoderKL(vae_cfg, dtype=dtype)
        self.latent_factor = 2 ** (len(vae_cfg.block_out_channels) - 1)
        self.latent_channels = vae_cfg.latent_channels
        self.mesh = (
            chipset.mesh() if chipset is not None else make_mesh(jax.devices()[:1])
        )
        self.data_parts = self.mesh.shape.get("data", 1)
        self.tensor_parts = self.mesh.shape.get("tensor", 1)

        if streaming is None:
            # auto: page transformer blocks from host RAM when the model
            # cannot sit resident on this slice (the TPU analog of the
            # reference's enable_sequential_cpu_offload — VERDICT r04 #2).
            # Same flux_admissible rule as the job gate and the worker's
            # flux_runnable advertisement — stream exactly when admission
            # came from the streaming arm.
            from ..chips.requirements import flux_admissible

            if chipset is None:
                streaming = False
            else:
                _, mode = flux_admissible(
                    chipset, 1, self.default_size, model_name=model_name)
                streaming = mode == "streaming"
        self.streaming = bool(streaming)
        self._host_double: list = []
        self._host_single: list = []
        self._stream_int8 = False  # set for real in _place_streaming

        t0 = time.perf_counter()
        self.params = self._load_params(allow_random_init)
        model_dir = self._model_dir()
        self.clip_tokenizer = load_tokenizer(model_dir, clip_cfg.vocab_size)
        self.t5_tokenizer = _load_t5_tokenizer(model_dir, t5_cfg.vocab_size)
        logger.info("%s resident in %.1fs (dtype=%s)", model_name,
                    time.perf_counter() - t0, dtype)

        self._jit_lock = threading.Lock()
        # insertion-ordered so the program_cache_max bound below can evict
        # least-recently-used first (SW007; same knob as the SD family)
        self._programs: OrderedDict = OrderedDict()
        self._encode_program = jax.jit(self._encode_impl)

    def _model_dir(self) -> Path | None:
        root = Path(load_settings().model_root_dir).expanduser()
        d = root / self.model_name
        return d if d.is_dir() else None

    def _place(self, params):
        if self.streaming:
            return self._place_streaming(params)
        cast = lambda x: jnp.asarray(x, self.dtype)
        params = jax.tree_util.tree_map(cast, params)
        if self.tensor_parts <= 1:
            return jax.device_put(params, replicated(self.mesh))
        from ..parallel.tensor import shard_params

        placed = {}
        for key, tree in params.items():
            if key == "vae":
                placed[key] = jax.device_put(tree, replicated(self.mesh))
            else:
                placed[key] = shard_params(self.mesh, tree)
        return placed

    def _place_streaming(self, params):
        """Resident tail (T5/CLIP/VAE + flux head/final) on the chip;
        transformer blocks stay in HOST RAM (serving-dtype jax CPU arrays,
        halving the per-step PCIe traffic vs f32 — or int8 with
        per-channel scales when flux_stream_int8 is on, halving it again)
        and page through the chip double-buffered during sampling."""
        cfg = self.config
        cpu = jax.local_devices(backend="cpu")[0]
        flux = params["flux"]
        self._stream_int8 = bool(load_settings().flux_stream_int8)
        if self._stream_int8:
            from ..ops.quant import quantize_tree

            pack = lambda tree: quantize_tree(tree, self.dtype)
        else:
            pack = lambda tree: jax.tree_util.tree_map(
                lambda x: jnp.asarray(x, self.dtype), tree)
        with jax.default_device(cpu):
            self._host_double = [
                pack(flux[f"double_blocks_{i}"])
                for i in range(cfg.depth_double)
            ]
            self._host_single = [
                pack(flux[f"single_blocks_{i}"])
                for i in range(cfg.depth_single)
            ]
        cast = lambda x: jnp.asarray(x, self.dtype)
        resident = {
            "flux": {k: flux[k] for k in (*HEAD_KEYS, *FINAL_KEYS)
                     if k in flux},
            "t5": params["t5"], "clip": params["clip"], "vae": params["vae"],
        }
        resident = jax.tree_util.tree_map(cast, resident)
        return jax.device_put(resident, replicated(self.mesh))

    def _load_params(self, allow_random_init: bool) -> dict:
        model_dir = self._model_dir()
        if model_dir is not None:
            try:
                return self._convert_params(model_dir)
            except FileNotFoundError:
                require_weights_present(
                    self.model_name, model_dir, allow_random_init
                )
                logger.warning("no safetensors under %s; random init", model_dir)
        else:
            require_weights_present(self.model_name, None, allow_random_init)

        cfg = self.config
        seed = zlib.crc32(self.model_name.encode())
        k1, k2, k3, k4 = jax.random.split(jax.random.key(seed), 4)
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            s_img, s_txt = 4, 8
            flux_params = self.transformer.init(
                k1,
                jnp.zeros((1, s_img, cfg.in_channels)),
                jnp.zeros((1, s_img, 3), jnp.int32),
                jnp.zeros((1, s_txt, cfg.context_dim)),
                jnp.zeros((1, s_txt, 3), jnp.int32),
                jnp.zeros((1,)),
                jnp.zeros((1, cfg.pooled_dim)),
                guidance=jnp.ones((1,)),
            )["params"]
            t5_params = self.t5.init(k2, jnp.zeros((1, 8), jnp.int32))["params"]
            clip_params = self.clip.init(k3, jnp.zeros((1, 77), jnp.int32))["params"]
            hw = 2 * self.latent_factor
            vae_params = self.vae.init(k4, jnp.zeros((1, hw, hw, 3)))["params"]
        return self._place({
            "flux": flux_params, "t5": t5_params, "clip": clip_params,
            "vae": vae_params,
        })

    def _convert_params(self, model_dir: Path) -> dict:
        from ..models.conversion import (
            convert_clip,
            convert_flux,
            convert_t5,
            convert_vae,
            load_torch_state_dict,
        )

        params = {
            "flux": convert_flux(load_torch_state_dict(model_dir, "transformer")),
            "t5": convert_t5(load_torch_state_dict(model_dir, "text_encoder_2")),
            "clip": convert_clip(load_torch_state_dict(model_dir, "text_encoder")),
            "vae": convert_vae(load_torch_state_dict(model_dir, "vae")),
        }
        return self._place(params)

    def release(self):
        self.params = None
        self._programs.clear()
        self._host_double = []
        self._host_single = []
        if hasattr(self, "_sfns"):
            del self._sfns

    # --- conditioning ---

    def _encode_impl(self, params, clip_ids, t5_ids):
        pooled = self.clip.apply({"params": params["clip"]}, clip_ids)["pooled"]
        context = self.t5.apply({"params": params["t5"]}, t5_ids)
        return context, pooled

    # --- sampling program ---

    def _program(self, key: tuple):
        with self._jit_lock:
            if key in self._programs:
                self._programs.move_to_end(key)
                return self._programs[key]
        lh, lw, batch, steps, txt_len = key
        shift = _sigma_shift((lh // 2) * (lw // 2), self.dynamic_shift)
        scheduler = FlowMatchEulerScheduler(
            SchedulerConfig(prediction_type="flow", shift=shift)
        )
        schedule = scheduler.schedule(steps)
        sigmas = jnp.asarray(schedule.sigmas)
        transformer = self.transformer
        vae = self.vae
        latent_c = self.latent_channels

        def run(params, init_rng, context, pooled, guidance):
            latents = jax.random.normal(
                init_rng, (batch, lh, lw, latent_c), jnp.float32
            )
            img, img_ids = patchify(latents.astype(self.dtype))
            txt_ids = jnp.zeros((batch, txt_len, 3), jnp.int32)

            def body(img, i):
                t = jnp.broadcast_to(sigmas[i], (batch,))
                velocity = transformer.apply(
                    {"params": params["flux"]},
                    img.astype(self.dtype),
                    img_ids,
                    context,
                    txt_ids,
                    t,
                    pooled,
                    guidance=guidance,
                ).astype(jnp.float32)
                img = img.astype(jnp.float32) + (
                    sigmas[i + 1] - sigmas[i]
                ) * velocity
                return img, ()

            img, _ = jax.lax.scan(body, img.astype(jnp.float32),
                                  jnp.arange(steps))
            latents = unpatchify(img, lh, lw).astype(self.dtype)
            pixels = vae.apply(
                {"params": params["vae"]}, latents, method=vae.decode
            )
            return (
                (pixels.astype(jnp.float32) + 1.0) * 127.5
            ).clip(0.0, 255.0).round().astype(jnp.uint8)

        program = jax.jit(run)
        with self._jit_lock:
            self._programs[key] = program
            from .common import PROGRAM_EVICTED, program_cache_cap

            cap = program_cache_cap()
            while cap and len(self._programs) > cap:
                self._programs.popitem(last=False)
                PROGRAM_EVICTED.inc(kind="program")
        return program

    # --- weight-streaming sampler (host-RAM paged transformer blocks) ---

    def _stream_fns(self) -> dict:
        """Jitted per-block programs: ONE executable per block type is
        reused by all 19/38 block instances (identical shapes/structure),
        so compile cost is constant, not per-block."""
        with self._jit_lock:
            if hasattr(self, "_sfns"):
                return self._sfns
        cfg, dtype = self.config, self.dtype
        head = FluxHead(cfg, dtype=dtype)
        final = FluxFinal(cfg, dtype=dtype)
        dbl = DoubleStreamBlock(cfg, dtype=dtype)
        sgl = SingleStreamBlock(cfg, dtype=dtype)
        vae = self.vae
        if self._stream_int8:
            # transfers stay int8 over PCIe; the dequant runs on-chip as
            # part of the same jitted block program
            from ..ops.quant import dequantize_tree

            dq = lambda p: dequantize_tree(p, dtype)
        else:
            dq = lambda p: p
        fns = {
            "head": jax.jit(lambda p, img, txt, t, pooled, g: head.apply(
                {"params": p}, img, txt, t, pooled, guidance=g)),
            "double": jax.jit(lambda p, img, txt, vec, cos, sin: dbl.apply(
                {"params": dq(p)}, img, txt, vec, cos, sin)),
            "single": jax.jit(lambda p, x, vec, cos, sin: sgl.apply(
                {"params": dq(p)}, x, vec, cos, sin)),
            "final": jax.jit(lambda p, x, vec: final.apply(
                {"params": p}, x, vec)),
            "euler": jax.jit(lambda img, v, ds: (
                img.astype(jnp.float32) + ds * v.astype(jnp.float32))),
            "decode": jax.jit(lambda p, lat: (
                (vae.apply({"params": p}, lat, method=vae.decode)
                 .astype(jnp.float32) + 1.0) * 127.5
            ).clip(0.0, 255.0).round().astype(jnp.uint8)),
        }
        with self._jit_lock:
            self._sfns = fns
        return fns

    def _run_streaming(self, lh, lw, batch, steps, txt_len, init_rng,
                       context, pooled, guidance):
        """Python-loop sampler: per step, page every transformer block
        through the chip. `jax.device_put` is async, so issuing block
        i+1's transfer BEFORE dispatching block i's compute overlaps PCIe
        with the MXU — the same pipelining trick as the reference's
        sequential offload, minus the per-job from_pretrained."""
        cfg = self.config
        fns = self._stream_fns()
        shift = _sigma_shift((lh // 2) * (lw // 2), self.dynamic_shift)
        scheduler = FlowMatchEulerScheduler(
            SchedulerConfig(prediction_type="flow", shift=shift)
        )
        sigmas = np.asarray(scheduler.schedule(steps).sigmas, np.float32)

        params = self.params
        head_p = {k: params["flux"][k] for k in HEAD_KEYS
                  if k in params["flux"]}
        final_p = {k: params["flux"][k] for k in FINAL_KEYS
                   if k in params["flux"]}

        latents = jax.random.normal(
            init_rng, (batch, lh, lw, self.latent_channels), jnp.float32
        )
        carry, img_ids = patchify(latents)
        txt_ids = jnp.zeros((batch, txt_len, 3), jnp.int32)
        ids = jnp.concatenate([txt_ids, img_ids], axis=1)
        cos, sin = rope_frequencies(ids, cfg.axes_dims_rope, cfg.theta)
        cos, sin = cos.astype(self.dtype), sin.astype(self.dtype)

        # page blocks onto THIS pipeline's slice, not the process default
        # device — a 1-chip slice k>0 on a multi-chip host would otherwise
        # compute against device 0 (or pay a silent extra hop per block)
        target = replicated(self.mesh)
        page = lambda tree: jax.device_put(tree, target)

        for i in range(steps):
            t = jnp.broadcast_to(jnp.float32(sigmas[i]), (batch,))
            img, txt, vec = fns["head"](
                head_p, carry.astype(self.dtype), context, t, pooled,
                guidance,
            )
            # seed the prefetch from the first NON-EMPTY block list: a
            # config with depth_double == 0 must hand the first
            # SingleStreamBlock a real param tree, not None (ADVICE r05)
            if cfg.depth_double:
                nxt = page(self._host_double[0])
            elif cfg.depth_single:
                nxt = page(self._host_single[0])
            else:
                nxt = None
            for b in range(cfg.depth_double):
                cur = nxt
                if b + 1 < cfg.depth_double:
                    nxt = page(self._host_double[b + 1])
                elif cfg.depth_single:
                    nxt = page(self._host_single[0])
                img, txt = fns["double"](cur, img, txt, vec, cos, sin)
            x = jnp.concatenate([txt, img], axis=1)
            for b in range(cfg.depth_single):
                cur = nxt
                if b + 1 < cfg.depth_single:
                    nxt = page(self._host_single[b + 1])
                x = fns["single"](cur, x, vec, cos, sin)
            x = x[:, txt_len:]
            velocity = fns["final"](final_p, x, vec)
            carry = fns["euler"](
                carry, velocity, jnp.float32(sigmas[i + 1] - sigmas[i])
            )

        latents = unpatchify(carry, lh, lw).astype(self.dtype)
        return fns["decode"](params["vae"], latents)

    # --- public job API ---

    def run(self, prompt="", negative_prompt="", pipeline_type="FluxPipeline",
            **kwargs):
        params = self.params
        if params is None:
            raise Exception(
                f"pipeline {self.model_name} was evicted; resubmit the job"
            )
        timings: dict[str, float] = {}
        steps = int(kwargs.pop("num_inference_steps", self.default_steps))
        guidance_scale = float(kwargs.pop("guidance_scale", 3.5))
        n_images = int(kwargs.pop("num_images_per_prompt", 1))
        max_seq = int(kwargs.pop("max_sequence_length", 512))
        rng = kwargs.pop("rng", None)
        if rng is None:
            rng = jax.random.key(0)
        kwargs.pop("chipset", None)
        kwargs.pop("scheduler_type", None)  # flow matching is the family's solver

        height = int(kwargs.pop("height", None) or self.default_size)
        width = int(kwargs.pop("width", None) or self.default_size)
        # latent grid must patchify 2x2: canvas snaps to /16 of pixel space
        snap = self.latent_factor * 2
        height, width = (max(snap, (d // snap) * snap) for d in (height, width))
        lh, lw = height // self.latent_factor, width // self.latent_factor

        t0 = time.perf_counter()
        clip_ids = jnp.asarray(self.clip_tokenizer([prompt] * n_images))
        t5_ids = jnp.asarray(
            self.t5_tokenizer([prompt] * n_images, max_seq), jnp.int32
        )
        context, pooled = self._encode_program(params, clip_ids, t5_ids)
        timings["text_encode_s"] = round(time.perf_counter() - t0, 3)

        def place_b(x):
            if self.data_parts > 1 and x.shape[0] % self.data_parts == 0:
                return jax.device_put(x, batch_sharding(self.mesh, x.ndim))
            return jax.device_put(x, replicated(self.mesh))

        context, pooled = place_b(context), place_b(pooled)
        guidance = jnp.full((n_images,), guidance_scale, jnp.float32)

        rng, init_rng = jax.random.split(rng)
        if self.streaming:
            t0 = time.perf_counter()
            pixels = jax.block_until_ready(
                self._run_streaming(
                    lh, lw, n_images, steps, int(t5_ids.shape[1]),
                    init_rng, context, pooled, guidance,
                )
            )
            timings["denoise_decode_s"] = round(time.perf_counter() - t0, 3)
        else:
            key = (lh, lw, n_images, steps, int(t5_ids.shape[1]))
            t0 = time.perf_counter()
            program = self._program(key)
            timings["trace_s"] = round(time.perf_counter() - t0, 3)

            t0 = time.perf_counter()
            pixels = jax.block_until_ready(
                program(params, init_rng, context, pooled, guidance)
            )
            timings["denoise_decode_s"] = round(time.perf_counter() - t0, 3)

        from PIL import Image

        images = [Image.fromarray(img) for img in np.asarray(pixels)]
        pipeline_config = {
            "model": self.model_name,
            "pipeline": pipeline_type,
            "scheduler": "FlowMatchEulerScheduler",
            "mode": "txt2img",
            "steps": steps,
            "size": [width, height],
            "guidance_scale": guidance_scale,
            "timings": timings,
        }
        if self.streaming:
            # visible in the envelope like the reference's offload mode:
            # slower, but serving on hardware the resident model outgrows
            pipeline_config["weight_streaming"] = True
            if self._stream_int8:
                pipeline_config["stream_int8"] = True
        return images, pipeline_config

    # --- coalesced txt2img (ISSUE 20: flux joins run_batched) ---

    def _batched_program(self, key: tuple):
        """Like _program, but the init latents arrive PRE-DRAWN: each
        request's rows are sampled eagerly from its own rng with the
        exact split + draw shape run() uses, so a coalesced row matches
        its solo twin to within one uint8 quantization step — the
        MMDiT/VAE programs are row-independent and nothing inside the
        jit depends on who a row was batched with; only XLA's
        batch-width vectorization can move the last float bit. Shares
        the LRU-bounded program cache with the solo entries (the
        leading "batched" tag keeps the two key shapes from
        colliding)."""
        with self._jit_lock:
            if key in self._programs:
                self._programs.move_to_end(key)
                return self._programs[key]
        _tag, lh, lw, batch, steps, txt_len = key
        shift = _sigma_shift((lh // 2) * (lw // 2), self.dynamic_shift)
        scheduler = FlowMatchEulerScheduler(
            SchedulerConfig(prediction_type="flow", shift=shift)
        )
        sigmas = jnp.asarray(scheduler.schedule(steps).sigmas)
        transformer = self.transformer
        vae = self.vae

        def run(params, latents, context, pooled, guidance):
            img, img_ids = patchify(latents.astype(self.dtype))
            txt_ids = jnp.zeros((batch, txt_len, 3), jnp.int32)

            def body(img, i):
                t = jnp.broadcast_to(sigmas[i], (batch,))
                velocity = transformer.apply(
                    {"params": params["flux"]},
                    img.astype(self.dtype),
                    img_ids,
                    context,
                    txt_ids,
                    t,
                    pooled,
                    guidance=guidance,
                ).astype(jnp.float32)
                img = img.astype(jnp.float32) + (
                    sigmas[i + 1] - sigmas[i]
                ) * velocity
                return img, ()

            img, _ = jax.lax.scan(body, img.astype(jnp.float32),
                                  jnp.arange(steps))
            latents = unpatchify(img, lh, lw).astype(self.dtype)
            pixels = vae.apply(
                {"params": params["vae"]}, latents, method=vae.decode
            )
            return (
                (pixels.astype(jnp.float32) + 1.0) * 127.5
            ).clip(0.0, 255.0).round().astype(jnp.uint8)

        program = jax.jit(run)
        with self._jit_lock:
            self._programs[key] = program
            from .common import PROGRAM_EVICTED, program_cache_cap

            cap = program_cache_cap()
            while cap and len(self._programs) > cap:
                self._programs.popitem(last=False)
                PROGRAM_EVICTED.inc(kind="program")
        return program

    def run_batched(self, requests: list[dict], *, height=None, width=None,
                    num_inference_steps=None, guidance_scale=3.5,
                    pipeline_type: str = "FluxPipeline", **_shared):
        """Coalesced flux txt2img: N independent requests, ONE padded
        jitted flow-matching pass (batching.py design; coalesce_key
        admits only the shapes this reproduces — txt2img, no adapters,
        no ControlNet, explicit steps + guidance). Per-row payload is
        prompt / rng / num_images_per_prompt; everything shared rides as
        keyword arguments. There is no CFG row doubling, so the pass
        batches exactly sum(rows) images padded to a power-of-two
        bucket.

        Returns [(images_j, pipeline_config_j)] aligned with requests.
        Raising here is fine: the worker's solo fallback serves the
        members individually (the contract SD's run_batched set)."""
        from .common import pad_bucket, split_by_counts

        params = self.params
        if params is None:
            raise Exception(
                f"pipeline {self.model_name} was evicted; resubmit the job"
            )
        if self.streaming:
            # the paged sampler is host-RAM-bound, not row-bound: wider
            # rows don't amortize the PCIe traffic, and the python-loop
            # sampler has no batched-latents seam — solo fallback
            raise ValueError(
                "weight-streaming flux serves members individually")
        if any(r.get("lora") for r in requests):
            raise ValueError("flux adapters serve on the single path")
        if any(r.get("image") is not None for r in requests):
            raise ValueError("flux has no coalesced img2img variant")

        timings: dict[str, float] = {}
        steps = int(num_inference_steps or self.default_steps)
        guidance_scale = float(guidance_scale)
        max_seq = 512
        height = int(height or self.default_size)
        width = int(width or height)
        snap = self.latent_factor * 2
        height, width = (max(snap, (d // snap) * snap) for d in (height, width))
        lh, lw = height // self.latent_factor, width // self.latent_factor

        counts = [
            max(int(r.get("num_images_per_prompt", 1) or 1), 1)
            for r in requests
        ]
        total = sum(counts)
        padded = pad_bucket(total)
        pad_rows = padded - total

        # --- conditioning: every row carries its own prompt; padding
        # rows are empty prompts whose outputs are discarded ---
        t0 = time.perf_counter()
        prompts: list[str] = []
        for r, n in zip(requests, counts):
            prompts.extend([str(r.get("prompt") or "")] * n)
        prompts.extend([""] * pad_rows)
        clip_ids = jnp.asarray(self.clip_tokenizer(prompts))
        t5_ids = jnp.asarray(self.t5_tokenizer(prompts, max_seq), jnp.int32)
        context, pooled = self._encode_program(params, clip_ids, t5_ids)
        timings["text_encode_s"] = round(time.perf_counter() - t0, 3)

        def place_b(x):
            if self.data_parts > 1 and x.shape[0] % self.data_parts == 0:
                return jax.device_put(x, batch_sharding(self.mesh, x.ndim))
            return jax.device_put(x, replicated(self.mesh))

        context, pooled = place_b(context), place_b(pooled)
        guidance = jnp.full((padded,), guidance_scale, jnp.float32)

        # --- per-request init latents, drawn EXACTLY as run() draws
        # them (split the request's rng, sample the request-shaped
        # block) so each row matches its solo twin; padding rows are
        # zeros a row-independent program never mixes in ---
        blocks = []
        for r, n in zip(requests, counts):
            base = r.get("rng")
            if base is None:
                base = jax.random.key(0)
            init_rng = jax.random.split(base)[1]
            blocks.append(jax.random.normal(
                init_rng, (n, lh, lw, self.latent_channels), jnp.float32))
        if pad_rows:
            blocks.append(jnp.zeros(
                (pad_rows, lh, lw, self.latent_channels), jnp.float32))
        latents = place_b(jnp.concatenate(blocks, axis=0))

        key = ("batched", lh, lw, padded, steps, int(t5_ids.shape[1]))
        t0 = time.perf_counter()
        program = self._batched_program(key)
        timings["trace_s"] = round(time.perf_counter() - t0, 3)

        t0 = time.perf_counter()
        pixels = jax.block_until_ready(
            program(params, latents, context, pooled, guidance)
        )
        timings["denoise_decode_s"] = round(time.perf_counter() - t0, 3)

        from PIL import Image

        groups = split_by_counts(
            [Image.fromarray(a) for a in np.asarray(pixels[:total])], counts)
        results = []
        offset = 0
        for n, images in zip(counts, groups):
            results.append((images, {
                "model": self.model_name,
                "pipeline": pipeline_type,
                "scheduler": "FlowMatchEulerScheduler",
                "mode": "txt2img",
                "steps": steps,
                "size": [width, height],
                "guidance_scale": guidance_scale,
                "batched_with": len(requests),
                "batch_rows": [offset, n],
                "padded_rows": padded,
                # shared pass timings, copied per envelope: the envelope
                # must stand alone once the hive splits the batch apart
                "timings": dict(timings),
            }))
            offset += n
        return results


class _HashT5Tokenizer:
    """Deterministic stand-in (tiny models / missing spiece.model)."""

    def __init__(self, vocab_size: int):
        self.vocab_size = vocab_size

    def __call__(self, texts: list[str], max_length: int):
        out = np.zeros((len(texts), max_length), np.int64)
        for r, text in enumerate(texts):
            ids = [zlib.crc32(w.encode()) % (self.vocab_size - 2) + 2
                   for w in text.lower().split()][: max_length - 1]
            ids.append(1)  # T5 EOS
            out[r, : len(ids)] = ids
        return out


class _SentencePieceT5Tokenizer:
    def __init__(self, model_path: Path):
        import sentencepiece

        self.sp = sentencepiece.SentencePieceProcessor(model_file=str(model_path))

    def __call__(self, texts: list[str], max_length: int):
        out = np.zeros((len(texts), max_length), np.int64)
        for r, text in enumerate(texts):
            ids = self.sp.encode(text)[: max_length - 1] + [1]  # EOS=1, PAD=0
            out[r, : len(ids)] = ids
        return out


def _load_t5_tokenizer(model_dir: Path | None, vocab_size: int):
    if model_dir is not None:
        for rel in ("tokenizer_2/spiece.model", "tokenizer/spiece.model",
                    "spiece.model"):
            path = model_dir / rel
            if path.is_file():
                try:
                    return _SentencePieceT5Tokenizer(path)
                except ImportError:
                    logger.warning(
                        "sentencepiece not installed; hash T5 tokenizer"
                    )
                    break
    return _HashT5Tokenizer(vocab_size)


@register_family("flux")
def _build_flux(model_name, chipset, **variant):
    return FluxPipeline(model_name, chipset, **variant)
