"""Jitted Flax inference pipelines (the TPU compute path).

Each module registers a pipeline family with the residency registry
(`..registry`). Modules are imported lazily by the registry / workflow
callbacks so the dispatch layer stays importable without model code.
"""
