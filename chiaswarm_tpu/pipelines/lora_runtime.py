"""Runtime per-row LoRA deltas inside the jitted denoise program.

The ISSUE 13 tentpole: instead of merging each adapter into a full COPY
of the base UNet tree (per-adapter HBM residency, no coalescing across
tenants), the padded batched program carries up to N adapters as STACKED
low-rank factors and computes, per batch row b with adapter slot s(b):

    y_b = W·x_b + gain_b · B[s(b)] · (A[s(b)] · x_b)

- ``A`` stacks are ``[N, r, in]`` and ``B`` stacks ``[N, out, r]`` per
  Dense module path, zero-padded in both the slot dim (slot 0 is always
  the zero adapter — adapter-free rows compute an exact zero delta) and
  the rank dim (every adapter pads to one shared power-of-two rank
  bucket; zero rows/cols keep B@A exact), so ONE compiled program serves
  any mix of adapters with those bucket dims — adapter identity is data,
  not program structure, and swapping adapters never recompiles.
- ``gain`` carries ``scale * (alpha/rank)`` per row (0 for no-adapter
  rows), so per-module alphas and per-job lora_scale ride per row too.

Injection uses flax's method interceptor (`nn.intercept_methods`) scoped
to the UNet apply alone: every `nn.Dense.__call__` whose module path has
a factor stack gets the low-rank correction added to its output. The
base model's params and HLO are untouched — a pass with an empty operand
dict traces to the identical program (pinned bitwise by tests).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from ..telemetry import counter as telemetry_counter

# image rows through SD denoise passes by adapter mode: "delta" rows had
# a runtime per-row delta applied, "merged" rows ran on a merged-tree
# param copy (the fallback path), "none" rows carried no adapter. The
# multi-tenant refactor's whole point is delta >> merged at scale.
LORA_ROWS = telemetry_counter(
    "swarm_lora_rows_total",
    "Image rows through denoise passes by adapter mode "
    "(delta | merged | none)",
    ("mode",),
)

# slot-count and rank buckets: each distinct (slots, rank) pair is one
# compiled program variant per shape bucket, so both snap to powers of
# two. MIN_RANK keeps trivial adapters from fragmenting the space; it
# AND the bucketing function are shared with the jax-free coalesce
# vocabulary so the rank buckets that gang jobs together are exactly the
# ones that compile together.
from ..coalesce import LORA_MIN_RANK as MIN_RANK
from ..coalesce import _pow2_bucket as pow2_bucket


class DeltaIneligibleError(ValueError):
    """A coalesced group carries adapters the runtime delta cannot
    express (conv/LoCon modules, rank past lora_rank_max). Carries the
    affected member job ids so the worker can RE-BATCH the eligible
    majority and route only these members through the solo merged-tree
    fallback — one slow adapter must not serialize its batchmates.
    Subclasses ValueError so callers without per-member identity (direct
    run_batched users) still get the classic whole-group solo fallback.
    """

    def __init__(self, job_ids):
        self.job_ids = [j for j in job_ids if j is not None]
        super().__init__(
            f"adapter(s) for jobs {self.job_ids or list(job_ids)} are not "
            "delta-eligible; merged-tree fallback")


def adapter_rank(factors: dict[str, tuple]) -> int:
    """The largest rank across an adapter's matched modules."""
    return max((np.asarray(a).shape[0] for a, _b, _al in factors.values()),
               default=0)


def stacks_sig(adapters: list[dict]) -> tuple:
    """The operand signature — (n_slot_bucket, rank_bucket,
    targeted-module-paths) — computed host-side WITHOUT assembling or
    uploading anything, so the operand-residency cache (lora_operands.py)
    can be consulted before any stacking work. The path set is part of
    the sig because it is the operand dict's PYTREE STRUCTURE: two
    adapters hitting different Dense subsets would otherwise silently
    retrace inside one cached jit wrapper."""
    n_slots = pow2_bucket(1 + len(adapters))
    ranks = [adapter_rank(f) for f in adapters]
    r_bucket = pow2_bucket(max([MIN_RANK] + ranks))
    paths = sorted({p for f in adapters for p in f})
    return (n_slots, r_bucket, tuple(paths))


def build_stacks(adapters: list[dict], dtype,
                 sig: tuple | None = None) -> tuple[dict, dict, int]:
    """Assemble + upload the per-path A/B stacks — the expensive leg
    (host numpy assembly then `jnp.asarray` device transfer). Returns
    (a_map, b_map, nbytes) where nbytes is the device footprint the
    residency cache charges for the pair. Scale-INDEPENDENT by
    construction: the per-module ``alpha/rank`` folds into A here
    (adapter-intrinsic), while the job's ``lora_scale`` rides the
    per-row gain vector (row_operands), so one resident stack serves
    the same adapter at any scale."""
    if sig is None:
        sig = stacks_sig(adapters)
    n_slots, r_bucket, paths = sig
    a_map: dict[str, jnp.ndarray] = {}
    b_map: dict[str, jnp.ndarray] = {}
    nbytes = 0
    for path in paths:
        a_stack = b_stack = None
        for slot, factors in enumerate(adapters, start=1):
            entry = factors.get(path)
            if entry is None:
                continue
            a, b, alpha = entry
            a = np.asarray(a, np.float32)
            b = np.asarray(b, np.float32)
            rank = a.shape[0]
            if a_stack is None:
                a_stack = np.zeros((n_slots, r_bucket, a.shape[1]), np.float32)
                b_stack = np.zeros((n_slots, b.shape[0], r_bucket), np.float32)
            # per-module alpha/rank folds into A so one per-row gain
            # (the job's lora_scale) serves modules with distinct alphas
            eff = (alpha / rank) if alpha is not None else 1.0
            a_stack[slot, :rank, :] = eff * a
            b_stack[slot, :, :rank] = b
        a_map[path] = jnp.asarray(a_stack, dtype)
        b_map[path] = jnp.asarray(b_stack, dtype)
        nbytes += a_map[path].nbytes + b_map[path].nbytes
    return a_map, b_map, nbytes


def row_operands(a_map: dict, b_map: dict, row_slots: list[int],
                 row_gains: list[float]) -> dict:
    """Join (possibly cache-resident) stacks with the pass's tiny
    per-row slot/gain vectors into the jitted program's lora operand.
    ``row_slots``/``row_gains`` are per BATCH ROW (pre-CFG; the step
    body tiles them over the CFG rows)."""
    return {
        "a": a_map,
        "b": b_map,
        "slot": jnp.asarray(np.asarray(row_slots, np.int32)),
        "gain": jnp.asarray(np.asarray(row_gains, np.float32)),
    }


def build_operands(adapters: list[dict], row_slots: list[int],
                   row_gains: list[float], dtype) -> tuple[dict, tuple]:
    """Stack per-slot factors into the jitted program's lora operand.

    ``adapters``: matched factor dicts ({path: (A, B, alpha)}), one per
    occupied slot, slot numbers 1..len(adapters) — slot 0 is the
    implicit zero adapter. Returns (operands, sig); same sig => same
    compiled program, any adapters. The uncached composition of
    stacks_sig + build_stacks + row_operands — the residency-aware path
    (SDPipeline._lora_operands) calls the legs separately so a repeat
    gang skips build_stacks entirely.
    """
    sig = stacks_sig(adapters)
    a_map, b_map, _nbytes = build_stacks(adapters, dtype, sig)
    return row_operands(a_map, b_map, row_slots, row_gains), sig


def _path_interceptor(a_map: dict, b_map: dict, slots, gains, prefix: str):
    """The shared Dense-call interceptor body: every `nn.Dense.__call__`
    whose (prefixed) module path has a factor stack gets the per-row
    low-rank correction added to its output. Dense calls whose leading
    dim is not the expected batch pass through untouched."""
    rows = slots.shape[0]

    def interceptor(next_fun, args, kwargs, context):
        if (context.method_name != "__call__"
                or not isinstance(context.module, nn.Dense)):
            return next_fun(*args, **kwargs)
        key = prefix + "/".join(context.module.path)
        stack_a = a_map.get(key)
        if stack_a is None:
            return next_fun(*args, **kwargs)
        x = args[0]
        if getattr(x, "ndim", 0) < 2 or x.shape[0] != rows:
            return next_fun(*args, **kwargs)
        y = next_fun(*args, **kwargs)
        stack_b = b_map[key]
        a = jnp.take(stack_a, slots, axis=0)  # [rows, r, in]
        b = jnp.take(stack_b, slots, axis=0)  # [rows, out, r]
        if x.ndim == 2:
            low = jnp.einsum("bi,bri->br", x, a)
            delta = jnp.einsum("br,bor->bo", low, b)
            delta = delta * gains[:, None]
        else:
            low = jnp.einsum("bsi,bri->bsr", x, a)
            delta = jnp.einsum("bsr,bor->bso", low, b)
            delta = delta * gains[:, None, None]
        return y + delta.astype(y.dtype)

    return interceptor


def make_interceptor(operands: dict, cfg_rows: int):
    """Flax method interceptor applying the stacked per-row deltas to
    every targeted Dense inside ONE unet apply. ``operands['slot']`` /
    ``['gain']`` are per batch row; the UNet sees the CFG-tiled batch
    (uncond rows first), so both tile by ``cfg_rows`` here. Text-encoder
    paths in the stacks carry a ``te{i}:`` prefix, which can never equal
    a flax module path (':' is not a module-name character), so one
    shared stack map serves both interceptors without cross-matching."""
    slots = jnp.tile(operands["slot"], (cfg_rows,))
    gains = jnp.tile(operands["gain"], (cfg_rows,)).astype(jnp.float32)
    return _path_interceptor(operands["a"], operands["b"], slots, gains, "")


def make_te_interceptor(operands: dict, enc_index: int):
    """Interceptor for ONE text-encoder apply (ISSUE 16 tentpole part
    2): stacks are looked up under the ``te{enc_index}:`` namespace the
    TE-aware matcher emits, and ``operands['slot']``/``['gain']`` are
    already per TEXT ROW (the encoder batch is the text batch — no CFG
    tiling; callers lay slots out to match their negs+prompts rows)."""
    slots = operands["slot"]
    gains = operands["gain"].astype(jnp.float32)
    return _path_interceptor(operands["a"], operands["b"], slots, gains,
                             f"te{enc_index}:")
