"""Resident NSFW safety checker feeding the result-envelope flag.

Reference behavior replaced: diffusers' bundled safety checker whose
output rides `nsfw_content_detected` into the envelope
(swarm/worker.py:166). Policy here: the checker is *auxiliary* — when its
weights aren't on the worker the job still serves (flag False,
`nsfw_checked: false` recorded) rather than failing; tiny/test model
names random-init for hermetic tests.
"""

from __future__ import annotations

import logging
import threading
import zlib
from pathlib import Path

import numpy as np

logger = logging.getLogger(__name__)

DEFAULT_SAFETY_MODEL = "CompVis/stable-diffusion-safety-checker"
# CLIP image normalization
_MEAN = np.asarray([0.48145466, 0.4578275, 0.40821073], np.float32)
_STD = np.asarray([0.26862954, 0.26130258, 0.27577711], np.float32)

_CHECKER = None
_CHECKER_NAME = None
_LOCK = threading.Lock()


class NSFWChecker:
    def __init__(self, model_name: str = DEFAULT_SAFETY_MODEL):
        import jax
        import jax.numpy as jnp

        from ..models.safety import SafetyChecker, SafetyConfig, TINY_SAFETY
        from ..settings import load_settings
        from ..weights import is_test_model

        self.model_name = model_name
        self.config = TINY_SAFETY if is_test_model(model_name) else SafetyConfig()
        on_tpu = jax.default_backend() == "tpu"
        self.dtype = jnp.bfloat16 if on_tpu else jnp.float32
        self.model = SafetyChecker(self.config, dtype=self.dtype)
        self.available = False

        root = Path(load_settings().model_root_dir).expanduser()
        model_dir = root / model_name
        params = None
        if model_dir.is_dir():
            try:
                from ..models.conversion import (
                    convert_safety_checker,
                    load_torch_state_dict,
                )

                params = convert_safety_checker(load_torch_state_dict(model_dir))
                self.available = bool(params.get("vision"))
            except FileNotFoundError:
                params = None
        if params is None or not self.available:
            if is_test_model(model_name):
                size = self.config.image_size
                params = self.model.init(
                    jax.random.key(zlib.crc32(model_name.encode())),
                    jnp.zeros((1, size, size, 3)),
                )["params"]
                self.available = True
            else:
                logger.warning(
                    "safety checker %s not present; NSFW flag disabled",
                    model_name,
                )
                self.params = None
                return
        cast = lambda x: jnp.asarray(x, self.dtype)
        self.params = jax.tree_util.tree_map(cast, params)
        self._program = jax.jit(
            lambda p, px: self.model.apply({"params": p}, px)
        )

    def check(self, images) -> list[bool] | None:
        """PIL images -> per-image NSFW booleans; None when unavailable."""
        if not self.available:
            return None
        import jax.numpy as jnp
        from PIL import Image

        size = self.config.image_size
        batch = np.stack([
            (
                np.asarray(
                    im.convert("RGB").resize((size, size), Image.BICUBIC),
                    np.float32,
                ) / 255.0 - _MEAN
            ) / _STD
            for im in images
        ])
        flags = self._program(self.params, jnp.asarray(batch, self.dtype))
        return [bool(f) for f in np.asarray(flags)]


class _DisabledChecker:
    available = False

    def check(self, images):
        return None


def get_checker(model_name: str | None = None):
    global _CHECKER, _CHECKER_NAME
    if model_name is None:
        from ..settings import load_settings

        model_name = getattr(
            load_settings(), "safety_checker_model", DEFAULT_SAFETY_MODEL
        )
    if not model_name:  # settings contract: "" disables the checker
        return _DisabledChecker()
    with _LOCK:
        if _CHECKER is not None and _CHECKER_NAME == model_name:
            return _CHECKER
        try:
            checker = NSFWChecker(model_name)
        except Exception as e:  # noqa: BLE001 — corrupt checkpoint etc.
            logger.warning(
                "safety checker %s failed to load (%s); NSFW flag disabled",
                model_name, e,
            )
            checker = _DisabledChecker()  # cache: don't re-parse per job
        _CHECKER, _CHECKER_NAME = checker, model_name
        return checker


def flag_images(images) -> tuple[bool, bool]:
    """-> (any_nsfw, checked). Never raises — auxiliary subsystem."""
    try:
        flags = get_checker().check(images)
    except Exception as e:  # noqa: BLE001 — must not fail the job
        logger.warning("safety check failed: %s", e)
        return False, False
    if flags is None:
        return False, False
    return any(flags), True
