"""I2VGenXL pipeline — the reference's DEFAULT img2vid path
(swarm/job_arguments.py:143 resolves img2vid jobs to I2VGenXLPipeline and
swarm/video/img2vid.py:14-38 runs it with the shipped scheduler and
default guidance).

TPU redesign: the same resident one-scan shape as SVD — CLIP text encode
(pos+neg rows) and CLIP-vision image embedding once per job, the
first-frame VAE latents + position-ramp frames assembled host-side, then
one jitted `lax.scan` DDIM denoise over a CFG batch of 2 (unconditional
row: negative text + ZEROED image embedding, same image latents) and a
per-frame chunked VAE decode in the same program. Real checkpoints
convert at load (conversion.py convert_i2vgen_unet + CLIP/vision/VAE
converters, geometry inferred from the checkpoints).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import threading
import time
import zlib
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np
from PIL import Image

from ..models import configs as cfgs
from ..models.clip import CLIPTextEncoder
from ..models.i2vgen import TINY_I2VGEN, I2VGenConfig, I2VGenXLUNet
from ..models.safety import TINY_SAFETY, CLIPVisionEncoder, SafetyConfig
from ..models.tokenizer import load_tokenizer
from ..models.vae import AutoencoderKL
from ..parallel.mesh import make_mesh, replicated
from ..registry import register_family
from ..schedulers import get_scheduler
from ..weights import (
    MissingWeightsError,
    is_test_model,
    model_dir_for,
    require_weights_present,
)

logger = logging.getLogger(__name__)

_NO_CONVERSION_HINT = (
    "No converted i2vgen-xl checkpoint is present for this model name; "
    "download it first (initialize --download) or use a test/tiny name."
)

_is_tiny = is_test_model

# the tiny vision tower reuses the safety checker's geometry (same
# CLIPVisionEncoder consumer)
TINY_VISION = TINY_SAFETY


def convert_i2vgen_checkpoint(model_dir):
    """One i2vgen-xl repo conversion recipe -> component configs+params —
    shared by serving and `initialize --check`."""
    from ..models.conversion import (
        convert_clip,
        convert_clip_vision,
        convert_i2vgen_unet,
        convert_vae,
        infer_clip_vision_config,
        infer_i2vgen_config,
        infer_vae_config,
        load_torch_state_dict,
    )

    def cfg_json(sub):
        p = model_dir / sub / "config.json"
        return json.loads(p.read_text()) if p.is_file() else {}

    unet_state = load_torch_state_dict(model_dir, "unet")
    ucfg = infer_i2vgen_config(unet_state, cfg_json("unet"))
    unet = convert_i2vgen_unet(unet_state)
    tj = cfg_json("text_encoder")
    clip_cfg = dataclasses.replace(
        cfgs.SD15_CLIP,
        vocab_size=int(tj.get("vocab_size", 49408)),
        hidden_size=int(tj.get("hidden_size", 1024)),
        num_layers=int(tj.get("num_hidden_layers", 24)),
        num_heads=int(tj.get("num_attention_heads", 16)),
        hidden_act=str(tj.get("hidden_act", "gelu")),
    )
    text = convert_clip(load_torch_state_dict(model_dir, "text_encoder"))
    vision_cfg = infer_clip_vision_config(cfg_json("image_encoder"))
    vision = convert_clip_vision(
        load_torch_state_dict(model_dir, "image_encoder")
    )
    vae_state = load_torch_state_dict(model_dir, "vae")
    vae_cfg = infer_vae_config(vae_state, cfg_json("vae"))
    vae = convert_vae(vae_state)
    return {
        "unet_cfg": ucfg, "unet": unet,
        "clip_cfg": clip_cfg, "text": text,
        "vision_cfg": vision_cfg, "vision": vision,
        "vae_cfg": vae_cfg, "vae": vae,
        "model_dir": model_dir,
    }


def _load_converted_i2vgen(model_name: str):
    if _is_tiny(model_name):
        return None
    d = model_dir_for(model_name)
    if d is None:
        return None
    try:
        return convert_i2vgen_checkpoint(d)
    except (FileNotFoundError, OSError):
        return None
    except Exception as e:
        raise MissingWeightsError(
            f"checkpoint under {d} could not be converted for "
            f"'{model_name}': {e}"
        ) from e


class I2VGenPipeline:
    """Resident image-to-video pipeline serving the I2VGenXLPipeline wire
    name (the img2vid workflow default)."""

    accepts_micro_conditioning = False

    def __init__(self, model_name: str, chipset=None,
                 allow_random_init: bool = False):
        converted = _load_converted_i2vgen(model_name)
        if converted is None:
            require_weights_present(
                model_name, model_dir_for(model_name), allow_random_init,
                component="i2vgen-xl", hint=_NO_CONVERSION_HINT,
            )
        self.model_name = model_name
        self.chipset = chipset
        if converted is not None:
            unet_cfg = converted["unet_cfg"]
            clip_cfg = converted["clip_cfg"]
            vision_cfg = converted["vision_cfg"]
            vae_cfg = converted["vae_cfg"]
            self.default_size = 512
        elif _is_tiny(model_name):
            unet_cfg, clip_cfg, vision_cfg, vae_cfg = (
                TINY_I2VGEN,
                dataclasses.replace(cfgs.TINY_CLIP, hidden_size=16,
                                    num_heads=2),
                TINY_VISION,
                cfgs.TINY_VAE,
            )
            self.default_size = 64
        else:
            unet_cfg, clip_cfg, vision_cfg, vae_cfg = (
                I2VGenConfig(),
                dataclasses.replace(cfgs.SD15_CLIP, hidden_size=1024,
                                    num_layers=24, num_heads=16,
                                    hidden_act="gelu"),
                # ViT-H tower projecting into the UNet's 1024-wide context
                dataclasses.replace(SafetyConfig(), projection_dim=1024,
                                    hidden_act="gelu"),
                cfgs.SD_VAE,
            )
            self.default_size = 512
        on_tpu = jax.default_backend() == "tpu"
        self.dtype = jnp.bfloat16 if on_tpu else jnp.float32
        self.unet = I2VGenXLUNet(unet_cfg, dtype=self.dtype)
        self.text_encoder = CLIPTextEncoder(clip_cfg, dtype=self.dtype)
        self.vision = CLIPVisionEncoder(vision_cfg, dtype=self.dtype)
        self.vae = AutoencoderKL(vae_cfg, dtype=self.dtype)
        self.vision_cfg = vision_cfg
        self.tokenizer = load_tokenizer(None, vocab_size=clip_cfg.vocab_size)
        self.latent_factor = 2 ** (len(vae_cfg.block_out_channels) - 1)
        self.mesh = (
            chipset.mesh() if chipset is not None else make_mesh(jax.devices()[:1])
        )

        if converted is not None:
            from ..models.conversion import checked_converted

            rng = jax.random.key(0)
            f = 2
            checked_converted(
                self.unet,
                (jnp.zeros((f, 16, 16, unet_cfg.in_channels)),
                 jnp.zeros((1,)), jnp.ones((1,)),
                 jnp.zeros((f, 16, 16, unet_cfg.in_channels)),
                 jnp.zeros((1, unet_cfg.cross_attention_dim)),
                 jnp.zeros((1, 4, unet_cfg.cross_attention_dim))),
                converted["unet"], "i2vgen unet", rng,
                example_kwargs={"num_frames": f},
            )
            checked_converted(
                self.text_encoder, (jnp.zeros((1, 77), jnp.int32),),
                converted["text"], "i2vgen text_encoder", rng,
            )
            checked_converted(
                self.vision,
                (jnp.zeros((1, vision_cfg.image_size,
                            vision_cfg.image_size, 3)),),
                converted["vision"], "i2vgen image_encoder", rng,
            )
            lf = self.latent_factor
            checked_converted(
                self.vae, (jnp.zeros((1, 4 * lf, 4 * lf, 3)),),
                converted["vae"], "i2vgen vae", rng,
            )
            params = {
                "unet": converted["unet"], "text": converted["text"],
                "vision": converted["vision"], "vae": converted["vae"],
            }
        else:
            params = self._random_params(unet_cfg, vision_cfg)
        cast = lambda x: jnp.asarray(x, self.dtype)
        self.params = jax.device_put(
            jax.tree_util.tree_map(cast, params), replicated(self.mesh)
        )
        # insertion-ordered so the program_cache_max bound below can evict
        # least-recently-used first (SW007; same knob as the SD family)
        self._programs: OrderedDict = OrderedDict()
        self._lock = threading.Lock()

    def _random_params(self, unet_cfg, vision_cfg):
        rng = jax.random.key(zlib.crc32(self.model_name.encode()))
        k1, k2, k3, k4 = jax.random.split(rng, 4)
        f = 2
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            unet_params = self.unet.init(
                k1,
                jnp.zeros((f, 16, 16, unet_cfg.in_channels)),
                jnp.zeros((1,)), jnp.ones((1,)),
                jnp.zeros((f, 16, 16, unet_cfg.in_channels)),
                jnp.zeros((1, unet_cfg.cross_attention_dim)),
                jnp.zeros((1, 4, unet_cfg.cross_attention_dim)), f,
            )["params"]
            text_params = self.text_encoder.init(
                k2, jnp.zeros((1, 77), jnp.int32)
            )["params"]
            vision_params = self.vision.init(
                k3,
                jnp.zeros((1, vision_cfg.image_size,
                           vision_cfg.image_size, 3)),
            )["params"]
            lf = self.latent_factor
            vae_params = self.vae.init(
                k4, jnp.zeros((1, 4 * lf, 4 * lf, 3))
            )["params"]
        return {"unet": unet_params, "text": text_params,
                "vision": vision_params, "vae": vae_params}

    def release(self):
        self.params = None
        self._programs.clear()

    def _program(self, key: tuple):
        with self._lock:
            if key in self._programs:
                self._programs.move_to_end(key)
                return self._programs[key]
        lh, lw, frames, steps, sched_name = key
        scheduler = get_scheduler(sched_name)
        schedule = scheduler.schedule(steps)
        unet = self.unet
        vae = self.vae
        latent_c = unet.config.in_channels

        def run(params, rng, context, image_embed, image_latents, fps,
                guidance):
            """context [2, S, D] rows [uncond | cond]; image_embed [1, D];
            image_latents [frames, lh, lw, C] (frame 0 real, rest ramp)."""
            latents = jax.random.normal(
                rng, (frames, lh, lw, latent_c), jnp.float32
            ) * jnp.asarray(schedule.init_noise_sigma, jnp.float32)
            state = scheduler.init_state(latents.shape, latents.dtype)
            # CFG batch of 2: rows [zeroed image embed | real image embed]
            embed2 = jnp.concatenate(
                [jnp.zeros_like(image_embed), image_embed], axis=0
            ).astype(self.dtype)
            il2 = jnp.concatenate(
                [image_latents, image_latents], axis=0
            ).astype(self.dtype)
            fps2 = jnp.broadcast_to(fps, (2,))

            def body(carry, i):
                latents, state = carry
                inp = scheduler.scale_model_input(schedule, latents, i)
                model_in = jnp.concatenate([inp, inp], axis=0).astype(
                    self.dtype
                )
                t = jnp.asarray(schedule.timesteps)[i]
                pred = unet.apply(
                    {"params": params["unet"]},
                    model_in,
                    jnp.broadcast_to(t, (2,)),
                    fps2,
                    il2,
                    embed2,
                    context,
                    frames,
                ).astype(jnp.float32)
                pred_u, pred_c = jnp.split(pred, 2, axis=0)
                pred = pred_u + guidance * (pred_c - pred_u)
                noise = jax.random.normal(
                    jax.random.fold_in(rng, i), latents.shape, jnp.float32
                )
                state, latents = scheduler.step(
                    schedule, state, i, latents, pred, noise
                )
                return (latents, state), ()

            (latents, _), _ = jax.lax.scan(
                body, (latents, state), jnp.arange(steps)
            )
            pixels = jax.lax.map(
                lambda z: vae.apply(
                    {"params": params["vae"]}, z[None].astype(self.dtype),
                    method=vae.decode,
                )[0],
                latents,
            )
            return (
                (pixels.astype(jnp.float32) + 1.0) * 127.5
            ).clip(0.0, 255.0).round().astype(jnp.uint8)

        program = jax.jit(run)
        with self._lock:
            self._programs[key] = program
            from .common import PROGRAM_EVICTED, program_cache_cap

            cap = program_cache_cap()
            while cap and len(self._programs) > cap:
                self._programs.popitem(last=False)
                PROGRAM_EVICTED.inc(kind="program")
        return program

    def run(self, prompt="", negative_prompt="",
            pipeline_type="I2VGenXLPipeline", **kwargs):
        params = self.params
        if params is None:
            raise Exception(
                f"pipeline {self.model_name} was evicted; resubmit the job"
            )
        image = kwargs.pop("image", None)
        if image is None:
            raise ValueError("img2vid requires an input image. None provided")
        timings: dict[str, float] = {}
        steps = int(kwargs.pop("num_inference_steps", 25))
        frames = int(
            kwargs.pop("num_frames", 16 if self.default_size > 64 else 4)
        )
        fps = float(kwargs.pop("target_fps", kwargs.pop("fps", 16)))
        guidance = float(kwargs.pop("guidance_scale", 9.0))
        # honor the job's requested solver like the sibling pipelines do
        # (ADVICE r04: DDIM was hardcoded and the request silently ignored);
        # the job layer defaults img2vid to DPMSolverMultistepScheduler
        # (job_arguments.py DEFAULT_SCHEDULER, reference job_arguments.py:143)
        scheduler_type = kwargs.pop(
            "scheduler_type", "DPMSolverMultistepScheduler"
        )
        rng = kwargs.pop("rng", None)
        if rng is None:
            rng = jax.random.key(0)
        kwargs.pop("chipset", None)

        width, height = image.size
        size = min(self.default_size, max(width, height))
        scale = size / max(width, height)
        width = max(64, (int(width * scale) // 64) * 64)
        height = max(64, (int(height * scale) // 64) * 64)
        lh, lw = height // self.latent_factor, width // self.latent_factor

        t0 = time.perf_counter()
        # text rows [uncond | cond]
        ids = jnp.asarray(self.tokenizer([negative_prompt, prompt]))
        context = self.text_encoder.apply(
            {"params": params["text"]}, ids
        )["hidden_states"]

        # CLIP-vision image embedding
        vi = self.vision_cfg.image_size
        varr = (
            np.asarray(
                image.convert("RGB").resize((vi, vi), Image.BICUBIC),
                np.float32,
            )
            / 255.0
        )
        varr = (varr - np.asarray([0.48145466, 0.4578275, 0.40821073])) / (
            np.asarray([0.26862954, 0.26130258, 0.27577711])
        )
        image_embed = self.vision.apply(
            {"params": params["vision"]},
            jnp.asarray(varr[None], self.dtype),
        ).astype(jnp.float32)  # [1, projection_dim]

        # first-frame latents + position-ramp frames
        parr = jnp.asarray(
            np.asarray(
                image.convert("RGB").resize((width, height)), np.float32
            )[None]
            / 127.5
            - 1.0
        )
        first = self.vae.apply(
            {"params": params["vae"]}, parr.astype(self.dtype),
            method=self.vae.encode,
        ).astype(jnp.float32)
        if frames > 1:
            ramp = jnp.ones((frames - 1, lh, lw, first.shape[-1]),
                            jnp.float32) * (
                jnp.arange(1, frames, dtype=jnp.float32)[:, None, None, None]
                / (frames - 1)
            )
            image_latents = jnp.concatenate([first, ramp], axis=0)
        else:
            image_latents = first
        timings["conditioning_s"] = round(time.perf_counter() - t0, 3)

        program = self._program((lh, lw, frames, steps, scheduler_type))
        t0 = time.perf_counter()
        pixels = jax.block_until_ready(
            program(params, rng, context, image_embed, image_latents,
                    jnp.float32(fps), jnp.float32(guidance))
        )
        timings["denoise_decode_s"] = round(time.perf_counter() - t0, 3)

        pil_frames = [Image.fromarray(f) for f in np.asarray(pixels)]
        config = {
            "model": self.model_name,
            "pipeline": pipeline_type,
            "scheduler": scheduler_type,
            "mode": "img2vid",
            "steps": steps,
            "frames": frames,
            "fps": int(fps),
            "size": [width, height],
            "guidance_scale": guidance,
            "timings": timings,
        }
        return pil_frames, config


@register_family("i2vgenxl")
def _build_i2vgen(model_name, chipset, **variant):
    return I2VGenPipeline(model_name, chipset, **variant)
