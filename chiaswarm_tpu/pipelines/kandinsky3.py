"""Kandinsky 3 pipeline: single-stage T5-conditioned latent diffusion.

Reference behavior replaced: swarm/test.py:130-147 schedules
`kandinsky-community/kandinsky-3` via AutoPipeline with
`Kandinsky3Pipeline` semantics — unlike Kandinsky 2.x there is no prior
stage; the prompt conditions the Kandinsky3UNet directly through FLAN-UL2's
T5 encoder (128 tokens, attention-masked all the way into the UNet's
cross-attention and time-embedding pooling), and the pixels come out of a
MoVQ decode.

TPU redesign: the same resident one-scan shape as the other families —
T5 encode once per job, CFG as a batch of 2 inside a single jitted
`lax.scan` denoise + MoVQ decode program. Real checkpoints convert at
load (models/conversion.py convert_kandinsky3_unet + convert_movq +
convert_t5, geometry inferred from the checkpoint); test/tiny names run
the same true architecture at toy widths.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import zlib
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np
from PIL import Image

from ..models.movq import MoVQ, TINY_MOVQ, MoVQConfig, movq_config_from_json
from ..models.t5 import TINY_T5, T5Config, T5Encoder, t5_config_from_json
from ..models.unet_kandinsky3 import (
    TINY_K3_UNET,
    K3UNetConfig,
    Kandinsky3UNet,
)
from ..parallel.mesh import make_mesh, replicated
from ..registry import register_family
from ..schedulers import get_scheduler
from ..weights import (
    MissingWeightsError,
    is_test_model,
    model_dir_for,
    require_weights_present,
)

logger = logging.getLogger(__name__)

_NO_CONVERSION_HINT = (
    "No converted Kandinsky 3 checkpoint is present for this model name; "
    "download it first (initialize --download) or use a test/tiny name."
)

_is_tiny = is_test_model

# the diffusers pipeline tokenizes to 128 T5 tokens
MAX_TOKENS = 128


def convert_k3_checkpoint(model_dir):
    """One Kandinsky 3 repo conversion recipe ->
    (unet_cfg, unet, movq_cfg, movq, t5_cfg, t5) — shared by serving and
    `initialize --check` so a green check means EXACTLY what the worker
    will load."""
    from ..models.conversion import (
        convert_kandinsky3_unet,
        convert_movq,
        convert_t5,
        load_torch_state_dict,
    )

    def cfg_json(sub):
        p = model_dir / sub / "config.json"
        return json.loads(p.read_text()) if p.is_file() else {}

    ucfg, unet = convert_kandinsky3_unet(
        load_torch_state_dict(model_dir, "unet"), cfg_json("unet")
    )
    movq_cfg = movq_config_from_json(cfg_json("movq"))
    movq = convert_movq(load_torch_state_dict(model_dir, "movq"))
    t5_cfg = t5_config_from_json(cfg_json("text_encoder"))
    t5 = convert_t5(load_torch_state_dict(model_dir, "text_encoder"))
    return ucfg, unet, movq_cfg, movq, t5_cfg, t5


def _load_converted_k3(model_name: str):
    """-> dict of configs+params or None when no checkpoint is local. A
    present-but-unconvertible checkpoint fails as MissingWeightsError."""
    if _is_tiny(model_name):
        return None
    d = model_dir_for(model_name)
    if d is None:
        return None
    try:
        ucfg, unet, mcfg, movq, tcfg, t5 = convert_k3_checkpoint(d)
    except (FileNotFoundError, OSError):
        return None
    except Exception as e:
        raise MissingWeightsError(
            f"checkpoint under {d} could not be converted for "
            f"'{model_name}': {e}"
        ) from e
    return {
        "unet_cfg": ucfg, "unet": unet,
        "movq_cfg": mcfg, "movq": movq,
        "t5_cfg": tcfg, "t5": t5,
        "model_dir": d,
    }


class Kandinsky3Pipeline:
    """Resident single-stage pipeline serving Kandinsky3Pipeline wire
    names (txt2img; img2img starts from MoVQ-encoded noised latents)."""

    def __init__(self, model_name: str, chipset=None,
                 allow_random_init: bool = False):
        converted = _load_converted_k3(model_name)
        if converted is None:
            require_weights_present(
                model_name, model_dir_for(model_name), allow_random_init,
                component="Kandinsky 3", hint=_NO_CONVERSION_HINT,
            )
        self.model_name = model_name
        self.chipset = chipset
        if converted is not None:
            unet_cfg = converted["unet_cfg"]
            movq_cfg = converted["movq_cfg"]
            t5_cfg = converted["t5_cfg"]
            self.default_size = 1024
        elif _is_tiny(model_name):
            unet_cfg, movq_cfg, t5_cfg = TINY_K3_UNET, TINY_MOVQ, TINY_T5
            self.default_size = 64
        else:  # allow_random_init bench path at real geometry
            unet_cfg, movq_cfg, t5_cfg = (
                K3UNetConfig(), MoVQConfig(), T5Config()
            )
            self.default_size = 1024
        on_tpu = jax.default_backend() == "tpu"
        self.dtype = jnp.bfloat16 if on_tpu else jnp.float32
        self.unet = Kandinsky3UNet(unet_cfg, dtype=self.dtype)
        self.t5 = T5Encoder(t5_cfg, dtype=self.dtype)
        self.movq = MoVQ(movq_cfg, dtype=self.dtype)
        self.vae = self.movq  # common.encode_init_image's codec handle
        self.latent_factor = 2 ** (len(movq_cfg.block_out_channels) - 1)
        from .flux import _load_t5_tokenizer

        self.tokenizer = _load_t5_tokenizer(
            converted["model_dir"] if converted else None, t5_cfg.vocab_size
        )
        self.mesh = (
            chipset.mesh() if chipset is not None else make_mesh(jax.devices()[:1])
        )

        params = (
            {"unet": converted["unet"], "t5": converted["t5"],
             "movq": converted["movq"]}
            if converted is not None
            else self._random_params(unet_cfg, t5_cfg)
        )
        if converted is not None:
            from ..models.conversion import checked_converted

            rng = jax.random.key(0)
            hw = 2 ** (len(unet_cfg.block_out_channels) + 1)
            checked_converted(
                self.unet,
                (jnp.zeros((1, hw, hw, unet_cfg.in_channels)),
                 jnp.zeros((1,)),
                 jnp.zeros((1, 4, unet_cfg.encoder_hid_dim)),
                 jnp.ones((1, 4))),
                converted["unet"], "kandinsky3 unet", rng,
            )
            # a stale/missing movq or text_encoder config.json would
            # otherwise surface mid-job as an opaque XLA shape error
            f = self.latent_factor
            checked_converted(
                self.movq, (jnp.zeros((1, 4 * f, 4 * f, 3)),),
                converted["movq"], "kandinsky3 movq", rng,
            )
            checked_converted(
                self.t5, (jnp.zeros((1, 4), jnp.int32),),
                converted["t5"], "kandinsky3 text_encoder", rng,
            )
        cast = lambda x: jnp.asarray(x, self.dtype)
        self.params = jax.device_put(
            jax.tree_util.tree_map(cast, params), replicated(self.mesh)
        )
        # insertion-ordered so the program_cache_max bound below can evict
        # least-recently-used first (SW007; same knob as the SD family)
        self._programs: OrderedDict = OrderedDict()
        self._lock = threading.Lock()

    def _random_params(self, unet_cfg, t5_cfg):
        rng = jax.random.key(zlib.crc32(self.model_name.encode()))
        k1, k2, k3 = jax.random.split(rng, 3)
        n_down = len(unet_cfg.block_out_channels) - 1
        hw = 2 ** max(n_down + 1, 3)
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            unet_params = self.unet.init(
                k1,
                jnp.zeros((1, hw, hw, unet_cfg.in_channels)),
                jnp.zeros((1,)),
                jnp.zeros((1, 8, unet_cfg.encoder_hid_dim)),
                jnp.ones((1, 8)),
            )["params"]
            t5_params = self.t5.init(
                k2, jnp.zeros((1, 8), jnp.int32)
            )["params"]
            movq_params = self.movq.init(
                k3,
                jnp.zeros(
                    (1, 4 * self.latent_factor, 4 * self.latent_factor, 3)
                ),
            )["params"]
        return {"unet": unet_params, "t5": t5_params, "movq": movq_params}

    def release(self):
        self.params = None
        self._programs.clear()

    def _program(self, key: tuple):
        with self._lock:
            if key in self._programs:
                self._programs.move_to_end(key)
                return self._programs[key]
        mode, lh, lw, batch, steps, sched_name, t_start = key
        scheduler = get_scheduler(sched_name)
        schedule = scheduler.schedule(steps)
        loop_start, loop_end = scheduler.loop_bounds(schedule, steps, t_start)
        unet = self.unet
        movq = self.movq
        latent_c = unet.config.in_channels

        def run(params, rng, context, context_mask, guidance, image_latents):
            """context [2B,S,D] rows [uncond | cond]; context_mask [2B,S];
            img2img starts from the init image's MoVQ latents noised to the
            strength level."""
            noise0 = jax.random.normal(
                rng, (batch, lh, lw, latent_c), jnp.float32
            )
            if mode == "img2img":
                latents = scheduler.add_noise(
                    schedule, image_latents.astype(jnp.float32), noise0,
                    loop_start,
                )
            else:
                latents = noise0 * jnp.asarray(
                    schedule.init_noise_sigma, jnp.float32
                )
            state = scheduler.init_state(latents.shape, latents.dtype)

            def body(carry, i):
                latents, state = carry
                inp = scheduler.scale_model_input(schedule, latents, i)
                model_in = jnp.concatenate([inp, inp], axis=0).astype(self.dtype)
                t = jnp.asarray(schedule.timesteps)[i]
                pred = unet.apply(
                    {"params": params["unet"]},
                    model_in,
                    jnp.broadcast_to(t, (2 * batch,)),
                    context,
                    context_mask,
                ).astype(jnp.float32)
                pred_u, pred_c = jnp.split(pred, 2, axis=0)
                pred = pred_u + guidance * (pred_c - pred_u)
                noise = jax.random.normal(
                    jax.random.fold_in(rng, i), latents.shape, jnp.float32
                )
                state, latents = scheduler.step(
                    schedule, state, i, latents, pred, noise
                )
                return (latents, state), ()

            (latents, _), _ = jax.lax.scan(
                body, (latents, state), jnp.arange(loop_start, loop_end)
            )
            pixels = movq.apply(
                {"params": params["movq"]}, latents.astype(self.dtype),
                method=movq.decode,
            )
            return (
                (pixels.astype(jnp.float32) + 1.0) * 127.5
            ).clip(0.0, 255.0).round().astype(jnp.uint8)

        program = jax.jit(run)
        with self._lock:
            self._programs[key] = program
            from .common import PROGRAM_EVICTED, program_cache_cap

            cap = program_cache_cap()
            while cap and len(self._programs) > cap:
                self._programs.popitem(last=False)
                PROGRAM_EVICTED.inc(kind="program")
        return program

    def run(self, prompt="", negative_prompt="",
            pipeline_type="Kandinsky3Pipeline", **kwargs):
        params = self.params
        if params is None:
            raise Exception(
                f"pipeline {self.model_name} was evicted; resubmit the job"
            )
        timings: dict[str, float] = {}
        steps = int(kwargs.pop("num_inference_steps", 25))
        guidance_scale = float(kwargs.pop("guidance_scale", 3.0))
        n_images = int(kwargs.pop("num_images_per_prompt", 1))
        scheduler_type = kwargs.pop("scheduler_type", "DDPMScheduler")
        rng = kwargs.pop("rng", None)
        if rng is None:
            rng = jax.random.key(0)
        kwargs.pop("chipset", None)
        kwargs.pop("pipeline_prior_type", None)  # K3 has no prior stage
        image = kwargs.pop("image", None)
        from .common import (
            clamp_strength,
            encode_init_image,
            img2img_t_start,
        )

        strength = clamp_strength(kwargs.pop("strength", 0.75))

        if image is not None:
            width, height = image.size
            kwargs.pop("height", None)
            kwargs.pop("width", None)
        else:
            height = int(kwargs.pop("height", None) or self.default_size)
            width = int(kwargs.pop("width", None) or self.default_size)
        height, width = (max(64, (d // 64) * 64) for d in (height, width))
        lh, lw = height // self.latent_factor, width // self.latent_factor

        mode = "img2img" if image is not None else "txt2img"
        t_start = img2img_t_start(steps, strength) if mode == "img2img" else 0
        image_latents = jnp.zeros((1, 1, 1, 1), jnp.float32)
        if image is not None:
            image_latents = encode_init_image(
                self, params["movq"], image, width, height, n_images,
                lh, lw, self.unet.config.in_channels,
            )

        max_seq = MAX_TOKENS if not _is_tiny(self.model_name) else 16
        texts = [negative_prompt] * n_images + [prompt] * n_images
        tok = np.asarray(self.tokenizer(texts, max_seq), np.int32)
        # 1-keep mask over non-pad positions (pad id 0 for T5 tokenizers);
        # position 0 of an empty prompt keeps at least the EOS token
        mask = (tok != 0).astype(np.float32)
        mask[:, 0] = 1.0
        ids = jnp.asarray(tok)
        context_mask = jnp.asarray(mask)
        t0 = time.perf_counter()
        context = self.t5.apply(
            {"params": params["t5"]}, ids, context_mask
        )
        # diffusers' encode_prompt zeroes padded positions before the UNet
        # (the attention-pooling mean query would otherwise average in
        # full-magnitude pad-position states)
        context = context * context_mask[..., None].astype(context.dtype)
        timings["text_encode_s"] = round(time.perf_counter() - t0, 3)

        program = self._program(
            (mode, lh, lw, n_images, steps, scheduler_type, t_start)
        )
        t0 = time.perf_counter()
        pixels = jax.block_until_ready(
            program(params, rng, context, context_mask,
                    jnp.float32(guidance_scale), image_latents)
        )
        timings["denoise_decode_s"] = round(time.perf_counter() - t0, 3)

        images = [Image.fromarray(img) for img in np.asarray(pixels)]
        pipeline_config = {
            "model": self.model_name,
            "pipeline": pipeline_type,
            "scheduler": scheduler_type,
            "mode": mode,
            "steps": steps,
            "size": [width, height],
            "guidance_scale": guidance_scale,
            "timings": timings,
        }
        return images, pipeline_config


@register_family("kandinsky3")
def _build_kandinsky3(model_name, chipset, **variant):
    return Kandinsky3Pipeline(model_name, chipset, **variant)
