"""Kandinsky 3 pipeline: single-stage T5-conditioned latent diffusion.

Reference behavior replaced: swarm/test.py:130-147 schedules
`kandinsky-community/kandinsky-3` via AutoPipeline with
`Kandinsky3Pipeline` semantics — unlike Kandinsky 2.x there is no prior
stage; the prompt conditions a latent UNet directly through a FLAN-T5
text encoder (the same family split diffusers implements).

TPU redesign: the same resident one-scan shape as the other families —
T5 encode once per job, CFG as a batch of 2 inside a single jitted
`lax.scan` denoise + VAE decode program. The MoVQ decoder is served by
this package's AutoencoderKL (as with Kandinsky 2.x; real-weight
conversion for this family is not wired yet, so non-test model names fail
loudly per weights.py).
"""

from __future__ import annotations

import logging
import threading
import time
import zlib

import jax
import jax.numpy as jnp
import numpy as np
from PIL import Image

from ..models import configs as cfgs
from ..models.t5 import TINY_T5, T5Config, T5Encoder
from ..models.unet2d import UNet2DConditionModel, UNet2DConfig
from ..models.vae import AutoencoderKL
from ..parallel.mesh import make_mesh, replicated
from ..registry import register_family
from ..schedulers import get_scheduler
from ..weights import is_test_model, require_weights_present

logger = logging.getLogger(__name__)

_NO_CONVERSION_HINT = (
    "This worker cannot serve real Kandinsky 3 weights yet; only the "
    "test/tiny Kandinsky 3 model is available."
)

_is_tiny = is_test_model

# Kandinsky3 UNet analog: latent-space, FLAN-T5-conditioned (the real model
# cross-attends on 4096-d T5 states at three scales)
K3_UNET = UNet2DConfig(
    block_out_channels=(384, 768, 1536, 3072),
    transformer_layers=(0, 1, 1, 1),
    num_attention_heads=(6, 12, 24, 48),
    cross_attention_dim=4096,
)
TINY_K3_UNET = UNet2DConfig(
    block_out_channels=(32, 64),
    transformer_layers=(1, 1),
    mid_transformer_layers=1,
    layers_per_block=1,
    num_attention_heads=4,
    cross_attention_dim=32,
)


def _configs(model_name: str):
    """(unet_cfg, t5_cfg, vae_cfg, default_size)."""
    if _is_tiny(model_name):
        return TINY_K3_UNET, TINY_T5, cfgs.TINY_VAE, 64
    return K3_UNET, T5Config(), cfgs.SD_VAE, 1024


class Kandinsky3Pipeline:
    """Resident single-stage pipeline serving Kandinsky3Pipeline wire
    names (txt2img; img2img arrives as noised init latents)."""

    def __init__(self, model_name: str, chipset=None,
                 allow_random_init: bool = False):
        require_weights_present(
            model_name, None, allow_random_init, component="Kandinsky 3",
            hint=_NO_CONVERSION_HINT,
        )
        self.model_name = model_name
        self.chipset = chipset
        unet_cfg, t5_cfg, vae_cfg, self.default_size = _configs(model_name)
        on_tpu = jax.default_backend() == "tpu"
        self.dtype = jnp.bfloat16 if on_tpu else jnp.float32
        self.unet = UNet2DConditionModel(unet_cfg, dtype=self.dtype)
        self.t5 = T5Encoder(t5_cfg, dtype=self.dtype)
        self.vae = AutoencoderKL(vae_cfg, dtype=self.dtype)
        self.latent_factor = 2 ** (len(vae_cfg.block_out_channels) - 1)
        from .flux import _load_t5_tokenizer

        self.tokenizer = _load_t5_tokenizer(None, t5_cfg.vocab_size)
        self.mesh = (
            chipset.mesh() if chipset is not None else make_mesh(jax.devices()[:1])
        )

        rng = jax.random.key(zlib.crc32(model_name.encode()))
        k1, k2, k3 = jax.random.split(rng, 3)
        n_down = len(unet_cfg.block_out_channels) - 1
        hw = 2 ** max(n_down, 2)
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            unet_params = self.unet.init(
                k1,
                jnp.zeros((1, hw, hw, unet_cfg.in_channels)),
                jnp.zeros((1,)),
                jnp.zeros((1, 16, unet_cfg.cross_attention_dim)),
            )["params"]
            t5_params = self.t5.init(
                k2, jnp.zeros((1, 16), jnp.int32)
            )["params"]
            vae_params = self.vae.init(
                k3,
                jnp.zeros(
                    (1, hw * self.latent_factor, hw * self.latent_factor, 3)
                ),
            )["params"]
        cast = lambda x: jnp.asarray(x, self.dtype)
        self.params = jax.device_put(
            jax.tree_util.tree_map(cast, {
                "unet": unet_params, "t5": t5_params, "vae": vae_params
            }),
            replicated(self.mesh),
        )
        self._programs: dict[tuple, callable] = {}
        self._lock = threading.Lock()

    def release(self):
        self.params = None
        self._programs.clear()

    def _program(self, key: tuple):
        with self._lock:
            if key in self._programs:
                return self._programs[key]
        mode, lh, lw, batch, steps, sched_name, t_start = key
        scheduler = get_scheduler(sched_name)
        schedule = scheduler.schedule(steps)
        loop_start, loop_end = scheduler.loop_bounds(schedule, steps, t_start)
        unet = self.unet
        vae = self.vae
        latent_c = unet.config.in_channels

        def run(params, rng, context, guidance, image_latents):
            """context [2B,S,D] rows [uncond | cond]; img2img starts from
            the init image's latents noised to the strength level."""
            noise0 = jax.random.normal(
                rng, (batch, lh, lw, latent_c), jnp.float32
            )
            if mode == "img2img":
                latents = scheduler.add_noise(
                    schedule, image_latents.astype(jnp.float32), noise0,
                    loop_start,
                )
            else:
                latents = noise0 * jnp.asarray(
                    schedule.init_noise_sigma, jnp.float32
                )
            state = scheduler.init_state(latents.shape, latents.dtype)

            def body(carry, i):
                latents, state = carry
                inp = scheduler.scale_model_input(schedule, latents, i)
                model_in = jnp.concatenate([inp, inp], axis=0).astype(self.dtype)
                t = jnp.asarray(schedule.timesteps)[i]
                pred = unet.apply(
                    {"params": params["unet"]},
                    model_in,
                    jnp.broadcast_to(t, (2 * batch,)),
                    context,
                ).astype(jnp.float32)
                pred_u, pred_c = jnp.split(pred, 2, axis=0)
                pred = pred_u + guidance * (pred_c - pred_u)
                noise = jax.random.normal(
                    jax.random.fold_in(rng, i), latents.shape, jnp.float32
                )
                state, latents = scheduler.step(
                    schedule, state, i, latents, pred, noise
                )
                return (latents, state), ()

            (latents, _), _ = jax.lax.scan(
                body, (latents, state), jnp.arange(loop_start, loop_end)
            )
            pixels = vae.apply(
                {"params": params["vae"]}, latents.astype(self.dtype),
                method=vae.decode,
            )
            return (
                (pixels.astype(jnp.float32) + 1.0) * 127.5
            ).clip(0.0, 255.0).round().astype(jnp.uint8)

        program = jax.jit(run)
        with self._lock:
            self._programs[key] = program
        return program

    def run(self, prompt="", negative_prompt="",
            pipeline_type="Kandinsky3Pipeline", **kwargs):
        params = self.params
        if params is None:
            raise Exception(
                f"pipeline {self.model_name} was evicted; resubmit the job"
            )
        timings: dict[str, float] = {}
        steps = int(kwargs.pop("num_inference_steps", 25))
        guidance_scale = float(kwargs.pop("guidance_scale", 3.0))
        n_images = int(kwargs.pop("num_images_per_prompt", 1))
        scheduler_type = kwargs.pop("scheduler_type", "DDPMScheduler")
        rng = kwargs.pop("rng", None)
        if rng is None:
            rng = jax.random.key(0)
        kwargs.pop("chipset", None)
        kwargs.pop("pipeline_prior_type", None)  # K3 has no prior stage
        image = kwargs.pop("image", None)
        from .common import clamp_strength, encode_init_image, img2img_t_start

        strength = clamp_strength(kwargs.pop("strength", 0.75))

        if image is not None:
            width, height = image.size
            kwargs.pop("height", None)
            kwargs.pop("width", None)
        else:
            height = int(kwargs.pop("height", None) or self.default_size)
            width = int(kwargs.pop("width", None) or self.default_size)
        height, width = (max(64, (d // 64) * 64) for d in (height, width))
        lh, lw = height // self.latent_factor, width // self.latent_factor

        mode = "img2img" if image is not None else "txt2img"
        t_start = img2img_t_start(steps, strength) if mode == "img2img" else 0
        image_latents = jnp.zeros((1, 1, 1, 1), jnp.float32)
        if image is not None:
            image_latents = encode_init_image(
                self, params["vae"], image, width, height, n_images,
                lh, lw, self.unet.config.in_channels,
            )

        max_seq = 77
        texts = [negative_prompt] * n_images + [prompt] * n_images
        ids = jnp.asarray(np.asarray(self.tokenizer(texts, max_seq), np.int32))
        t0 = time.perf_counter()
        context = self.t5.apply({"params": params["t5"]}, ids)
        timings["text_encode_s"] = round(time.perf_counter() - t0, 3)

        program = self._program(
            (mode, lh, lw, n_images, steps, scheduler_type, t_start)
        )
        t0 = time.perf_counter()
        pixels = jax.block_until_ready(
            program(params, rng, context, jnp.float32(guidance_scale),
                    image_latents)
        )
        timings["denoise_decode_s"] = round(time.perf_counter() - t0, 3)

        images = [Image.fromarray(img) for img in np.asarray(pixels)]
        pipeline_config = {
            "model": self.model_name,
            "pipeline": pipeline_type,
            "scheduler": scheduler_type,
            "mode": mode,
            "steps": steps,
            "size": [width, height],
            "guidance_scale": guidance_scale,
            "timings": timings,
        }
        return images, pipeline_config


@register_family("kandinsky3")
def _build_kandinsky3(model_name, chipset, **variant):
    return Kandinsky3Pipeline(model_name, chipset, **variant)
