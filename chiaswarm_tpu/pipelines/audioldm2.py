"""AudioLDM2 pipeline: dual-conditioned mel-latent diffusion.

Reference behavior replaced: the reference serves AudioLDM2 through the
same txt2audio callback as v1 when a job sets
`parameters.pipeline_type = "AudioLDM2Pipeline"`
(swarm/job_arguments.py get_type resolves any diffusers class;
swarm/audio/audioldm.py:12-21 runs it and mp3-encodes the waveform).

TPU redesign: the conditioning chain runs once per job host-side —
CLAP pooled embedding (unit-norm, one token) and masked T5 states feed
the projection model's [sos|clap|eos|sos_1|t5|eos_1] sequence, GPT-2
rolls 8 deterministic last-hidden-state continuations (each step a
cached jit per sequence length), and the denoise is one `lax.scan` DDIM
program over a CFG batch of 2 with BOTH contexts cross-attended per
layer, mel VAE decode and HiFi-GAN vocoding fused at the end (only the
waveform crosses back to the host). Real checkpoints convert at load;
GPT-2 and the text towers have exact transformers parity tests.
"""

from __future__ import annotations

import time
import zlib
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np

from ..models.audioldm2_unet import (
    TINY_AUDIOLDM2_UNET,
    AudioLDM2Projection,
    AudioLDM2UNet,
)
from ..models.clap import TINY_CLAP, ClapTextEncoder
from ..models.gpt2 import TINY_GPT2, GPT2Model
from ..models.hifigan import TINY_HIFIGAN, HifiGanGenerator
from ..models.t5 import TINY_T5, T5Encoder, t5_config_from_json
from ..models.vae import AutoencoderKL, VAEConfig
from ..registry import register_family
from ..weights import (
    MissingWeightsError,
    is_test_model,
    model_dir_for,
    require_weights_present,
)
from .audio import (
    HOP,
    SAMPLE_RATE,
    _clap_tokenizer,
    _config_json,
    _infer_clap_vocoder_configs,
    normalize_wav,
)

_NO_CONVERSION_HINT = (
    "No converted AudioLDM2 checkpoint is present for this model name; "
    "download it first (initialize --download) or use a test/tiny name."
)

_is_tiny = is_test_model

# fixed T5 token budget so GPT-2 generation lengths are static per job
MAX_T5_TOKENS = 128
TINY_MAX_T5 = 12
GENERATED_TOKENS = 8

TINY_MEL_VAE = VAEConfig(
    in_channels=1, latent_channels=8, block_out_channels=(32, 32),
    layers_per_block=1,
)


def convert_audioldm2_checkpoint(model_dir):
    """One cvssp/audioldm2 repo conversion recipe -> component
    configs+params — shared by serving and `initialize --check`."""
    from ..models.conversion import (
        convert_audioldm2_projection,
        convert_audioldm2_unet,
        convert_clap,
        convert_gpt2,
        convert_hifigan,
        convert_t5,
        convert_vae,
        gpt2_config_from_json,
        infer_audioldm2_unet_config,
        infer_vae_config,
        load_torch_state_dict,
    )

    unet_state = load_torch_state_dict(model_dir, "unet")
    ucfg = infer_audioldm2_unet_config(
        unet_state, _config_json(model_dir, "unet")
    )
    unet = convert_audioldm2_unet(unet_state)
    # the ClapModel checkpoint carries the audio tower too — only the
    # text branch serves
    clap_state = {
        k: v
        for k, v in load_torch_state_dict(model_dir, "text_encoder").items()
        if k.startswith(("text_model.", "text_projection."))
    }
    clap = convert_clap(clap_state)
    clap_cfg, vocoder_cfg = _infer_clap_vocoder_configs(model_dir)
    t5 = convert_t5(load_torch_state_dict(model_dir, "text_encoder_2"))
    t5_cfg = t5_config_from_json(_config_json(model_dir, "text_encoder_2"))
    gpt2 = convert_gpt2(load_torch_state_dict(model_dir, "language_model"))
    gpt2_cfg = gpt2_config_from_json(
        _config_json(model_dir, "language_model")
    )
    proj = convert_audioldm2_projection(
        load_torch_state_dict(model_dir, "projection_model")
    )
    vae_state = load_torch_state_dict(model_dir, "vae")
    vae_cfg = infer_vae_config(vae_state, _config_json(model_dir, "vae"))
    vae = convert_vae(vae_state)
    vocoder = convert_hifigan(load_torch_state_dict(model_dir, "vocoder"))
    return {
        "unet_cfg": ucfg, "unet": unet,
        "clap_cfg": clap_cfg, "clap": clap,
        "t5_cfg": t5_cfg, "t5": t5,
        "gpt2_cfg": gpt2_cfg, "gpt2": gpt2,
        "proj": proj,
        "vae_cfg": vae_cfg, "vae": vae,
        "vocoder_cfg": vocoder_cfg, "vocoder": vocoder,
        "model_dir": model_dir,
    }


def _load_converted_audioldm2(model_name: str):
    if _is_tiny(model_name):
        return None
    d = model_dir_for(model_name)
    if d is None:
        return None
    try:
        return convert_audioldm2_checkpoint(d)
    except (FileNotFoundError, OSError):
        return None
    except Exception as e:
        raise MissingWeightsError(
            f"checkpoint under {d} could not be converted for "
            f"'{model_name}': {e}"
        ) from e


class AudioLDM2Pipeline:
    """Resident AudioLDM2 bundle serving the AudioLDM2Pipeline wire
    name on the txt2audio workflow."""

    def __init__(self, model_name: str, chipset=None,
                 allow_random_init: bool = False):
        converted = _load_converted_audioldm2(model_name)
        if converted is None:
            require_weights_present(
                model_name, model_dir_for(model_name), allow_random_init,
                component="AudioLDM2", hint=_NO_CONVERSION_HINT,
            )
        self.model_name = model_name
        self.chipset = chipset
        tiny = _is_tiny(model_name)
        if converted is not None:
            ucfg = converted["unet_cfg"]
            clap_cfg = converted["clap_cfg"]
            t5_cfg = converted["t5_cfg"]
            gpt2_cfg = converted["gpt2_cfg"]
            vae_cfg = converted["vae_cfg"]
            vocoder_cfg = converted["vocoder_cfg"]
        else:
            import dataclasses

            ucfg = TINY_AUDIOLDM2_UNET
            clap_cfg = TINY_CLAP  # projection feeds the Linear below
            t5_cfg = dataclasses.replace(
                TINY_T5,
                d_model=TINY_AUDIOLDM2_UNET.cross_attention_dims[1],
            )
            gpt2_cfg = TINY_GPT2  # hidden == cross_attention_dims[0]
            vae_cfg = TINY_MEL_VAE
            vocoder_cfg = TINY_HIFIGAN
        if tiny or converted is None:
            self.max_t5 = TINY_MAX_T5
        else:
            # the joint sequence [sos|clap|eos|sos_1|t5|eos_1] plus the 8
            # generated continuations must fit the LM's position table
            self.max_t5 = min(
                MAX_T5_TOKENS,
                gpt2_cfg.n_positions - 5 - GENERATED_TOKENS,
            )
        on_tpu = jax.default_backend() == "tpu"
        self.dtype = jnp.bfloat16 if on_tpu else jnp.float32
        self.unet = AudioLDM2UNet(ucfg, dtype=self.dtype)
        self.clap = ClapTextEncoder(clap_cfg, dtype=self.dtype)
        self.t5 = T5Encoder(t5_cfg, dtype=self.dtype)
        # GPT-2 operates at the first cross width (the generated tokens
        # the UNet attends)
        self.lm_dim = ucfg.cross_attention_dims[0]
        self.gpt2 = GPT2Model(gpt2_cfg, dtype=self.dtype)
        self.projection = AudioLDM2Projection(self.lm_dim, dtype=self.dtype)
        self.vae = AutoencoderKL(vae_cfg, dtype=self.dtype)
        self.vocoder = HifiGanGenerator(vocoder_cfg, dtype=self.dtype)
        self.vocoder_hop = int(np.prod(vocoder_cfg.upsample_rates))
        self.latent_factor = 2 ** (len(vae_cfg.block_out_channels) - 1)
        d = model_dir_for(model_name)
        self.clap_tokenizer, self._real_tok = _clap_tokenizer(
            d, clap_cfg.vocab_size
        )
        from .flux import _load_t5_tokenizer

        self.t5_tokenizer = _load_t5_tokenizer(d, t5_cfg.vocab_size)

        if converted is not None:
            from ..models.conversion import checked_converted

            rng = jax.random.key(0)
            checked_converted(
                self.unet,
                (jnp.zeros((1, 16, 8, ucfg.in_channels)), jnp.zeros((1,)),
                 jnp.zeros((1, 4, ucfg.cross_attention_dims[0])),
                 jnp.ones((1, 4)),
                 jnp.zeros((1, 4, ucfg.cross_attention_dims[1])),
                 jnp.ones((1, 4))),
                converted["unet"], "audioldm2 unet", rng,
            )
            checked_converted(
                self.gpt2, (jnp.zeros((1, 4, gpt2_cfg.hidden_size)),),
                converted["gpt2"], "audioldm2 language_model", rng,
            )
            checked_converted(
                self.projection,
                (jnp.zeros((1, 1, clap_cfg.projection_dim)),
                 jnp.ones((1, 1)),
                 jnp.zeros((1, 4, t5_cfg.d_model)), jnp.ones((1, 4))),
                converted["proj"], "audioldm2 projection_model", rng,
            )
            if not self._real_tok:
                raise MissingWeightsError(
                    f"{model_name}: converted CLAP weights need the real "
                    "tokenizer files (re-run initialize --download)"
                )
            params = {
                "unet": converted["unet"], "clap": converted["clap"],
                "t5": converted["t5"], "gpt2": converted["gpt2"],
                "proj": converted["proj"], "vae": converted["vae"],
                "vocoder": converted["vocoder"],
            }
        else:
            params = self._random_params(ucfg, clap_cfg, t5_cfg, vae_cfg)
        self.params = jax.tree_util.tree_map(
            lambda x: jnp.asarray(x, self.dtype), params
        )
        # insertion-ordered so the program_cache_max bound below can evict
        # least-recently-used first (SW007; same knob as the SD family)
        self._programs: OrderedDict = OrderedDict()
        self._gpt2_step = jax.jit(
            lambda p, seq, mask: self.gpt2.apply(
                {"params": p}, seq, mask
            )[:, -1:, :]
        )
        self._encode = jax.jit(
            lambda p, clap_ids, t5_ids, t5_mask: self._encode_impl(
                p, clap_ids, t5_ids, t5_mask
            )
        )

    def _random_params(self, ucfg, clap_cfg, t5_cfg, vae_cfg):
        rng = jax.random.key(zlib.crc32(self.model_name.encode()))
        ks = jax.random.split(rng, 7)
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            return {
                "unet": self.unet.init(
                    ks[0], jnp.zeros((1, 16, 8, ucfg.in_channels)),
                    jnp.zeros((1,)),
                    jnp.zeros((1, 4, ucfg.cross_attention_dims[0])),
                    jnp.ones((1, 4)),
                    jnp.zeros((1, 4, ucfg.cross_attention_dims[1])),
                    jnp.ones((1, 4)),
                )["params"],
                "clap": self.clap.init(
                    ks[1], jnp.zeros((1, 8), jnp.int32)
                )["params"],
                "t5": self.t5.init(
                    ks[2], jnp.zeros((1, 8), jnp.int32)
                )["params"],
                "gpt2": self.gpt2.init(
                    ks[3], jnp.zeros((1, 4, self.gpt2.config.hidden_size))
                )["params"],
                "proj": self.projection.init(
                    ks[4],
                    jnp.zeros((1, 1, self.clap.config.projection_dim)),
                    jnp.ones((1, 1)),
                    jnp.zeros((1, 4, self.t5.config.d_model)),
                    jnp.ones((1, 4)),
                )["params"],
                "vae": self.vae.init(
                    ks[5],
                    jnp.zeros((1, 4 * self.latent_factor,
                               4 * self.latent_factor, 1)),
                )["params"],
                "vocoder": self.vocoder.init(
                    ks[6],
                    jnp.zeros((1, 16, self.vocoder.config.model_in_dim)),
                )["params"],
            }

    def _encode_impl(self, params, clap_ids, t5_ids, t5_mask):
        pooled = self.clap.apply({"params": params["clap"]}, clap_ids)[
            "pooled"
        ].astype(jnp.float32)
        # transformers ClapModel.get_text_features unit-normalizes
        pooled = pooled / jnp.maximum(
            jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-8
        )
        h0 = pooled[:, None, :]
        m0 = jnp.ones(h0.shape[:2], jnp.float32)
        h1 = self.t5.apply({"params": params["t5"]}, t5_ids, t5_mask)
        seq, mask = self.projection.apply(
            {"params": params["proj"]}, h0, m0, h1, t5_mask
        )
        return seq, mask, h1

    def release(self):
        self.params = None
        self._programs.clear()

    def _generate(self, params, seq, mask):
        """GPT-2 rollout: append the last hidden state GENERATED_TOKENS
        times (the diffusers generate_language_model semantics — no
        sampling)."""
        for _ in range(GENERATED_TOKENS):
            nxt = self._gpt2_step(params["gpt2"], seq, mask)
            seq = jnp.concatenate([seq, nxt.astype(seq.dtype)], axis=1)
            mask = jnp.concatenate(
                [mask, jnp.ones((mask.shape[0], 1), mask.dtype)], axis=-1
            )
        return seq[:, -GENERATED_TOKENS:, :]

    def _program(self, key):
        if key in self._programs:
            self._programs.move_to_end(key)
            return self._programs[key]
        lt, lf, steps, sched_name = key
        from ..schedulers import get_scheduler

        scheduler = get_scheduler(sched_name)
        schedule = scheduler.schedule(steps)

        def run(params, latents, gen, t5_states, t5_mask, guidance, rng):
            """latents [1, lt, lf, C]; gen [2, 8, lm]; t5_states
            [2, S, d]; rows [uncond | cond]."""
            latents = latents * jnp.asarray(
                schedule.init_noise_sigma, latents.dtype
            )
            state = scheduler.init_state(latents.shape, latents.dtype)
            gen_mask = jnp.ones(gen.shape[:2], jnp.float32)

            def body(carry, i):
                latents, state = carry
                inp = scheduler.scale_model_input(schedule, latents, i)
                model_in = jnp.concatenate([inp, inp], axis=0).astype(
                    self.dtype
                )
                t = jnp.broadcast_to(
                    jnp.asarray(schedule.timesteps)[i], (2,)
                )
                out = self.unet.apply(
                    {"params": params["unet"]}, model_in, t,
                    gen.astype(self.dtype), gen_mask,
                    t5_states.astype(self.dtype), t5_mask,
                ).astype(jnp.float32)
                out_u, out_c = jnp.split(out, 2, axis=0)
                out = out_u + guidance * (out_c - out_u)
                noise = jax.random.normal(
                    jax.random.fold_in(rng, i), latents.shape, jnp.float32
                )
                state, latents = scheduler.step(
                    schedule, state, i, latents, out, noise
                )
                return (latents, state), ()

            (latents, _), _ = jax.lax.scan(
                body, (latents.astype(jnp.float32), state),
                jnp.arange(steps),
            )
            mel = self.vae.apply(
                {"params": params["vae"]}, latents.astype(self.dtype),
                method=self.vae.decode,
            )
            wav = self.vocoder.apply(
                {"params": params["vocoder"]}, mel[..., 0]
            )
            return wav.astype(jnp.float32)

        program = jax.jit(run)
        self._programs[key] = program
        from .common import PROGRAM_EVICTED, program_cache_cap

        cap = program_cache_cap()
        while cap and len(self._programs) > cap:
            self._programs.popitem(last=False)
            PROGRAM_EVICTED.inc(kind="program")
        return program

    def run(self, prompt="", negative_prompt="", **kwargs):
        params = self.params
        if params is None:
            raise Exception(
                f"pipeline {self.model_name} was evicted; resubmit"
            )
        steps = int(kwargs.pop("num_inference_steps", 20))
        guidance_scale = float(kwargs.pop("guidance_scale", 3.5))
        duration_s = float(kwargs.pop("audio_length_in_s", 5.0))
        scheduler_type = kwargs.pop("scheduler_type", "DDIMScheduler")
        rng = kwargs.pop("rng", None)
        if rng is None:
            rng = jax.random.key(0)

        frames = int(duration_s * SAMPLE_RATE / HOP)
        lt = max(8, frames // self.latent_factor // 8 * 8)
        # the decoded mel must hit the vocoder's freq-bin count exactly
        lf = max(4, self.vocoder.config.model_in_dim // self.latent_factor)

        t0 = time.perf_counter()
        clap_ids = jnp.asarray(
            np.asarray(self.clap_tokenizer([negative_prompt, prompt]),
                       np.int32)
        )
        t5_tok = np.asarray(
            self.t5_tokenizer([negative_prompt, prompt], self.max_t5),
            np.int32,
        )
        t5_mask = (t5_tok != 0).astype(np.float32)
        t5_mask[:, 0] = 1.0
        t5_ids = jnp.asarray(t5_tok)
        t5_mask = jnp.asarray(t5_mask)
        seq, mask, t5_states = self._encode(params, clap_ids, t5_ids, t5_mask)
        generated = self._generate(params, seq, mask)
        timings = {"conditioning_s": round(time.perf_counter() - t0, 3)}

        rng, init_rng, step_rng = jax.random.split(rng, 3)
        latent_c = self.unet.config.in_channels
        noise = jax.random.normal(
            init_rng, (1, lt, lf, latent_c), jnp.float32
        )
        t0 = time.perf_counter()
        program = self._program((lt, lf, steps, scheduler_type))
        wav = jax.block_until_ready(
            program(params, noise, generated, t5_states, t5_mask,
                    jnp.float32(guidance_scale), step_rng)
        )
        timings["denoise_vocode_s"] = round(time.perf_counter() - t0, 3)

        wav = normalize_wav(np.asarray(wav, np.float32)[0])
        out_rate = int(SAMPLE_RATE / HOP * self.vocoder_hop)
        config = {
            "model": self.model_name,
            "pipeline": "AudioLDM2Pipeline",
            "steps": steps,
            "duration_s": duration_s,
            "sample_rate": out_rate,
            "scheduler": scheduler_type,
            "vocoder": "hifigan",
            "guidance_scale": guidance_scale,
            "timings": timings,
        }
        return wav, config


@register_family("audioldm2")
def _build_audioldm2(model_name, chipset, **variant):
    return AudioLDM2Pipeline(model_name, chipset, **variant)
