"""SD-x2 learned latent upscaler (stabilityai/sd-x2-latent-upscaler).

Reference behavior replaced: swarm/post_processors/upscale.py:5-36 loads
`StableDiffusionLatentUpscalePipeline` per upscale job and runs 20 unguided
steps on the decoded images; swarm/diffusion/diffusion_func.py:163 chains
it after the main/refiner/decoder stages whenever the job sets `upscale`.

TPU redesign: a resident jitted program. The input image VAE-encodes to
latents, the latents nearest-upsample 2x as the conditioning half of an
8-channel UNet input (noise latents + image latents, the latent-upscaler
conditioning scheme), a `lax.scan` runs the Euler solver unguided
(reference passes guidance_scale=0), and the decode happens at 2x inside
the same program — the handoff never leaves the device between encode and
final pixels.
"""

from __future__ import annotations

import logging
import threading
import time
import zlib

import jax
import jax.numpy as jnp
import numpy as np
from PIL import Image

from ..models import configs as cfgs
from ..models.clip import CLIPTextEncoder
from ..models.tokenizer import load_tokenizer
from ..models.unet2d import UNet2DConditionModel, UNet2DConfig
from ..models.vae import AutoencoderKL
from ..parallel.mesh import make_mesh, replicated
from ..registry import register_family
from ..schedulers import get_scheduler
from ..weights import is_test_model, require_weights_present

logger = logging.getLogger(__name__)

_NO_CONVERSION_HINT = (
    "This worker cannot serve real sd-x2-latent-upscaler weights yet; only "
    "the test/tiny upscaler is available."
)

# noise latents + image latents concatenated on channels
IN_CHANNELS = 8

# sd-x2-latent-upscaler geometry (approximated; text tower is CLIP ViT-L)
SDX2_UNET = UNet2DConfig(
    in_channels=IN_CHANNELS,
    block_out_channels=(384, 768, 1280, 1280),
    transformer_layers=(1, 1, 1, 0),
    num_attention_heads=(6, 12, 20, 20),
    cross_attention_dim=768,
)
TINY_SDX2_UNET = UNet2DConfig(
    in_channels=IN_CHANNELS,
    block_out_channels=(32, 64),
    transformer_layers=(1, 1),
    mid_transformer_layers=1,
    layers_per_block=1,
    num_attention_heads=4,
    cross_attention_dim=32,
)


_is_tiny = is_test_model


def upscaler_name_for(model_name: str) -> str:
    """The upscaler to chain after a main pipeline of `model_name`."""
    if _is_tiny(model_name):
        return "test/tiny-upscaler"
    return "stabilityai/sd-x2-latent-upscaler"


class LatentUpscalePipeline:
    """Resident 2x latent upscaler serving the
    StableDiffusionLatentUpscalePipeline wire name, standalone or chained
    after any image-producing stage."""

    def __init__(self, model_name: str, chipset=None,
                 allow_random_init: bool = False):
        require_weights_present(
            model_name, None, allow_random_init, component="latent upscaler",
            hint=_NO_CONVERSION_HINT,
        )
        self.model_name = model_name
        self.chipset = chipset
        if _is_tiny(model_name):
            unet_cfg, clip_cfg, vae_cfg = (
                TINY_SDX2_UNET, cfgs.TINY_CLIP, cfgs.TINY_VAE
            )
        else:
            unet_cfg, clip_cfg, vae_cfg = SDX2_UNET, cfgs.SD15_CLIP, cfgs.SD_VAE
        on_tpu = jax.default_backend() == "tpu"
        self.dtype = jnp.bfloat16 if on_tpu else jnp.float32
        self.unet = UNet2DConditionModel(unet_cfg, dtype=self.dtype)
        self.text_encoder = CLIPTextEncoder(clip_cfg, dtype=self.dtype)
        self.tokenizer = load_tokenizer(None, vocab_size=clip_cfg.vocab_size)
        self.vae = AutoencoderKL(vae_cfg, dtype=self.dtype)
        self.latent_factor = 2 ** (len(vae_cfg.block_out_channels) - 1)
        self.mesh = (
            chipset.mesh() if chipset is not None else make_mesh(jax.devices()[:1])
        )

        rng = jax.random.key(zlib.crc32(model_name.encode()))
        k1, k2, k3 = jax.random.split(rng, 3)
        n_down = len(unet_cfg.block_out_channels) - 1
        hw = 2 ** max(n_down, 2)
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            unet_params = self.unet.init(
                k1,
                jnp.zeros((1, hw, hw, IN_CHANNELS)),
                jnp.zeros((1,)),
                jnp.zeros((1, 77, unet_cfg.cross_attention_dim)),
            )["params"]
            text_params = self.text_encoder.init(
                k2, jnp.zeros((1, 77), jnp.int32)
            )["params"]
            vae_params = self.vae.init(
                k3,
                jnp.zeros(
                    (1, hw * self.latent_factor, hw * self.latent_factor, 3)
                ),
            )["params"]
        cast = lambda x: jnp.asarray(x, self.dtype)
        self.params = jax.device_put(
            jax.tree_util.tree_map(cast, {
                "unet": unet_params,
                "text": text_params,
                "vae": vae_params,
            }),
            replicated(self.mesh),
        )
        self._programs: dict[tuple, callable] = {}
        self._lock = threading.Lock()

    def release(self):
        self.params = None
        self._programs.clear()

    def _program(self, key: tuple):
        with self._lock:
            if key in self._programs:
                return self._programs[key]
        lh, lw, batch, steps = key  # INPUT latent dims; output is 2x
        scheduler = get_scheduler("EulerDiscreteScheduler")
        schedule = scheduler.schedule(steps)
        unet = self.unet
        vae = self.vae
        latent_c = self.vae.config.latent_channels
        # the 2x decode has 4x the activation footprint of a base decode —
        # chunk it per-image on big canvases (same guard as SDPipeline;
        # batch 4 x 1024^2 OOM'd a v5e chip in round 1)
        big_decode = (2 * lh) * (2 * lw) >= 9216 and batch >= 2

        def run(params, rng, pixels, context):
            """pixels [B,H,W,3] in [-1,1]; unguided (reference passes
            guidance_scale=0 at upscale.py:31)."""
            image_latents = vae.apply(
                {"params": params["vae"]}, pixels.astype(self.dtype),
                method=vae.encode,
            ).astype(jnp.float32)
            cond = jax.image.resize(
                image_latents, (batch, 2 * lh, 2 * lw, latent_c), "nearest"
            )
            latents = jax.random.normal(
                rng, (batch, 2 * lh, 2 * lw, latent_c), jnp.float32
            ) * jnp.asarray(schedule.init_noise_sigma, jnp.float32)
            state = scheduler.init_state(latents.shape, latents.dtype)

            def body(carry, i):
                latents, state = carry
                inp = scheduler.scale_model_input(schedule, latents, i)
                model_in = jnp.concatenate([inp, cond], axis=-1)
                t = jnp.asarray(schedule.timesteps)[i]
                pred = unet.apply(
                    {"params": params["unet"]},
                    model_in.astype(self.dtype),
                    jnp.broadcast_to(t, (batch,)),
                    context,
                ).astype(jnp.float32)
                noise = jax.random.normal(
                    jax.random.fold_in(rng, i), latents.shape, jnp.float32
                )
                state, latents = scheduler.step(
                    schedule, state, i, latents, pred, noise
                )
                return (latents, state), ()

            (latents, _), _ = jax.lax.scan(
                body, (latents, state), jnp.arange(steps)
            )
            latents = latents.astype(self.dtype)
            if big_decode:
                pixels = jax.lax.map(
                    lambda z: vae.apply(
                        {"params": params["vae"]}, z[None], method=vae.decode
                    )[0],
                    latents,
                )
            else:
                pixels = vae.apply(
                    {"params": params["vae"]}, latents, method=vae.decode
                )
            return (
                (pixels.astype(jnp.float32) + 1.0) * 127.5
            ).clip(0.0, 255.0).round().astype(jnp.uint8)

        program = jax.jit(run)
        with self._lock:
            self._programs[key] = program
        return program

    def upscale(self, images: list[Image.Image], prompt: str = "",
                negative_prompt: str = "", steps: int = 20, rng=None):
        """images -> 2x images (the chained-stage entry point)."""
        params = self.params
        if params is None:
            raise Exception(f"upscaler {self.model_name} was evicted; resubmit")
        if rng is None:
            rng = jax.random.key(0)
        if any(img.size != images[0].size for img in images):
            # silently resizing to the first image's canvas would distort
            # the rest of the batch
            raise ValueError(
                "latent upscale requires equal-size input images; got "
                + str([img.size for img in images])
            )
        w, h = images[0].size
        w, h = (max(64, (d // 64) * 64) for d in (w, h))
        batch = len(images)
        pixels = jnp.asarray(
            np.stack([
                np.asarray(img.convert("RGB").resize((w, h)), np.float32)
                for img in images
            ]) / 127.5 - 1.0
        )
        # unguided: the prompt still conditions via cross-attention, one row
        ids = jnp.asarray(self.tokenizer([prompt] * batch))
        context = self.text_encoder.apply(
            {"params": params["text"]}, ids
        )["hidden_states"]
        program = self._program(
            (h // self.latent_factor, w // self.latent_factor, batch, steps)
        )
        out = jax.block_until_ready(program(params, rng, pixels, context))
        return [Image.fromarray(img) for img in np.asarray(out)]

    def run(self, prompt="", negative_prompt="",
            pipeline_type="StableDiffusionLatentUpscalePipeline", **kwargs):
        """Standalone upscale job (img2img wire shape with this
        pipeline_type)."""
        image = kwargs.pop("image", None)
        if image is None:
            raise ValueError("latent upscale requires an input image")
        steps = int(kwargs.pop("num_inference_steps", 20))
        rng = kwargs.pop("rng", None)
        images = image if isinstance(image, list) else [image]
        t0 = time.perf_counter()
        out = self.upscale(
            images, prompt=prompt, negative_prompt=negative_prompt,
            steps=steps, rng=rng,
        )
        pipeline_config = {
            "model": self.model_name,
            "pipeline": pipeline_type,
            "scheduler": "EulerDiscreteScheduler",
            "mode": "upscale",
            "steps": steps,
            "size": list(out[0].size),
            "timings": {
                "denoise_decode_s": round(time.perf_counter() - t0, 3)
            },
        }
        return out, pipeline_config


@register_family("sd_upscale")
def _build_upscaler(model_name, chipset, **variant):
    return LatentUpscalePipeline(model_name, chipset, **variant)
