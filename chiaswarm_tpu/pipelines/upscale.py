"""SD-x2 learned latent upscaler (stabilityai/sd-x2-latent-upscaler).

Reference behavior replaced: swarm/post_processors/upscale.py:5-36 loads
`StableDiffusionLatentUpscalePipeline` per upscale job and runs 20 unguided
steps on the decoded images; swarm/diffusion/diffusion_func.py:163 chains
it after the main/refiner/decoder stages whenever the job sets `upscale`.

TPU redesign: a resident jitted program around the TRUE architecture
(models/k_upscaler.py — the K-diffusion upscaler UNet). The input image
VAE-encodes to scaled latents, the latents nearest-upsample 2x as the
conditioning half of the 8-channel UNet input, a `lax.scan` runs the
denoised-sample Euler solver unguided (reference passes guidance_scale=0)
with the pipeline's exact conditioning — continuous log(sigma)/4
timesteps, and a 896-d timestep condition of [fixed 64 ones | 64 zeros |
CLIP pooler output] — and the decode happens at 2x inside the same
program. Real checkpoints convert at load (conversion.py
convert_k_upscaler, geometry inferred from the checkpoint); the 5th
output channel is dropped exactly as the diffusers pipeline drops it.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import threading
import time
import zlib
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np
from PIL import Image

from ..models import configs as cfgs
from ..models.clip import CLIPTextEncoder
from ..models.k_upscaler import (
    TINY_K_UPSCALER,
    KUpscalerConfig,
    KUpscalerUNet,
)
from ..models.tokenizer import load_tokenizer
from ..models.vae import AutoencoderKL
from ..parallel.mesh import make_mesh, replicated
from ..registry import register_family
from ..schedulers import get_scheduler
from ..weights import (
    MissingWeightsError,
    is_test_model,
    model_dir_for,
    require_weights_present,
)

logger = logging.getLogger(__name__)

_NO_CONVERSION_HINT = (
    "No converted sd-x2-latent-upscaler checkpoint is present; download it "
    "first (initialize --download) or use the test/tiny upscaler."
)

_is_tiny = is_test_model


def upscaler_name_for(model_name: str) -> str:
    """The upscaler to chain after a main pipeline of `model_name`."""
    if _is_tiny(model_name):
        return "test/tiny-upscaler"
    return "stabilityai/sd-x2-latent-upscaler"


def convert_upscaler_checkpoint(model_dir):
    """One sd-x2 repo conversion recipe ->
    (unet_cfg, unet, clip_cfg, text, vae_cfg, vae, sched_json) — shared by
    serving and `initialize --check`."""
    from ..models.conversion import (
        convert_clip,
        convert_k_upscaler,
        convert_vae,
        infer_vae_config,
        load_torch_state_dict,
    )

    def cfg_json(sub):
        p = model_dir / sub / "config.json"
        return json.loads(p.read_text()) if p.is_file() else {}

    ucfg, unet = convert_k_upscaler(
        load_torch_state_dict(model_dir, "unet"), cfg_json("unet")
    )
    text = convert_clip(load_torch_state_dict(model_dir, "text_encoder"))
    tj = cfg_json("text_encoder")
    clip_cfg = dataclasses.replace(
        cfgs.SD15_CLIP,
        vocab_size=int(tj.get("vocab_size", 49408)),
        hidden_size=int(tj.get("hidden_size", 768)),
        num_layers=int(tj.get("num_hidden_layers", 12)),
        num_heads=int(tj.get("num_attention_heads", 12)),
        hidden_act=str(tj.get("hidden_act", "quick_gelu")),
        # the pipeline conditions on hidden_states[-1]: the last layer's
        # output BEFORE the final LayerNorm (pooled still uses final LN)
        hidden_state_index=-1,
        apply_final_norm=False,
    )
    vae_state = load_torch_state_dict(model_dir, "vae")
    vae_cfg = infer_vae_config(vae_state, cfg_json("vae"))
    vae = convert_vae(vae_state)
    p = model_dir / "scheduler" / "scheduler_config.json"
    sched_json = json.loads(p.read_text()) if p.is_file() else {}
    return ucfg, unet, clip_cfg, text, vae_cfg, vae, sched_json


def _load_converted_upscaler(model_name: str):
    if _is_tiny(model_name):
        return None
    d = model_dir_for(model_name)
    if d is None:
        return None
    try:
        ucfg, unet, ccfg, text, vcfg, vae, sj = convert_upscaler_checkpoint(d)
    except (FileNotFoundError, OSError):
        return None
    except Exception as e:
        raise MissingWeightsError(
            f"checkpoint under {d} could not be converted for "
            f"'{model_name}': {e}"
        ) from e
    return {
        "unet_cfg": ucfg, "unet": unet, "clip_cfg": ccfg, "text": text,
        "vae_cfg": vcfg, "vae": vae, "scheduler_json": sj, "model_dir": d,
    }


# the pipeline's fixed noise-level embedding: noise_level=0 ->
# [ones(half) | zeros(half)], concatenated before the CLIP pooler output
def _timestep_condition(cond_dim: int, pooled):
    b, pw = pooled.shape
    half = (cond_dim - pw) // 2
    return jnp.concatenate(
        [
            jnp.ones((b, half), pooled.dtype),
            jnp.zeros((b, cond_dim - pw - half), pooled.dtype),
            pooled,
        ],
        axis=-1,
    )


class LatentUpscalePipeline:
    """Resident 2x latent upscaler serving the
    StableDiffusionLatentUpscalePipeline wire name, standalone or chained
    after any image-producing stage."""

    def __init__(self, model_name: str, chipset=None,
                 allow_random_init: bool = False):
        converted = _load_converted_upscaler(model_name)
        if converted is None:
            require_weights_present(
                model_name, model_dir_for(model_name), allow_random_init,
                component="latent upscaler", hint=_NO_CONVERSION_HINT,
            )
        self.model_name = model_name
        self.chipset = chipset
        if converted is not None:
            unet_cfg = converted["unet_cfg"]
            clip_cfg = converted["clip_cfg"]
            vae_cfg = converted["vae_cfg"]
            self.scheduler_json = converted["scheduler_json"]
        elif _is_tiny(model_name):
            unet_cfg, clip_cfg, vae_cfg = (
                TINY_K_UPSCALER,
                dataclasses.replace(cfgs.TINY_CLIP, apply_final_norm=False),
                cfgs.TINY_VAE,
            )
            self.scheduler_json = {}
        else:  # bench path at real geometry
            unet_cfg, clip_cfg, vae_cfg = (
                KUpscalerConfig(),
                dataclasses.replace(
                    cfgs.SD15_CLIP, hidden_state_index=-1,
                    apply_final_norm=False,
                ),
                cfgs.SD_VAE,
            )
            self.scheduler_json = {}
        on_tpu = jax.default_backend() == "tpu"
        self.dtype = jnp.bfloat16 if on_tpu else jnp.float32
        self.unet = KUpscalerUNet(unet_cfg, dtype=self.dtype)
        self.text_encoder = CLIPTextEncoder(clip_cfg, dtype=self.dtype)
        self.tokenizer = load_tokenizer(None, vocab_size=clip_cfg.vocab_size)
        self.vae = AutoencoderKL(vae_cfg, dtype=self.dtype)
        self.latent_factor = 2 ** (len(vae_cfg.block_out_channels) - 1)
        self.mesh = (
            chipset.mesh() if chipset is not None else make_mesh(jax.devices()[:1])
        )

        if converted is not None:
            from ..models.conversion import checked_converted

            rng = jax.random.key(0)
            checked_converted(
                self.unet,
                (jnp.zeros((1, 8, 8, unet_cfg.in_channels)),
                 jnp.zeros((1,)),
                 jnp.zeros((1, 77, unet_cfg.cross_attention_dim)),
                 jnp.zeros((1, unet_cfg.time_cond_proj_dim))),
                converted["unet"], "upscaler unet", rng,
            )
            # stale text_encoder/vae config.jsons would otherwise surface
            # mid-job as opaque XLA shape errors
            checked_converted(
                self.text_encoder, (jnp.zeros((1, 77), jnp.int32),),
                converted["text"], "upscaler text_encoder", rng,
            )
            f = self.latent_factor
            checked_converted(
                self.vae, (jnp.zeros((1, 4 * f, 4 * f, 3)),),
                converted["vae"], "upscaler vae", rng,
            )
            params = {
                "unet": converted["unet"],
                "text": converted["text"],
                "vae": converted["vae"],
            }
        else:
            params = self._random_params(unet_cfg, clip_cfg, vae_cfg)
        cast = lambda x: jnp.asarray(x, self.dtype)
        self.params = jax.device_put(
            jax.tree_util.tree_map(cast, params), replicated(self.mesh)
        )
        # insertion-ordered so the program_cache_max bound below can evict
        # least-recently-used first (SW007; same knob as the SD family)
        self._programs: OrderedDict = OrderedDict()
        self._lock = threading.Lock()

    def _random_params(self, unet_cfg, clip_cfg, vae_cfg):
        rng = jax.random.key(zlib.crc32(self.model_name.encode()))
        k1, k2, k3 = jax.random.split(rng, 3)
        n_down = len(unet_cfg.block_out_channels) - 1
        hw = 2 ** max(n_down, 2)
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            unet_params = self.unet.init(
                k1,
                jnp.zeros((1, hw, hw, unet_cfg.in_channels)),
                jnp.zeros((1,)),
                jnp.zeros((1, 77, unet_cfg.cross_attention_dim)),
                jnp.zeros((1, unet_cfg.time_cond_proj_dim)),
            )["params"]
            text_params = self.text_encoder.init(
                k2, jnp.zeros((1, 77), jnp.int32)
            )["params"]
            vae_params = self.vae.init(
                k3,
                jnp.zeros(
                    (1, hw * self.latent_factor, hw * self.latent_factor, 3)
                ),
            )["params"]
        return {"unet": unet_params, "text": text_params, "vae": vae_params}

    def release(self):
        self.params = None
        self._programs.clear()

    def _scheduler(self):
        """EulerDiscrete in denoised-sample prediction, geometry from the
        shipped scheduler_config.json when a real checkpoint is resident."""
        sj = self.scheduler_json
        kw = {"prediction_type": str(sj.get("prediction_type", "sample"))}
        for field in ("beta_start", "beta_end"):
            if field in sj:
                kw[field] = float(sj[field])
        if "beta_schedule" in sj:
            kw["beta_schedule"] = str(sj["beta_schedule"])
        if "num_train_timesteps" in sj:
            kw["num_train_timesteps"] = int(sj["num_train_timesteps"])
        return get_scheduler("EulerDiscreteScheduler", **kw)

    def _program(self, key: tuple):
        with self._lock:
            if key in self._programs:
                self._programs.move_to_end(key)
                return self._programs[key]
        lh, lw, batch, steps = key  # INPUT latent dims; output is 2x
        scheduler = self._scheduler()
        schedule = scheduler.schedule(steps)
        unet = self.unet
        vae = self.vae
        latent_c = self.vae.config.latent_channels
        cond_dim = self.unet.config.time_cond_proj_dim
        # the 2x decode has 4x the activation footprint of a base decode —
        # chunk it per-image on big canvases (same guard as SDPipeline;
        # batch 4 x 1024^2 OOM'd a v5e chip in round 1)
        big_decode = (2 * lh) * (2 * lw) >= 9216 and batch >= 2

        def run(params, rng, pixels, context, pooled):
            """pixels [B,H,W,3] in [-1,1]; unguided (reference passes
            guidance_scale=0 at upscale.py:31)."""
            image_latents = vae.apply(
                {"params": params["vae"]}, pixels.astype(self.dtype),
                method=vae.encode,
            ).astype(jnp.float32)
            # noise_level=0: inv_noise_level = 1, so the conditioning half
            # is exactly the nearest-2x latents
            cond = jax.image.resize(
                image_latents, (batch, 2 * lh, 2 * lw, latent_c), "nearest"
            )
            timestep_cond = _timestep_condition(cond_dim, pooled)
            latents = jax.random.normal(
                rng, (batch, 2 * lh, 2 * lw, latent_c), jnp.float32
            ) * jnp.asarray(schedule.init_noise_sigma, jnp.float32)
            state = scheduler.init_state(latents.shape, latents.dtype)
            sigmas = jnp.asarray(schedule.sigmas, jnp.float32)

            def body(carry, i):
                latents, state = carry
                sigma = sigmas[i]
                inp = scheduler.scale_model_input(schedule, latents, i)
                model_in = jnp.concatenate([inp, cond], axis=-1)
                # continuous K-diffusion timestep: log(sigma)/4
                t = jnp.log(sigma) * 0.25
                pred = unet.apply(
                    {"params": params["unet"]},
                    model_in.astype(self.dtype),
                    jnp.broadcast_to(t, (batch,)),
                    context,
                    timestep_cond,
                ).astype(jnp.float32)
                pred = pred[..., : latent_c]  # 5th channel dropped
                # Karras table-1 preconditioning (the diffusers pipeline
                # applies it OUTSIDE the UNet before the solver step):
                # x0 = c_skip*x + c_out*F(c_in*x), c_skip = 1/(sigma^2+1),
                # c_out = sigma/sqrt(sigma^2+1)
                x0_pred = latents / (sigma**2 + 1.0) + pred * (
                    sigma / jnp.sqrt(sigma**2 + 1.0)
                )
                noise = jax.random.normal(
                    jax.random.fold_in(rng, i), latents.shape, jnp.float32
                )
                state, latents = scheduler.step(
                    schedule, state, i, latents, x0_pred, noise
                )
                return (latents, state), ()

            (latents, _), _ = jax.lax.scan(
                body, (latents, state), jnp.arange(steps)
            )
            latents = latents.astype(self.dtype)
            if big_decode:
                pixels = jax.lax.map(
                    lambda z: vae.apply(
                        {"params": params["vae"]}, z[None], method=vae.decode
                    )[0],
                    latents,
                )
            else:
                pixels = vae.apply(
                    {"params": params["vae"]}, latents, method=vae.decode
                )
            return (
                (pixels.astype(jnp.float32) + 1.0) * 127.5
            ).clip(0.0, 255.0).round().astype(jnp.uint8)

        program = jax.jit(run)
        with self._lock:
            self._programs[key] = program
            from .common import PROGRAM_EVICTED, program_cache_cap

            cap = program_cache_cap()
            while cap and len(self._programs) > cap:
                self._programs.popitem(last=False)
                PROGRAM_EVICTED.inc(kind="program")
        return program

    def upscale(self, images: list[Image.Image], prompt: str = "",
                negative_prompt: str = "", steps: int = 20, rng=None):
        """images -> 2x images (the chained-stage entry point)."""
        params = self.params
        if params is None:
            raise Exception(f"upscaler {self.model_name} was evicted; resubmit")
        if rng is None:
            rng = jax.random.key(0)
        if any(img.size != images[0].size for img in images):
            # silently resizing to the first image's canvas would distort
            # the rest of the batch
            raise ValueError(
                "latent upscale requires equal-size input images; got "
                + str([img.size for img in images])
            )
        w, h = images[0].size
        w, h = (max(64, (d // 64) * 64) for d in (w, h))
        batch = len(images)
        pixels = jnp.asarray(
            np.stack([
                np.asarray(img.convert("RGB").resize((w, h)), np.float32)
                for img in images
            ]) / 127.5 - 1.0
        )
        # unguided: the prompt still conditions via cross-attention and the
        # pooled timestep condition, one row per image
        ids = jnp.asarray(self.tokenizer([prompt] * batch))
        out = self.text_encoder.apply({"params": params["text"]}, ids)
        context, pooled = out["hidden_states"], out["pooled"]
        program = self._program(
            (h // self.latent_factor, w // self.latent_factor, batch, steps)
        )
        out = jax.block_until_ready(
            program(params, rng, pixels, context, pooled)
        )
        return [Image.fromarray(img) for img in np.asarray(out)]

    def run(self, prompt="", negative_prompt="",
            pipeline_type="StableDiffusionLatentUpscalePipeline", **kwargs):
        """Standalone upscale job (img2img wire shape with this
        pipeline_type)."""
        image = kwargs.pop("image", None)
        if image is None:
            raise ValueError("latent upscale requires an input image")
        steps = int(kwargs.pop("num_inference_steps", 20))
        rng = kwargs.pop("rng", None)
        images = image if isinstance(image, list) else [image]
        t0 = time.perf_counter()
        out = self.upscale(
            images, prompt=prompt, negative_prompt=negative_prompt,
            steps=steps, rng=rng,
        )
        pipeline_config = {
            "model": self.model_name,
            "pipeline": pipeline_type,
            "scheduler": "EulerDiscreteScheduler",
            "mode": "upscale",
            "steps": steps,
            "size": list(out[0].size),
            "timings": {
                "denoise_decode_s": round(time.perf_counter() - t0, 3)
            },
        }
        return out, pipeline_config


@register_family("sd_upscale")
def _build_upscaler(model_name, chipset, **variant):
    return LatentUpscalePipeline(model_name, chipset, **variant)
