"""DeepFloyd IF cascade: pixel-space base diffusion + super-resolution.

Reference behavior replaced: swarm/diffusion/diffusion_func_if.py:13-69 —
a 3-stage cascade (IF-I 64px -> IF-II 256px -> x4 upscaler) that was
shipped half-finished: prompt embeddings were `torch.randn` placeholders
(:34-36) and :62 referenced an undefined variable (NameError on every
job). The capability is rebuilt here for real.

TPU redesign: both IF stages are resident jitted programs operating in
PIXEL space (no VAE anywhere — that is the defining trait of this family).
Stage I denoises a 64px RGB canvas under one `lax.scan` with CFG as a
batch of 2, cross-attending on real T5 encodings (the reference family
conditions on T5-XL; the same `models/t5.py` encoder that serves Flux).
Stage II concatenates the 4x nearest-upsampled stage-I output onto the
noise channels (6-channel UNet input, the IF super-res conditioning
scheme) and denoises at 256px. The reference's third stage (an SD x4
upscaler) maps onto this package's learned latent upscaler when the job
requests `upscale`. Real-weight conversion for this family is not wired
yet, so non-test model names fail loudly per weights.py.
"""

from __future__ import annotations

import logging
import threading
import time
import zlib
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np
from PIL import Image

from ..models.t5 import TINY_T5, T5Config, T5Encoder
from ..models.unet_kandinsky import (
    IF_UNET,
    TINY_IF_SR_UNET,
    TINY_IF_UNET,
    K22UNet,
)
from ..parallel.mesh import make_mesh, replicated
from ..registry import register_family
from ..schedulers import get_scheduler
from ..weights import is_test_model, require_weights_present

logger = logging.getLogger(__name__)

_NO_CONVERSION_HINT = (
    "DeepFloyd IF weights were not found under the model root; run "
    "`chiaswarm-tpu-init --download` to fetch and convert them (the "
    "cascade needs BOTH the IF-I and matching IF-II repos)."
)

# stage II upsamples the base canvas by this factor
SR_FACTOR = 4


_is_tiny = is_test_model


# Real IF geometry analogs (conversion re-derives the true numbers from
# the checkpoints; see models/unet_kandinsky.py — IF shares the
# ResnetDownsample/SimpleCrossAttn block family with Kandinsky 2.2)
import dataclasses as _dc

IF_SR_UNET = _dc.replace(
    IF_UNET,
    in_channels=6,
    block_out_channels=(320, 640, 1280, 1280),
    class_embed_timestep=True,
)


def _configs(model_name: str):
    """(base_cfg, sr_cfg, t5_cfg, base_size)."""
    if _is_tiny(model_name):
        return TINY_IF_UNET, TINY_IF_SR_UNET, TINY_T5, 32
    return IF_UNET, IF_SR_UNET, T5Config(), 64


def _sr_name_for(base_name: str) -> str:
    """DeepFloyd/IF-I-XL-v1.0 -> the matching stage-II repo (IF-II tops
    out at L, so XL maps to L)."""
    if "IF-I-XL" in base_name:
        return base_name.replace("IF-I-XL", "IF-II-L")
    return base_name.replace("IF-I-", "IF-II-")


def _model_dir(model_name: str):
    from ..weights import model_dir_for

    return model_dir_for(model_name)


def _load_converted_if(model_name: str):
    """-> {"base_cfg","base","sr_cfg","sr","t5","model_dir"} or None.
    All-or-nothing: the cascade needs IF-I unet + T5 + IF-II unet; a
    partial set would serve one real stage against one random stage."""
    if _is_tiny(model_name):
        return None
    d = _model_dir(model_name)
    sr_d = _model_dir(_sr_name_for(model_name))
    if d is None:
        return None
    from ..models.conversion import (
        convert_kandinsky_unet,
        convert_t5,
        load_torch_state_dict,
    )
    from ..weights import MissingWeightsError

    def unet_cfg_json(mdir):
        import json

        p = mdir / "unet" / "config.json"
        return json.loads(p.read_text()) if p.is_file() else {}

    try:
        base_cfg, base = convert_kandinsky_unet(
            load_torch_state_dict(d, "unet"), unet_cfg_json(d)
        )
        t5 = convert_t5(load_torch_state_dict(d, "text_encoder"))
        if sr_d is None:
            raise FileNotFoundError(
                f"stage-II repo {_sr_name_for(model_name)} not downloaded"
            )
        sr_cfg, sr = convert_kandinsky_unet(
            load_torch_state_dict(sr_d, "unet"), unet_cfg_json(sr_d)
        )
    except (FileNotFoundError, OSError):
        return None
    except Exception as e:
        raise MissingWeightsError(
            f"checkpoint under {d} could not be converted for "
            f"'{model_name}': {e}"
        ) from e
    return {
        "base_cfg": base_cfg, "base": base,
        "sr_cfg": sr_cfg, "sr": sr,
        "t5": t5, "model_dir": d,
    }


class DeepFloydIFPipeline:
    """Resident two-stage IF cascade serving `DeepFloyd/*` model names."""

    def __init__(self, model_name: str, chipset=None,
                 allow_random_init: bool = False):
        self.model_name = model_name
        self.chipset = chipset
        base_cfg, sr_cfg, t5_cfg, self.base_size = _configs(model_name)
        converted = _load_converted_if(model_name)
        if converted is None:
            require_weights_present(
                model_name, None, allow_random_init, component="DeepFloyd IF",
                hint=_NO_CONVERSION_HINT,
            )
        else:
            base_cfg = converted["base_cfg"]
            sr_cfg = converted["sr_cfg"]
        on_tpu = jax.default_backend() == "tpu"
        self.dtype = jnp.bfloat16 if on_tpu else jnp.float32
        self.base_unet = K22UNet(base_cfg, dtype=self.dtype)
        self.sr_unet = K22UNet(sr_cfg, dtype=self.dtype)
        self.t5 = T5Encoder(t5_cfg, dtype=self.dtype)
        from .flux import _load_t5_tokenizer

        self.tokenizer = _load_t5_tokenizer(
            converted["model_dir"] if converted else None, t5_cfg.vocab_size
        )
        self.mesh = (
            chipset.mesh() if chipset is not None else make_mesh(jax.devices()[:1])
        )

        rng = jax.random.key(zlib.crc32(model_name.encode()))
        k1, k2, k3 = jax.random.split(rng, 3)
        hw = 2 ** max(len(base_cfg.block_out_channels) - 1, 2)
        base_args = (
            jnp.zeros((1, hw, hw, base_cfg.in_channels)),
            jnp.zeros((1,)),
            jnp.zeros((1, 77, base_cfg.encoder_hid_dim)),
        )
        sr_args = (
            jnp.zeros((1, hw, hw, sr_cfg.in_channels)),
            jnp.zeros((1,)),
            jnp.zeros((1, 77, sr_cfg.encoder_hid_dim)),
        )
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            if converted is not None:
                from ..models.conversion import checked_converted as _checked_converted

                base_params = _checked_converted(
                    self.base_unet, base_args, converted["base"], "base", k1
                )
                sr_params = _checked_converted(
                    self.sr_unet, sr_args, converted["sr"], "sr", k2
                )
                t5_params = _checked_converted(
                    self.t5, (jnp.zeros((1, 16), jnp.int32),),
                    converted["t5"], "t5", k3,
                )
                logger.info("loaded converted IF weights for %s", model_name)
            else:
                base_params = self.base_unet.init(k1, *base_args)["params"]
                sr_params = self.sr_unet.init(k2, *sr_args)["params"]
                t5_params = self.t5.init(
                    k3, jnp.zeros((1, 16), jnp.int32)
                )["params"]
        cast = lambda x: jnp.asarray(x, self.dtype)
        self.params = jax.device_put(
            jax.tree_util.tree_map(cast, {
                "base": base_params,
                "sr": sr_params,
                "t5": t5_params,
            }),
            replicated(self.mesh),
        )
        # insertion-ordered so the program_cache_max bound below can evict
        # least-recently-used first (SW007; same knob as the SD family)
        self._programs: OrderedDict = OrderedDict()
        self._lock = threading.Lock()

    def release(self):
        self.params = None
        self._programs.clear()

    def _program(self, key: tuple):
        """One fused program: stage-I denoise -> 4x upsample -> stage-II
        denoise. Pixel space end to end; nothing leaves the device."""
        with self._lock:
            if key in self._programs:
                self._programs.move_to_end(key)
                return self._programs[key]
        size, batch, steps, sr_steps = key
        scheduler = get_scheduler("DDPMScheduler")
        base_schedule = scheduler.schedule(steps)
        sr_schedule = scheduler.schedule(sr_steps)
        base_unet = self.base_unet
        sr_unet = self.sr_unet
        sr_size = size * SR_FACTOR

        def denoise(rng, shape, schedule_, n_steps, model_fn):
            latents = jax.random.normal(rng, shape, jnp.float32) * jnp.asarray(
                schedule_.init_noise_sigma, jnp.float32
            )
            state = scheduler.init_state(latents.shape, latents.dtype)

            def body(carry, i):
                latents, state = carry
                inp = scheduler.scale_model_input(schedule_, latents, i)
                t = jnp.asarray(schedule_.timesteps)[i]
                pred = model_fn(inp, t, i)
                noise = jax.random.normal(
                    jax.random.fold_in(rng, i), latents.shape, jnp.float32
                )
                state, latents = scheduler.step(
                    schedule_, state, i, latents, pred, noise
                )
                return (latents, state), ()

            (latents, _), _ = jax.lax.scan(
                body, (latents, state), jnp.arange(n_steps)
            )
            return latents

        def run(params, rng, context, guidance):
            """context [2B,77,D] rows [uncond | cond]."""
            base_rng, sr_rng = jax.random.split(rng)

            def base_fn(inp, t, i):
                model_in = jnp.concatenate([inp, inp], axis=0).astype(self.dtype)
                pred = base_unet.apply(
                    {"params": params["base"]},
                    model_in,
                    jnp.broadcast_to(t, (2 * batch,)),
                    context,
                ).astype(jnp.float32)
                # learned-variance checkpoints emit 6 channels; the DDPM
                # step here is fixed-variance, so keep the pixel half
                pred = pred[..., :3]
                pred_u, pred_c = jnp.split(pred, 2, axis=0)
                return pred_u + guidance * (pred_c - pred_u)

            base_px = denoise(
                base_rng, (batch, size, size, 3), base_schedule, steps, base_fn
            )

            cond = jax.image.resize(
                base_px, (batch, sr_size, sr_size, 3), "nearest"
            )

            def sr_fn(inp, t, i):
                model_in = jnp.concatenate(
                    [
                        jnp.concatenate([inp, cond], axis=-1),
                        jnp.concatenate([inp, cond], axis=-1),
                    ],
                    axis=0,
                ).astype(self.dtype)
                pred = sr_unet.apply(
                    {"params": params["sr"]},
                    model_in,
                    jnp.broadcast_to(t, (2 * batch,)),
                    context,
                ).astype(jnp.float32)
                pred = pred[..., :3]
                pred_u, pred_c = jnp.split(pred, 2, axis=0)
                return pred_u + guidance * (pred_c - pred_u)

            pixels = denoise(
                sr_rng, (batch, sr_size, sr_size, 3), sr_schedule, sr_steps,
                sr_fn,
            )
            return (
                (pixels.astype(jnp.float32) + 1.0) * 127.5
            ).clip(0.0, 255.0).round().astype(jnp.uint8)

        program = jax.jit(run)
        with self._lock:
            self._programs[key] = program
            from .common import PROGRAM_EVICTED, program_cache_cap

            cap = program_cache_cap()
            while cap and len(self._programs) > cap:
                self._programs.popitem(last=False)
                PROGRAM_EVICTED.inc(kind="program")
        return program

    def run(self, prompt="", negative_prompt="", pipeline_type="IFPipeline",
            **kwargs):
        params = self.params
        if params is None:
            raise Exception(
                f"pipeline {self.model_name} was evicted; resubmit the job"
            )
        if pipeline_type == "IFSuperResolutionPipeline":
            # a standalone SR-typed job would need the caller's image;
            # silently regenerating from the prompt would violate the
            # fail-loud policy (the SR stage runs inside the cascade)
            raise Exception(
                "IFSuperResolutionPipeline is not schedulable standalone on "
                "this worker; submit the base DeepFloyd model (the super-"
                "resolution stage runs inside the cascade)."
            )
        timings: dict[str, float] = {}
        steps = int(kwargs.pop("num_inference_steps", 30))
        sr_steps = int(kwargs.pop("sr_steps", None) or max(steps // 2, 2))
        guidance_scale = float(kwargs.pop("guidance_scale", 7.0))
        n_images = int(kwargs.pop("num_images_per_prompt", 1))
        rng = kwargs.pop("rng", None)
        if rng is None:
            rng = jax.random.key(0)
        chipset = kwargs.pop("chipset", None)
        kwargs.pop("height", None)  # the cascade geometry fixes the canvas
        kwargs.pop("width", None)
        upscaler = None
        if kwargs.pop("upscale", False):
            # the reference's stage 3 (x4 SD upscaler, diffusion_func_if.py)
            # maps onto the learned latent upscaler; resolve BEFORE the
            # denoise so missing weights fail fast
            from ..registry import get_pipeline
            from .upscale import upscaler_name_for

            upscaler = get_pipeline(
                upscaler_name_for(self.model_name),
                pipeline_type="StableDiffusionLatentUpscalePipeline",
                chipset=chipset,
            )

        texts = [negative_prompt] * n_images + [prompt] * n_images
        max_seq = 77
        ids = jnp.asarray(
            np.asarray(self.tokenizer(texts, max_seq), np.int32)
        )
        t0 = time.perf_counter()
        context = self.t5.apply({"params": params["t5"]}, ids)
        timings["text_encode_s"] = round(time.perf_counter() - t0, 3)

        program = self._program((self.base_size, n_images, steps, sr_steps))
        t0 = time.perf_counter()
        pixels = jax.block_until_ready(
            program(params, rng, context, jnp.float32(guidance_scale))
        )
        timings["denoise_s"] = round(time.perf_counter() - t0, 3)

        images = [Image.fromarray(img) for img in np.asarray(pixels)]
        out_size = self.base_size * SR_FACTOR
        if upscaler is not None:
            t0 = time.perf_counter()
            images = upscaler.upscale(
                images, prompt=prompt, negative_prompt=negative_prompt,
                rng=jax.random.fold_in(rng, 0x1f),
            )
            timings["upscale_s"] = round(time.perf_counter() - t0, 3)
            out_size *= 2
        pipeline_config = {
            "model": self.model_name,
            "pipeline": pipeline_type,
            "scheduler": "DDPMScheduler",
            "mode": "txt2img",
            "steps": steps,
            "sr_steps": sr_steps,
            "size": [out_size, out_size],
            "guidance_scale": guidance_scale,
            "timings": timings,
        }
        return images, pipeline_config


@register_family("deepfloyd_if")
def _build_if(model_name, chipset, **variant):
    return DeepFloydIFPipeline(model_name, chipset, **variant)
