"""Shared per-job helpers for the resident pipelines.

The img2img start logic (strength clamp, scan start index, init-image
VAE encode through a cached jitted program) is identical across the
Kandinsky families — one implementation here so fixes land once.

Also home to the cross-job micro-batching helpers (batching.py design):
row-padding buckets so coalesce factors 3 and 4 share one compiled
program, per-request splitting of a coalesced image batch, and capacity
chunking that keeps every request's rows inside one denoise pass.
"""

from __future__ import annotations

import numpy as np

from .. import telemetry

# same metric the SD family's _trim_program_caches feeds — telemetry
# dedups by name, so whichever pipeline module imports first registers it
PROGRAM_EVICTED = telemetry.counter(
    "swarm_program_cache_evicted_total",
    "Compiled denoise programs / assembled runners evicted LRU at the "
    "program_cache_max bound, by kind",
    ("kind",),
)


def program_cache_cap() -> int:
    """Settings.program_cache_max at call time (env-overridable,
    CHIASWARM_PROGRAM_CACHE_MAX); 0 = unbounded. The dormant pipelines'
    `_programs` caches bound themselves with this (SW007 shrink,
    ISSUE 18) — the SD family keeps its own richer trim that also frees
    evicted executables (_trim_program_caches)."""
    try:
        from ..settings import load_settings

        return max(int(getattr(
            load_settings(), "program_cache_max", 64) or 0), 0)
    except Exception:  # settings must never gate a compile
        return 64


def pad_bucket(rows: int) -> int:
    """Next power-of-two row count >= rows.

    The batched denoise program is compiled per total row count; padding
    a coalesced batch up to the bucket boundary means factors 3 and 4
    (say) share one executable instead of compiling each distinct
    coalesce count the queue happens to produce.
    """
    p = 1
    while p < rows:
        p *= 2
    return p


def split_by_counts(items, counts: list[int]) -> list[list]:
    """Slice a flat per-row list back into per-request groups.

    The inverse of the row concatenation a coalesced batch performs;
    trailing padding rows (len(items) > sum(counts)) are dropped.
    """
    out, offset = [], 0
    for n in counts:
        out.append(list(items[offset:offset + n]))
        offset += n
    return out


def chunk_by_rows(counts: list[int], max_rows: int) -> list[tuple[int, int]]:
    """Greedy [start, end) request ranges whose row sums fit max_rows.

    Requests are atomic — one request's images never straddle two denoise
    passes. A single request bigger than max_rows still gets its own
    chunk (the pipeline's per-request capacity cap handles it), so every
    request is always served.
    """
    chunks: list[tuple[int, int]] = []
    start, rows = 0, 0
    for i, n in enumerate(counts):
        if i > start and rows + n > max_rows:
            chunks.append((start, i))
            start, rows = i, 0
        rows += n
    chunks.append((start, len(counts)))
    return chunks


def clamp_strength(value) -> float:
    """Strength outside [0,1] would index the schedule negatively."""
    return min(max(float(value), 0.0), 1.0)


def img2img_t_start(steps: int, strength: float) -> int:
    """Scan start index for an img2img job at this strength."""
    return min(max(int(steps * (1.0 - strength)), 0), steps - 1)


def encode_init_image(pipe, vae_params, image, width: int, height: int,
                      n_images: int, lh: int, lw: int, channels: int):
    """PIL init image -> [n_images, lh, lw, channels] float32 latents.

    Encodes through ONE cached jitted program per pipeline instance —
    an op-by-op `vae.apply` on the job hot path costs a host->device
    round trip per op (round-1 measurement: >50% of job time host-side,
    stable_diffusion.py's `_vae_encode_program` rationale).
    """
    import jax
    import jax.numpy as jnp
    from PIL import Image

    program = getattr(pipe, "_vae_encode_program", None)
    if program is None:
        program = jax.jit(
            lambda p, px: pipe.vae.apply(
                {"params": p}, px, method=pipe.vae.encode
            ).astype(jnp.float32)
        )
        pipe._vae_encode_program = program

    arr = (
        np.asarray(
            image.convert("RGB").resize((width, height), Image.LANCZOS),
            np.float32,
        )
        / 127.5
        - 1.0
    )
    latents = program(vae_params, jnp.asarray(arr)[None].astype(pipe.dtype))
    return jnp.broadcast_to(latents, (n_images, lh, lw, channels))
