"""Shared per-job helpers for the resident pipelines.

The img2img start logic (strength clamp, scan start index, init-image
VAE encode through a cached jitted program) is identical across the
Kandinsky families — one implementation here so fixes land once.
"""

from __future__ import annotations

import numpy as np


def clamp_strength(value) -> float:
    """Strength outside [0,1] would index the schedule negatively."""
    return min(max(float(value), 0.0), 1.0)


def img2img_t_start(steps: int, strength: float) -> int:
    """Scan start index for an img2img job at this strength."""
    return min(max(int(steps * (1.0 - strength)), 0), steps - 1)


def encode_init_image(pipe, vae_params, image, width: int, height: int,
                      n_images: int, lh: int, lw: int, channels: int):
    """PIL init image -> [n_images, lh, lw, channels] float32 latents.

    Encodes through ONE cached jitted program per pipeline instance —
    an op-by-op `vae.apply` on the job hot path costs a host->device
    round trip per op (round-1 measurement: >50% of job time host-side,
    stable_diffusion.py's `_vae_encode_program` rationale).
    """
    import jax
    import jax.numpy as jnp
    from PIL import Image

    program = getattr(pipe, "_vae_encode_program", None)
    if program is None:
        program = jax.jit(
            lambda p, px: pipe.vae.apply(
                {"params": p}, px, method=pipe.vae.encode
            ).astype(jnp.float32)
        )
        pipe._vae_encode_program = program

    arr = (
        np.asarray(
            image.convert("RGB").resize((width, height), Image.LANCZOS),
            np.float32,
        )
        / 127.5
        - 1.0
    )
    latents = program(vae_params, jnp.asarray(arr)[None].astype(pipe.dtype))
    return jnp.broadcast_to(latents, (n_images, lh, lw, channels))
