"""Kandinsky 2.x two-stage cascade: diffusion prior -> image-embed decoder.

Reference behavior replaced: swarm/diffusion/pipeline_steps.py:7-38 runs
KandinskyV22PriorPipeline per job (fresh `from_pretrained`) to turn the
prompt into CLIP image embeddings — including the split-embeds mode where
`pipeline_prior_type`/`prior_timesteps` ride the job parameters — then the
main pipeline consumes `image_embeds`/`negative_image_embeds` kwargs.

TPU redesign: both stages are resident jitted programs. The prior denoises
in embedding space with a `lax.scan` (DDPM, sample-prediction, CFG as a
batch of 2) through a PriorTransformer-parity graph (models/prior.py); the
decoder runs the TRUE K2.2 architecture — the SimpleCrossAttn/scale-shift
UNet conditioned only on the image embedding (models/unet_kandinsky.py) and
the MoVQ spatially-normalized codec (models/movq.py). Real checkpoints
convert mechanically (models/conversion.py convert_kandinsky_unet /
convert_movq / convert_prior); known approximation: the UNet's learned
variance channels are dropped (fixed-variance DDPM step instead of
learned_range — a sampling choice, not a weight-geometry gap).
"""

from __future__ import annotations

import logging
import threading
import time
import zlib
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np
from PIL import Image

from ..models import configs as cfgs
from ..models.clip import CLIPTextEncoder
from ..models.movq import TINY_MOVQ, MoVQ, MoVQConfig
from ..models.prior import TINY_PRIOR, DiffusionPrior, PriorConfig
from ..models.tokenizer import load_tokenizer
from ..models.unet_kandinsky import TINY_K22_UNET, K22UNet, K22UNetConfig
from ..parallel.mesh import make_mesh, replicated
from ..registry import register_family
from ..schedulers import get_scheduler
from ..weights import require_weights_present

logger = logging.getLogger(__name__)

_NO_CONVERSION_HINT = (
    "Kandinsky weights were not found under the model root; run "
    "`chiaswarm-tpu-init --download` to fetch and convert them."
)


def _is_tiny(name: str) -> bool:
    return "tiny" in name.lower() or name.startswith("test/")


def _prior_configs(model_name: str):
    """(prior_cfg, clip_cfg)."""
    if _is_tiny(model_name):
        return TINY_PRIOR, cfgs.TINY_CLIP_2
    if "2-1" in model_name or "2_1" in model_name:
        # Kandinsky 2.1: CLIP ViT-L/14 text tower, 768-wide joint space
        from ..models.clip import CLIPTextConfig

        return (
            PriorConfig(embed_dim=768, text_dim=768, num_heads=32,
                        num_layers=20),
            CLIPTextConfig(hidden_size=768, num_layers=12, num_heads=12,
                           hidden_act="quick_gelu", projection_dim=768),
        )
    # Kandinsky 2.2 rides the OpenCLIP ViT-bigG text tower (same one SDXL
    # uses as encoder 2) and a 1280-wide embedding space
    return PriorConfig(), cfgs.SDXL_CLIP_2


def _decoder_configs(model_name: str):
    """(unet_cfg, movq_cfg, embed_dim, default_size)."""
    if _is_tiny(model_name):
        return TINY_K22_UNET, TINY_MOVQ, TINY_PRIOR.embed_dim, 64
    return K22UNetConfig(), MoVQConfig(), PriorConfig().embed_dim, 512


def _model_dir(model_name: str):
    from ..weights import model_dir_for

    return model_dir_for(model_name)


def convert_decoder_checkpoint(model_dir):
    """One K2.2 decoder-repo conversion recipe -> (unet_cfg, unet, movq) —
    shared by serving (_load_converted_decoder) and initialize --check so
    a green check means EXACTLY what the worker will load. The UNet
    geometry comes from the checkpoint itself (conversion.py
    infer_k22_unet_config) — including the ControlNet variant's extra hint
    channels, which are baked into its conv_in."""
    import json

    from ..models.conversion import (
        convert_kandinsky_unet,
        convert_movq,
        load_torch_state_dict,
    )

    cfg_json = {}
    p = model_dir / "unet" / "config.json"
    if p.is_file():
        cfg_json = json.loads(p.read_text())
    ucfg, unet = convert_kandinsky_unet(
        load_torch_state_dict(model_dir, "unet"), cfg_json
    )
    movq = convert_movq(load_torch_state_dict(model_dir, "movq"))
    return ucfg, unet, movq


def _load_converted_decoder(model_name: str):
    """-> {"unet", "movq", "unet_cfg"} or None when no checkpoint is local.
    A present-but-unconvertible checkpoint (K2.1 layout, partial download,
    corrupt config) fails as MissingWeightsError, not a raw traceback."""
    if _is_tiny(model_name):
        return None
    d = _model_dir(model_name)
    if d is None:
        return None
    from ..weights import MissingWeightsError

    try:
        ucfg, unet, movq = convert_decoder_checkpoint(d)
    except (FileNotFoundError, OSError):
        return None
    except Exception as e:
        raise MissingWeightsError(
            f"checkpoint under {d} could not be converted for "
            f"'{model_name}': {e}"
        ) from e
    return {"unet": unet, "movq": movq, "unet_cfg": ucfg}


def _load_converted_prior(model_name: str):
    """-> {"prior", "text", "clip_stats", "model_dir"} or None. All-or-
    nothing: a prior without its text tower would embed garbage."""
    if _is_tiny(model_name):
        return None
    d = _model_dir(model_name)
    if d is None:
        return None
    from ..weights import MissingWeightsError

    try:
        from ..models.conversion import (
            convert_clip,
            convert_prior,
            load_torch_state_dict,
        )

        prior_params, stats = convert_prior(load_torch_state_dict(d, "prior"))
        text_params = convert_clip(load_torch_state_dict(d, "text_encoder"))
    except (FileNotFoundError, OSError):
        return None
    except Exception as e:
        raise MissingWeightsError(
            f"checkpoint under {d} could not be converted for "
            f"'{model_name}': {e}"
        ) from e
    # geometry overrides from the shipped config.json (2.1 and 2.2 priors
    # share the 20L/2048 transformer but differ in embedding width)
    prior_cfg_json = {}
    p = d / "prior" / "config.json"
    if p.is_file():
        import json

        prior_cfg_json = json.loads(p.read_text())
    return {
        "prior": prior_params,
        "text": text_params,
        "clip_stats": stats,
        "model_dir": d,
        "config_json": prior_cfg_json,
    }


def _checked_converted(module, example_args, converted, prefix, rng):
    from ..models.conversion import checked_converted

    return checked_converted(module, example_args, converted, prefix, rng)


def prior_config_with_overrides(cfg, config_json: dict | None):
    """Geometry overrides from prior/config.json — the ONE mapping shared
    by the serving pipeline and `initialize --check`."""
    import dataclasses

    cj = config_json or {}
    return dataclasses.replace(
        cfg,
        embed_dim=int(cj.get("embedding_dim", cfg.embed_dim)),
        num_heads=int(cj.get("num_attention_heads", cfg.num_heads)),
        head_dim=int(cj.get("attention_head_dim", cfg.head_dim)),
        num_layers=int(cj.get("num_layers", cfg.num_layers)),
    )


def _prior_name_for(decoder_name: str) -> str:
    if _is_tiny(decoder_name):
        return "test/tiny-kandinsky-prior"
    if "decoder" in decoder_name:
        return decoder_name.replace("decoder", "prior")
    if "2-1" in decoder_name or "2_1" in decoder_name:
        return "kandinsky-community/kandinsky-2-1-prior"
    return "kandinsky-community/kandinsky-2-2-prior"


class KandinskyPriorPipeline:
    """Resident prior stage; produces (image_embeds, negative_image_embeds).

    Not a standalone image job — the hive schedules the decoder and the
    prior runs as its prepipeline (reference pipeline_steps.py semantics).
    """

    def __init__(self, model_name: str, chipset=None,
                 allow_random_init: bool = False):
        self.model_name = model_name
        self.chipset = chipset
        self.config, clip_cfg = _prior_configs(model_name)
        converted = _load_converted_prior(model_name)
        if converted and converted.get("config_json"):
            self.config = prior_config_with_overrides(
                self.config, converted["config_json"]
            )
        if converted is None:
            require_weights_present(
                model_name, None, allow_random_init,
                component="Kandinsky prior", hint=_NO_CONVERSION_HINT,
            )
        on_tpu = jax.default_backend() == "tpu"
        self.dtype = jnp.bfloat16 if on_tpu else jnp.float32
        self.prior = DiffusionPrior(self.config, dtype=self.dtype)
        self.text_encoder = CLIPTextEncoder(clip_cfg, dtype=self.dtype)
        self.tokenizer = load_tokenizer(
            converted["model_dir"] if converted else None,
            vocab_size=clip_cfg.vocab_size,
        )
        # PriorTransformer whitens the embedding space; predictions un-whiten
        # through the checkpoint's clip_mean/std before the decoder sees them
        self.clip_stats = converted["clip_stats"] if converted else None
        # diffusers' negative embeds are the CLIP VISION embedding of a zero
        # image; initialize precomputes it offline (zero_image_embed.npy) so
        # the vision tower never has to be resident here
        self._zero_embed = None
        if converted is not None:
            p = converted["model_dir"] / "zero_image_embed.npy"
            if p.is_file():
                self._zero_embed = np.load(p).reshape(-1)
        self.mesh = (
            chipset.mesh() if chipset is not None else make_mesh(jax.devices()[:1])
        )

        rng = jax.random.key(zlib.crc32(model_name.encode()))
        k1, k2 = jax.random.split(rng)
        cfg = self.config
        prior_args = (
            jnp.zeros((1, cfg.embed_dim)),
            jnp.zeros((1,)),
            jnp.zeros((1, cfg.text_seq, cfg.text_dim)),
            jnp.zeros((1, cfg.text_dim)),
        )
        text_args = (jnp.zeros((1, 77), jnp.int32),)
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            if converted is not None:
                # eval_shape only: a full init would run the 20-layer
                # transformer just to produce a tree we throw away
                prior_params = _checked_converted(
                    self.prior, prior_args, converted["prior"], "prior", k1
                )
                text_params = _checked_converted(
                    self.text_encoder, text_args, converted["text"], "text", k2
                )
                logger.info("loaded converted prior weights for %s", model_name)
            else:
                prior_params = self.prior.init(k1, *prior_args)["params"]
                text_params = self.text_encoder.init(k2, *text_args)["params"]
        cast = lambda x: jnp.asarray(x, self.dtype)
        self.params = jax.device_put(
            jax.tree_util.tree_map(
                cast, {"prior": prior_params, "text": text_params}
            ),
            replicated(self.mesh),
        )
        # insertion-ordered so the program_cache_max bound below can evict
        # least-recently-used first (SW007; same knob as the SD family)
        self._programs: OrderedDict = OrderedDict()
        self._lock = threading.Lock()

    def release(self):
        self.params = None
        self._programs.clear()

    def run(self, *args, **kwargs):
        """Prior-typed jobs are not standalone image jobs — job-level error
        (the hive should schedule the decoder; the prior runs inside it)."""
        raise Exception(
            f"{self.model_name} is a prior prepipeline stage; schedule the "
            f"Kandinsky decoder model instead (the prior runs automatically)."
        )

    def _program(self, steps: int, guided: bool):
        key = (steps, guided)
        with self._lock:
            if key in self._programs:
                self._programs.move_to_end(key)
                return self._programs[key]
        scheduler = get_scheduler("DDPMScheduler", prediction_type="sample")
        schedule = scheduler.schedule(steps)
        prior = self.prior
        cfg = self.config

        def run(params, rng, text_hiddens, text_embed, text_mask, guidance):
            """guided: rows [uncond | cond] stacked on batch (CFG 2N);
            unguided: plain N rows (the zero-prompt negative pass)."""
            rows = 2 if guided else 1
            b = text_embed.shape[0] // rows
            latents = jax.random.normal(rng, (b, cfg.embed_dim), jnp.float32)
            latents = latents * jnp.asarray(
                schedule.init_noise_sigma, jnp.float32
            )
            state = scheduler.init_state(latents.shape, latents.dtype)

            def body(carry, i):
                latents, state = carry
                t = jnp.asarray(schedule.timesteps)[i]
                model_in = (
                    jnp.concatenate([latents, latents], axis=0)
                    if guided
                    else latents
                )
                pred = prior.apply(
                    {"params": params["prior"]},
                    model_in.astype(prior.dtype),
                    jnp.broadcast_to(t, (rows * b,)),
                    text_hiddens,
                    text_embed,
                    attention_mask=text_mask,
                ).astype(jnp.float32)
                if guided:
                    pred_u, pred_c = jnp.split(pred, 2, axis=0)
                    pred = pred_u + guidance * (pred_c - pred_u)
                noise = jax.random.normal(
                    jax.random.fold_in(rng, i), latents.shape, jnp.float32
                )
                state, latents = scheduler.step(
                    schedule, state, i, latents, pred, noise
                )
                return (latents, state), ()

            (latents, _), _ = jax.lax.scan(
                body, (latents, state), jnp.arange(steps)
            )
            return latents

        program = jax.jit(run)
        with self._lock:
            self._programs[key] = program
            from .common import PROGRAM_EVICTED, program_cache_cap

            cap = program_cache_cap()
            while cap and len(self._programs) > cap:
                self._programs.popitem(last=False)
                PROGRAM_EVICTED.inc(kind="program")
        return program

    def generate(self, prompt: str, negative_prompt: str = "",
                 num_images: int = 1, steps: int = 25,
                 guidance_scale: float = 4.0, rng=None):
        """-> (image_embeds [N, E], negative_image_embeds [N, E])."""
        params = self.params
        if params is None:
            raise Exception(f"prior {self.model_name} was evicted; resubmit")
        if rng is None:
            rng = jax.random.key(0)
        texts = [negative_prompt] * num_images + [prompt] * num_images
        ids = np.asarray(self.tokenizer(texts))
        out = self.text_encoder.apply(
            {"params": params["text"]}, jnp.asarray(ids)
        )
        embeds = self._program(steps, guided=True)(
            params, rng, out["hidden_states"], out["pooled"],
            jnp.asarray(self._text_mask(ids)), jnp.float32(guidance_scale),
        )
        embeds = self._unwhiten(embeds)
        if self._zero_embed is not None:
            # diffusers parity: negative = CLIP vision embedding of a zero
            # image (precomputed at conversion)
            negative = jnp.broadcast_to(
                jnp.asarray(self._zero_embed, jnp.float32)[None],
                (num_images, embeds.shape[-1]),
            )
            return embeds, negative
        # fallback: zero-prompt prior run — a plain unguided N-row pass
        zero_ids = np.asarray(self.tokenizer([""] * num_images))
        zero_out = self.text_encoder.apply(
            {"params": params["text"]}, jnp.asarray(zero_ids)
        )
        negative = self._program(steps, guided=False)(
            params, jax.random.fold_in(rng, 1), zero_out["hidden_states"],
            zero_out["pooled"], jnp.asarray(self._text_mask(zero_ids)),
            jnp.float32(1.0),
        )
        return embeds, self._unwhiten(negative)

    def _unwhiten(self, embeds):
        """PriorTransformer.post_process_latents: predictions live in the
        whitened embedding space; the decoder consumes raw CLIP space."""
        if self.clip_stats is None:
            return embeds
        return embeds * jnp.asarray(
            self.clip_stats["std"], jnp.float32
        ) + jnp.asarray(self.clip_stats["mean"], jnp.float32)

    def _text_mask(self, ids: np.ndarray) -> np.ndarray:
        """Keep-mask over the padded token grid: positions up to and
        including the first EOS are real (both tokenizers pad with EOS) —
        the mask PriorTransformer expects alongside its causal triangle."""
        eos = getattr(self.tokenizer, "eos", None)
        if eos is None:
            return np.ones_like(ids, np.float32)
        first_eos = np.argmax(ids[:, 1:] == eos, axis=1) + 1
        pos = np.arange(ids.shape[1])[None]
        return (pos <= first_eos[:, None]).astype(np.float32)


class KandinskyPipeline:
    """Resident decoder stage serving KandinskyV22Pipeline wire names; runs
    the prior prepipeline internally when a job arrives with a prompt."""

    def __init__(self, model_name: str, chipset=None,
                 allow_random_init: bool = False):
        self.model_name = model_name
        self.chipset = chipset
        unet_cfg, movq_cfg, self.embed_dim, self.default_size = _decoder_configs(
            model_name
        )
        # controlnet-depth checkpoints condition on a 3-channel depth hint
        # concatenated onto the latent input (reference job_arguments.py:387
        # passes `hint` instead of `image` for this model family)
        self.controlnet = "controlnet" in model_name.lower()
        if self.controlnet:
            import dataclasses

            unet_cfg = dataclasses.replace(
                unet_cfg, in_channels=unet_cfg.in_channels + 3
            )
        converted = _load_converted_decoder(model_name)
        if converted is None:
            require_weights_present(
                model_name, None, allow_random_init,
                component="Kandinsky decoder", hint=_NO_CONVERSION_HINT,
            )
        else:
            unet_cfg = converted["unet_cfg"]  # token count from checkpoint
        self.unet_cfg = unet_cfg
        # Kandinsky 2.1 checkpoints condition on MCLIP text as well as the
        # prior image embedding (conditioning="text_image", detected from
        # the checkpoint by infer_k22_unet_config)
        self.text_image = unet_cfg.conditioning == "text_image"
        self.text_encoder = None
        self.latent_channels = movq_cfg.latent_channels
        on_tpu = jax.default_backend() == "tpu"
        self.dtype = jnp.bfloat16 if on_tpu else jnp.float32
        if self.text_image:
            self._init_mclip(_model_dir(model_name))
        self.unet = K22UNet(unet_cfg, dtype=self.dtype)
        self.vae = MoVQ(movq_cfg, dtype=self.dtype)
        self.latent_factor = 2 ** (len(movq_cfg.block_out_channels) - 1)
        self.mesh = (
            chipset.mesh() if chipset is not None else make_mesh(jax.devices()[:1])
        )

        seed = zlib.crc32(model_name.encode())
        k1, k2, k3 = jax.random.split(jax.random.key(seed), 3)
        n_down = len(unet_cfg.block_out_channels) - 1
        hw = 2 ** max(n_down, 2)
        if self.text_image:
            unet_cond = {
                "text_states": jnp.zeros((1, 8, unet_cfg.encoder_hid_dim)),
                "text_embeds": jnp.zeros((1, unet_cfg.cross_attention_dim)),
                "image_embeds": jnp.zeros((1, unet_cfg.image_embed_dim)),
            }
        else:
            unet_cond = jnp.zeros((1, unet_cfg.encoder_hid_dim))
        unet_args = (
            jnp.zeros((1, hw, hw, unet_cfg.in_channels)),
            jnp.zeros((1,)),
            unet_cond,
        )
        movq_args = (
            jnp.zeros(
                (1, hw * self.latent_factor, hw * self.latent_factor, 3)
            ),
        )
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            if converted is not None:
                # eval_shape only: a full init would run the 1B-param
                # UNet+MoVQ forward just to produce a throwaway tree
                unet_params = _checked_converted(
                    self.unet, unet_args, converted["unet"], "unet", k1
                )
                movq_params = _checked_converted(
                    self.vae, movq_args, converted["movq"], "movq", k2
                )
                logger.info(
                    "loaded converted K2.%s weights for %s",
                    "1" if self.text_image else "2", model_name,
                )
            else:
                unet_params = self.unet.init(k1, *unet_args)["params"]
                movq_params = self.vae.init(k2, *movq_args)["params"]
            tree = {"unet": unet_params, "vae": movq_params}
            if self.text_image:
                from ..models.conversion import (
                    convert_mclip,
                    load_torch_state_dict,
                )

                tree["text"] = _checked_converted(
                    self.text_encoder, (jnp.zeros((1, 8), jnp.int32),),
                    convert_mclip(
                        load_torch_state_dict(
                            _model_dir(model_name), "text_encoder"
                        )
                    ),
                    "mclip", k3,
                )
        cast = lambda x: jnp.asarray(x, self.dtype)
        self.params = jax.device_put(
            jax.tree_util.tree_map(cast, tree), replicated(self.mesh)
        )
        # insertion-ordered so the program_cache_max bound below can evict
        # least-recently-used first (SW007; same knob as the SD family)
        self._programs: OrderedDict = OrderedDict()
        self._lock = threading.Lock()

    @staticmethod
    def _mclip_config_from_dir(model_dir):
        """MCLIP trunk geometry from text_encoder/config.json (MCLIPConfig
        nests the trunk dims under transformerDimensions/numDims; plain
        XLM-R config keys cover synthetic checkpoints)."""
        import json

        from ..models.clap import ClapTextConfig
        from ..models.mclip import MCLIP_XLMR_LARGE

        cfg = MCLIP_XLMR_LARGE
        p = model_dir / "text_encoder" / "config.json"
        if p.is_file():
            cj = json.loads(p.read_text())
            cfg = ClapTextConfig(
                vocab_size=int(cj.get("vocab_size", cfg.vocab_size)),
                hidden_size=int(
                    cj.get("transformerDimensions",
                           cj.get("hidden_size", cfg.hidden_size))
                ),
                num_layers=int(cj.get("num_hidden_layers", cfg.num_layers)),
                num_heads=int(
                    cj.get("num_attention_heads", cfg.num_heads)
                ),
                intermediate_size=int(
                    cj.get("intermediate_size", cfg.intermediate_size)
                ),
                max_positions=int(
                    cj.get("max_position_embeddings", cfg.max_positions)
                ),
                projection_dim=int(cj.get("numDims", cfg.projection_dim)),
                layer_norm_eps=float(
                    cj.get("layer_norm_eps", cfg.layer_norm_eps)
                ),
            )
        return cfg

    def _init_mclip(self, model_dir):
        """K2.1 text tower: MCLIP (XLM-R + LinearTransformation) with its
        fast tokenizer. Geometry from text_encoder/config.json."""
        from ..models.mclip import MCLIPTextEncoder
        from ..weights import MissingWeightsError

        if model_dir is None:
            raise MissingWeightsError(
                f"{self.model_name}: text_image checkpoints need the MCLIP "
                "text tower on disk"
            )
        cfg = self._mclip_config_from_dir(model_dir)
        self.mclip_cfg = cfg
        self.text_encoder = MCLIPTextEncoder(cfg, dtype=self.dtype)
        # the 24-layer XLM-R tower runs per job at a fixed (2, 77) shape:
        # one cached jitted program, like every other resident model here
        self._text_program = jax.jit(
            lambda p, ids, mask: self.text_encoder.apply(
                {"params": p}, ids, mask
            )
        )
        tok_dir = model_dir / "tokenizer"
        try:
            from transformers import AutoTokenizer

            self.mclip_tokenizer = AutoTokenizer.from_pretrained(str(tok_dir))
        except Exception as e:
            raise MissingWeightsError(
                f"{self.model_name}: MCLIP tokenizer failed to load from "
                f"{tok_dir} ({e}). XLM-R needs tokenizer.json (fast "
                "tokenizer) since sentencepiece is not installed."
            ) from e

    def release(self):
        self.params = None
        self._programs.clear()

    def _program(self, key: tuple):
        with self._lock:
            if key in self._programs:
                self._programs.move_to_end(key)
                return self._programs[key]
        mode, lh, lw, batch, steps, sched_name, t_start = key
        scheduler = get_scheduler(sched_name)
        schedule = scheduler.schedule(steps)
        loop_start, loop_end = scheduler.loop_bounds(schedule, steps, t_start)
        unet = self.unet
        vae = self.vae
        latent_c = self.latent_channels
        controlnet = self.controlnet

        def run(params, rng, embeds, neg_embeds, guidance, hint,
                image_latents):
            """hint [B, lh, lw, 3] depth conditioning (zeros when the model
            is not a controlnet variant — traced away, never concatenated);
            img2img starts from the init image's latents noised to the
            strength level (reference wire: kandinsky img2img jobs,
            swarm/test.py:100-113)."""
            # CFG rows carry [negative | positive] conditioning; `embeds`
            # is a raw image embedding (2.2) or the text_image dict (2.1)
            # — tree_map handles both
            embeds2 = jax.tree_util.tree_map(
                lambda n, p: jnp.concatenate([n, p], axis=0).astype(
                    self.dtype
                ),
                neg_embeds, embeds,
            )
            noise0 = jax.random.normal(
                rng, (batch, lh, lw, latent_c), jnp.float32
            )
            if mode == "img2img":
                latents = scheduler.add_noise(
                    schedule, image_latents.astype(jnp.float32), noise0,
                    loop_start,
                )
            else:
                latents = noise0 * jnp.asarray(
                    schedule.init_noise_sigma, jnp.float32
                )
            state = scheduler.init_state(latents.shape, latents.dtype)

            def body(carry, i):
                latents, state = carry
                inp = scheduler.scale_model_input(schedule, latents, i)
                if controlnet:
                    # depth hint concatenates onto the latent input channels
                    inp = jnp.concatenate(
                        [inp, hint.astype(inp.dtype)], axis=-1
                    )
                model_in = jnp.concatenate([inp, inp], axis=0).astype(self.dtype)
                t = jnp.asarray(schedule.timesteps)[i]
                out = unet.apply(
                    {"params": params["unet"]},
                    model_in,
                    jnp.broadcast_to(t, (2 * batch,)),
                    embeds2,
                ).astype(jnp.float32)
                # learned-variance checkpoints emit 2x channels; the DDPM
                # step here is fixed-variance, so keep the noise half
                out = out[..., :latent_c]
                out_u, out_c = jnp.split(out, 2, axis=0)
                out = out_u + guidance * (out_c - out_u)
                noise = jax.random.normal(
                    jax.random.fold_in(rng, i), latents.shape, jnp.float32
                )
                state, latents = scheduler.step(
                    schedule, state, i, latents, out, noise
                )
                return (latents, state), ()

            (latents, _), _ = jax.lax.scan(
                body, (latents, state), jnp.arange(loop_start, loop_end)
            )
            pixels = vae.apply(
                {"params": params["vae"]}, latents.astype(self.dtype),
                method=vae.decode,
            )
            return (
                (pixels.astype(jnp.float32) + 1.0) * 127.5
            ).clip(0.0, 255.0).round().astype(jnp.uint8)

        program = jax.jit(run)
        with self._lock:
            self._programs[key] = program
            from .common import PROGRAM_EVICTED, program_cache_cap

            cap = program_cache_cap()
            while cap and len(self._programs) > cap:
                self._programs.popitem(last=False)
                PROGRAM_EVICTED.inc(kind="program")
        return program

    def run(self, prompt="", negative_prompt="",
            pipeline_type="KandinskyV22Pipeline", **kwargs):
        params = self.params
        if params is None:
            raise Exception(
                f"pipeline {self.model_name} was evicted; resubmit the job"
            )
        hint = kwargs.pop("hint", None)
        if hint is None and (self.controlnet or "Controlnet" in pipeline_type):
            # a Controlnet-typed job on a non-controlnet checkpoint (or a
            # controlnet checkpoint with no control image) must not run
            # silently unconditioned
            raise Exception(
                "Kandinsky ControlNet requires a depth hint: schedule "
                "kandinsky-community/kandinsky-2-2-controlnet-depth with a "
                "control image (the depth estimator builds the hint)."
            )
        if hint is not None and not self.controlnet:
            # silently ignoring the depth hint would return an unconditioned
            # image as a "successful" controlnet job
            raise Exception(
                f"{self.model_name} is not a ControlNet checkpoint; the "
                f"depth hint cannot condition it (use "
                f"kandinsky-community/kandinsky-2-2-controlnet-depth)."
            )
        timings: dict[str, float] = {}
        steps = int(kwargs.pop("num_inference_steps", 30))
        guidance_scale = float(kwargs.pop("guidance_scale", 4.0))
        n_images = int(kwargs.pop("num_images_per_prompt", 1))
        scheduler_type = kwargs.pop("scheduler_type", "DDPMScheduler")
        prior_steps = int(kwargs.pop("prior_timesteps", None) or 25)
        kwargs.pop("pipeline_prior_type", None)
        rng = kwargs.pop("rng", None)
        if rng is None:
            rng = jax.random.key(0)
        chipset = kwargs.pop("chipset", None)
        image = kwargs.pop("image", None)
        kwargs.pop("control_image", None)  # the hint IS the conditioning
        from .common import clamp_strength, img2img_t_start

        strength = clamp_strength(kwargs.pop("strength", 0.75))

        if image is not None:
            width, height = image.size
            kwargs.pop("height", None)
            kwargs.pop("width", None)
        else:
            height = int(kwargs.pop("height", None) or self.default_size)
            width = int(kwargs.pop("width", None) or self.default_size)
        height, width = (max(64, (d // 64) * 64) for d in (height, width))
        lh, lw = height // self.latent_factor, width // self.latent_factor

        mode = "img2img" if image is not None else "txt2img"
        t_start = img2img_t_start(steps, strength) if mode == "img2img" else 0

        embeds = kwargs.pop("image_embeds", None)
        neg_embeds = kwargs.pop("negative_image_embeds", None)
        rng, prior_rng, dec_rng = jax.random.split(rng, 3)
        if embeds is None:
            # prepipeline stage (reference pipeline_steps.py:7-38)
            from ..registry import get_pipeline

            t0 = time.perf_counter()
            prior = get_pipeline(
                _prior_name_for(self.model_name),
                pipeline_type="KandinskyV22PriorPipeline",
                chipset=chipset,
            )
            embeds, neg_embeds = prior.generate(
                prompt, negative_prompt, num_images=n_images,
                steps=prior_steps, rng=prior_rng,
            )
            timings["prior_s"] = round(time.perf_counter() - t0, 3)
        embeds = jnp.asarray(embeds)
        if neg_embeds is None:
            neg_embeds = jnp.zeros_like(embeds)
        neg_embeds = jnp.asarray(neg_embeds)
        # split-embeds jobs deliver the batch via the embeds themselves
        n_images = int(embeds.shape[0])

        if self.text_image:
            # K2.1: MCLIP text conditioning rides alongside the prior's
            # image embedding (diffusers KandinskyPipeline._encode_prompt)
            tok = self.mclip_tokenizer(
                [negative_prompt or "", prompt], padding="max_length",
                truncation=True, max_length=77, return_tensors="np",
            )
            enc = self._text_program(
                params["text"],
                jnp.asarray(tok["input_ids"], jnp.int32),
                jnp.asarray(tok["attention_mask"], jnp.float32),
            )
            states = jnp.asarray(enc["hidden_states"], jnp.float32)
            pooled = jnp.asarray(enc["pooled_proj"], jnp.float32)
            tile = lambda x: jnp.repeat(x, n_images, axis=0)
            embeds = {
                "text_states": tile(states[1:2]),
                "text_embeds": tile(pooled[1:2]),
                "image_embeds": embeds,
            }
            neg_embeds = {
                "text_states": tile(states[0:1]),
                "text_embeds": tile(pooled[0:1]),
                "image_embeds": neg_embeds,
            }

        image_latents = jnp.zeros((1, 1, 1, 1), jnp.float32)
        if image is not None:
            from .common import encode_init_image

            image_latents = encode_init_image(
                self, params["vae"], image, width, height, n_images,
                lh, lw, self.latent_channels,
            )

        hint_lat = jnp.zeros((1, 1, 1, 3), jnp.float32)
        if self.controlnet:
            # HWC float hint (pre_processors/depth_estimator.make_hint) ->
            # latent-resolution conditioning planes
            hint_arr = jnp.asarray(np.asarray(hint, np.float32))
            if hint_arr.ndim == 3:
                hint_arr = hint_arr[None]
            hint_lat = jnp.broadcast_to(
                jax.image.resize(
                    hint_arr, (hint_arr.shape[0], lh, lw, 3), "bilinear"
                ),
                (n_images, lh, lw, 3),
            )

        key = (mode, lh, lw, n_images, steps, scheduler_type, t_start)
        program = self._program(key)
        t0 = time.perf_counter()
        pixels = jax.block_until_ready(
            program(params, dec_rng, embeds, neg_embeds,
                    jnp.float32(guidance_scale), hint_lat, image_latents)
        )
        timings["denoise_decode_s"] = round(time.perf_counter() - t0, 3)

        images = [Image.fromarray(img) for img in np.asarray(pixels)]
        pipeline_config = {
            "model": self.model_name,
            "pipeline": pipeline_type,
            "scheduler": scheduler_type,
            "mode": "controlnet" if self.controlnet else mode,
            "steps": steps,
            "size": [width, height],
            "guidance_scale": guidance_scale,
            "timings": timings,
        }
        return images, pipeline_config


@register_family("kandinsky")
def _build_kandinsky(model_name, chipset, **variant):
    return KandinskyPipeline(model_name, chipset, **variant)


@register_family("kandinsky_prior")
def _build_kandinsky_prior(model_name, chipset, **variant):
    return KandinskyPriorPipeline(model_name, chipset, **variant)
