"""Stable Cascade (Wuerstchen v3) two-stage cascade: prior (stage C) ->
latent decoder (stage B) -> pixel decode (stage A analog).

Reference behavior replaced: swarm/diffusion/pipeline_steps.py:70-90 chains
`StableCascadeDecoderPipeline.from_pretrained` after a prior main pipeline,
feeding `image_embeddings` with `num_inference_steps=10, guidance_scale=0`;
the hive schedules the prior as the main pipeline and rides a `decoder`
parameter dict (model_name / pipeline_type / variant).

TPU redesign: both stages are resident jitted programs, mirroring the
Kandinsky cascade in this package. Stage C denoises a ~42x-compressed
16-channel spatial latent with a text-conditioned UNet under one `lax.scan`
(CFG as a batch of 2); stage B denoises the 4x-compressed VQ latent space
conditioned on the flattened stage-C latent as cross-attention tokens —
guidance 0 per the reference, so the program is a single-row scan with no
CFG doubling. Stage A is served by this package's AutoencoderKL at 4x
(VQGAN-analog; real-weight conversion for this family is not wired yet, so
non-test model names fail loudly per weights.py).
"""

from __future__ import annotations

import logging
import math
import threading
import time
import zlib

import jax
import jax.numpy as jnp
import numpy as np
from PIL import Image

from ..models import configs as cfgs
from ..models.clip import CLIPTextEncoder
from ..models.tokenizer import load_tokenizer
from ..models.unet2d import UNet2DConditionModel, UNet2DConfig
from ..models.vae import AutoencoderKL, VAEConfig
from ..parallel.mesh import make_mesh, replicated
from ..registry import register_family
from ..schedulers import get_scheduler
from ..weights import is_test_model, require_weights_present

logger = logging.getLogger(__name__)

_NO_CONVERSION_HINT = (
    "This worker cannot serve real Stable Cascade weights yet; only "
    "test/tiny cascade models are available."
)

# stage-C latent channels (the "effnet" space both stages agree on)
PRIOR_CHANNELS = 16


_is_tiny = is_test_model


# stage-C prior UNet (StableCascadeUNet stage-C analog: text-conditioned,
# operates on the 16ch compressed latent; real geometry approximated)
CASCADE_PRIOR_UNET = UNet2DConfig(
    in_channels=PRIOR_CHANNELS,
    out_channels=PRIOR_CHANNELS,
    block_out_channels=(1024, 1536),
    transformer_layers=(4, 4),
    mid_transformer_layers=4,
    layers_per_block=2,
    num_attention_heads=(16, 24),
    cross_attention_dim=1280,
)
TINY_PRIOR_UNET = UNet2DConfig(
    in_channels=PRIOR_CHANNELS,
    out_channels=PRIOR_CHANNELS,
    block_out_channels=(32, 64),
    transformer_layers=(1, 1),
    mid_transformer_layers=1,
    layers_per_block=1,
    num_attention_heads=4,
    cross_attention_dim=32,
)

# stage-B decoder UNet: denoises the 4ch VQ latent, cross-attends on the
# flattened stage-C latent tokens
CASCADE_DECODER_UNET = UNet2DConfig(
    block_out_channels=(320, 640, 1280),
    transformer_layers=(0, 2, 4),
    mid_transformer_layers=4,
    num_attention_heads=(5, 10, 20),
    cross_attention_dim=1280,
)
# stage-A analog: 4x pixel decode (VQGAN compression factor)
CASCADE_VQ_VAE = VAEConfig(block_out_channels=(128, 256, 512))
TINY_VQ_VAE = VAEConfig(block_out_channels=(32, 32), layers_per_block=1)


def _prior_configs(model_name: str):
    """(unet_cfg, clip_cfg, compression, default_size)."""
    if _is_tiny(model_name):
        return TINY_PRIOR_UNET, cfgs.TINY_CLIP_2, 8, 64
    # Stable Cascade conditions on the OpenCLIP ViT-bigG text tower; the
    # stage-C latent is ~42.67x compressed (1024^2 -> 24x24, factor 1024/24)
    return CASCADE_PRIOR_UNET, cfgs.SDXL_CLIP_2, 1024 / 24, 1024


def _decoder_configs(model_name: str):
    """(unet_cfg, vae_cfg, default_size)."""
    if _is_tiny(model_name):
        return cfgs.TINY_UNET, TINY_VQ_VAE, 64
    return CASCADE_DECODER_UNET, CASCADE_VQ_VAE, 1024


def _decoder_name_for(prior_name: str) -> str:
    if _is_tiny(prior_name):
        return "test/tiny-cascade"
    if "prior" in prior_name:
        return prior_name.replace("-prior", "")
    return "stabilityai/stable-cascade"


def _prior_name_for(decoder_name: str) -> str:
    if _is_tiny(decoder_name):
        return "test/tiny-cascade-prior"
    return decoder_name + "-prior"


class CascadePriorPipeline:
    """Resident stage-C prior; produces `image_embeddings` (the compressed
    spatial latent). Unlike the Kandinsky prior, the hive schedules THIS as
    the main pipeline (reference diffusion_func.py:151-161 takes
    `.image_embeddings` from the main pipeline output), so `run()` chains
    into the decoder named by the job's `decoder` parameter.
    """

    def __init__(self, model_name: str, chipset=None,
                 allow_random_init: bool = False):
        require_weights_present(
            model_name, None, allow_random_init, component="Cascade prior",
            hint=_NO_CONVERSION_HINT,
        )
        self.model_name = model_name
        self.chipset = chipset
        self.config, clip_cfg, self.compression, self.default_size = (
            _prior_configs(model_name)
        )
        on_tpu = jax.default_backend() == "tpu"
        self.dtype = jnp.bfloat16 if on_tpu else jnp.float32
        self.unet = UNet2DConditionModel(self.config, dtype=self.dtype)
        self.text_encoder = CLIPTextEncoder(clip_cfg, dtype=self.dtype)
        self.tokenizer = load_tokenizer(None, vocab_size=clip_cfg.vocab_size)
        self.mesh = (
            chipset.mesh() if chipset is not None else make_mesh(jax.devices()[:1])
        )

        rng = jax.random.key(zlib.crc32(model_name.encode()))
        k1, k2 = jax.random.split(rng)
        n_down = len(self.config.block_out_channels) - 1
        hw = 2 ** max(n_down, 2)
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            unet_params = self.unet.init(
                k1,
                jnp.zeros((1, hw, hw, PRIOR_CHANNELS)),
                jnp.zeros((1,)),
                jnp.zeros((1, 77, self.config.cross_attention_dim)),
            )["params"]
            text_params = self.text_encoder.init(
                k2, jnp.zeros((1, 77), jnp.int32)
            )["params"]
        cast = lambda x: jnp.asarray(x, self.dtype)
        self.params = jax.device_put(
            jax.tree_util.tree_map(
                cast, {"unet": unet_params, "text": text_params}
            ),
            replicated(self.mesh),
        )
        self._programs: dict[tuple, callable] = {}
        self._lock = threading.Lock()

    def release(self):
        self.params = None
        self._programs.clear()

    def _program(self, key: tuple):
        with self._lock:
            if key in self._programs:
                return self._programs[key]
        ch, cw, batch, steps = key
        scheduler = get_scheduler("DDPMScheduler")
        schedule = scheduler.schedule(steps)
        unet = self.unet

        def run(params, rng, text_hiddens, guidance):
            """text_hiddens rows are [uncond | cond] stacked (CFG 2N)."""
            latents = jax.random.normal(
                rng, (batch, ch, cw, PRIOR_CHANNELS), jnp.float32
            ) * jnp.asarray(schedule.init_noise_sigma, jnp.float32)
            state = scheduler.init_state(latents.shape, latents.dtype)

            def body(carry, i):
                latents, state = carry
                inp = scheduler.scale_model_input(schedule, latents, i)
                model_in = jnp.concatenate([inp, inp], axis=0).astype(self.dtype)
                t = jnp.asarray(schedule.timesteps)[i]
                pred = unet.apply(
                    {"params": params["unet"]},
                    model_in,
                    jnp.broadcast_to(t, (2 * batch,)),
                    text_hiddens,
                ).astype(jnp.float32)
                pred_u, pred_c = jnp.split(pred, 2, axis=0)
                pred = pred_u + guidance * (pred_c - pred_u)
                noise = jax.random.normal(
                    jax.random.fold_in(rng, i), latents.shape, jnp.float32
                )
                state, latents = scheduler.step(
                    schedule, state, i, latents, pred, noise
                )
                return (latents, state), ()

            (latents, _), _ = jax.lax.scan(
                body, (latents, state), jnp.arange(steps)
            )
            return latents

        program = jax.jit(run)
        with self._lock:
            self._programs[key] = program
        return program

    def generate(self, prompt: str, negative_prompt: str = "",
                 num_images: int = 1, steps: int = 20,
                 guidance_scale: float = 4.0, height: int | None = None,
                 width: int | None = None, rng=None):
        """-> image_embeddings [N, ch, cw, 16] (stage-C latents)."""
        params = self.params
        if params is None:
            raise Exception(f"prior {self.model_name} was evicted; resubmit")
        if rng is None:
            rng = jax.random.key(0)
        height = int(height or self.default_size)
        width = int(width or self.default_size)
        ch = max(4, math.ceil(height / self.compression))
        cw = max(4, math.ceil(width / self.compression))
        texts = [negative_prompt] * num_images + [prompt] * num_images
        ids = jnp.asarray(self.tokenizer(texts))
        out = self.text_encoder.apply({"params": params["text"]}, ids)
        return self._program((ch, cw, num_images, steps))(
            params, rng, out["hidden_states"], jnp.float32(guidance_scale)
        )

    def run(self, prompt="", negative_prompt="",
            pipeline_type="StableCascadePriorPipeline", **kwargs):
        params = self.params
        if params is None:
            raise Exception(
                f"pipeline {self.model_name} was evicted; resubmit the job"
            )
        timings: dict[str, float] = {}
        steps = int(kwargs.pop("num_inference_steps", 20))
        guidance_scale = float(kwargs.pop("guidance_scale", 4.0))
        n_images = int(kwargs.pop("num_images_per_prompt", 1))
        height = kwargs.pop("height", None)
        width = kwargs.pop("width", None)
        rng = kwargs.pop("rng", None)
        chipset = kwargs.pop("chipset", None)
        decoder = kwargs.pop("decoder", None) or {}
        kwargs.pop("scheduler_type", None)

        if rng is None:
            rng = jax.random.key(0)
        prior_rng, dec_rng = jax.random.split(rng)

        # resolve (and weight-check) the decoder BEFORE the prior denoise
        # so a missing-weights failure doesn't cost the whole stage-C run
        # (reference pipeline_steps.py:70-90: decoder stage consumes the
        # embeddings with 10 steps, guidance 0)
        from ..registry import get_pipeline

        decoder_name = decoder.get(
            "model_name", _decoder_name_for(self.model_name)
        )
        if _is_tiny(self.model_name):
            # tiny-model jobs must stay hermetic end to end
            decoder_name = _decoder_name_for(self.model_name)
        decoder_pipe = get_pipeline(
            decoder_name,
            pipeline_type=decoder.get(
                "pipeline_type", "StableCascadeDecoderPipeline"
            ),
            chipset=chipset,
        )

        t0 = time.perf_counter()
        embeds = jax.block_until_ready(
            self.generate(
                prompt, negative_prompt, num_images=n_images, steps=steps,
                guidance_scale=guidance_scale, height=height, width=width,
                rng=prior_rng,
            )
        )
        timings["prior_s"] = round(time.perf_counter() - t0, 3)
        images, pipeline_config = decoder_pipe.run(
            image_embeddings=embeds,
            num_inference_steps=int(decoder.get("num_inference_steps", 10)),
            height=height,
            width=width,
            rng=dec_rng,
        )
        pipeline_config["prior"] = {
            "model": self.model_name,
            "pipeline": pipeline_type,
            "steps": steps,
            "guidance_scale": guidance_scale,
        }
        pipeline_config.setdefault("timings", {}).update(timings)
        return images, pipeline_config


class CascadePipeline:
    """Resident stage-B decoder serving StableCascadeDecoderPipeline wire
    names; turns `image_embeddings` into pixels (runs the prior internally
    when a job arrives with only a prompt)."""

    def __init__(self, model_name: str, chipset=None,
                 allow_random_init: bool = False):
        require_weights_present(
            model_name, None, allow_random_init, component="Cascade decoder",
            hint=_NO_CONVERSION_HINT,
        )
        self.model_name = model_name
        self.chipset = chipset
        unet_cfg, vae_cfg, self.default_size = _decoder_configs(model_name)
        on_tpu = jax.default_backend() == "tpu"
        self.dtype = jnp.bfloat16 if on_tpu else jnp.float32
        self.unet = UNet2DConditionModel(unet_cfg, dtype=self.dtype)
        self.vae = AutoencoderKL(vae_cfg, dtype=self.dtype)
        self.latent_factor = 2 ** (len(vae_cfg.block_out_channels) - 1)
        self.mesh = (
            chipset.mesh() if chipset is not None else make_mesh(jax.devices()[:1])
        )

        seed = zlib.crc32(model_name.encode())
        k1, k2, k3 = jax.random.split(jax.random.key(seed), 3)
        n_down = len(unet_cfg.block_out_channels) - 1
        hw = 2 ** max(n_down, 2)
        cross = unet_cfg.cross_attention_dim
        dtype = self.dtype
        import flax.linen as nn

        # flattened stage-C latents -> cross-attention tokens
        class EffnetProj(nn.Module):
            @nn.compact
            def __call__(self, e):
                b, ch, cw, c = e.shape
                return nn.Dense(cross, dtype=dtype, name="proj")(
                    e.reshape(b, ch * cw, c)
                )

        self.effnet_proj = EffnetProj()
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            unet_params = self.unet.init(
                k1,
                jnp.zeros((1, hw, hw, unet_cfg.in_channels)),
                jnp.zeros((1,)),
                jnp.zeros((1, 16, cross)),
            )["params"]
            vae_params = self.vae.init(
                k2,
                jnp.zeros(
                    (1, hw * self.latent_factor, hw * self.latent_factor, 3)
                ),
            )["params"]
            proj_params = self.effnet_proj.init(
                k3, jnp.zeros((1, 4, 4, PRIOR_CHANNELS))
            )["params"]
        cast = lambda x: jnp.asarray(x, self.dtype)
        self.params = jax.device_put(
            jax.tree_util.tree_map(cast, {
                "unet": unet_params,
                "vae": vae_params,
                "proj": proj_params,
            }),
            replicated(self.mesh),
        )
        self._programs: dict[tuple, callable] = {}
        self._lock = threading.Lock()

    def release(self):
        self.params = None
        self._programs.clear()

    def _program(self, key: tuple):
        with self._lock:
            if key in self._programs:
                return self._programs[key]
        lh, lw, batch, steps, ch, cw = key
        scheduler = get_scheduler("DDPMScheduler")
        schedule = scheduler.schedule(steps)
        unet = self.unet
        vae = self.vae
        proj = self.effnet_proj
        latent_c = unet.config.in_channels

        def run(params, rng, embeds):
            """Unguided (reference decoder stage runs guidance_scale=0)."""
            context = proj.apply(
                {"params": params["proj"]}, embeds.astype(self.dtype)
            )
            latents = jax.random.normal(
                rng, (batch, lh, lw, latent_c), jnp.float32
            ) * jnp.asarray(schedule.init_noise_sigma, jnp.float32)
            state = scheduler.init_state(latents.shape, latents.dtype)

            def body(carry, i):
                latents, state = carry
                inp = scheduler.scale_model_input(schedule, latents, i)
                t = jnp.asarray(schedule.timesteps)[i]
                pred = unet.apply(
                    {"params": params["unet"]},
                    inp.astype(self.dtype),
                    jnp.broadcast_to(t, (batch,)),
                    context,
                ).astype(jnp.float32)
                noise = jax.random.normal(
                    jax.random.fold_in(rng, i), latents.shape, jnp.float32
                )
                state, latents = scheduler.step(
                    schedule, state, i, latents, pred, noise
                )
                return (latents, state), ()

            (latents, _), _ = jax.lax.scan(
                body, (latents, state), jnp.arange(steps)
            )
            pixels = vae.apply(
                {"params": params["vae"]}, latents.astype(self.dtype),
                method=vae.decode,
            )
            return (
                (pixels.astype(jnp.float32) + 1.0) * 127.5
            ).clip(0.0, 255.0).round().astype(jnp.uint8)

        program = jax.jit(run)
        with self._lock:
            self._programs[key] = program
        return program

    def run(self, prompt="", negative_prompt="",
            pipeline_type="StableCascadeDecoderPipeline", **kwargs):
        params = self.params
        if params is None:
            raise Exception(
                f"pipeline {self.model_name} was evicted; resubmit the job"
            )
        timings: dict[str, float] = {}
        steps = int(kwargs.pop("num_inference_steps", 10))
        n_images = int(kwargs.pop("num_images_per_prompt", 1))
        # the decoder stage itself is unguided (reference passes
        # guidance_scale=0); on prompt-only/combined jobs the job's guidance
        # and step count belong to the internal prior stage instead
        guidance_scale = kwargs.pop("guidance_scale", None)
        prior_steps = kwargs.pop("prior_timesteps", None)
        kwargs.pop("scheduler_type", None)
        rng = kwargs.pop("rng", None)
        if rng is None:
            rng = jax.random.key(0)
        chipset = kwargs.pop("chipset", None)

        height = int(kwargs.pop("height", None) or self.default_size)
        width = int(kwargs.pop("width", None) or self.default_size)
        height, width = (max(64, (d // 64) * 64) for d in (height, width))
        lh, lw = height // self.latent_factor, width // self.latent_factor

        embeds = kwargs.pop("image_embeddings", None)
        rng, prior_rng, dec_rng = jax.random.split(rng, 3)
        if embeds is None:
            from ..registry import get_pipeline

            t0 = time.perf_counter()
            prior = get_pipeline(
                _prior_name_for(self.model_name),
                pipeline_type="StableCascadePriorPipeline",
                chipset=chipset,
            )
            # combined-job semantics: the job's steps/guidance steer the
            # prior (the reference's MAIN pipeline); the decoder stage keeps
            # its fixed reference default of 10 unguided steps
            embeds = jax.block_until_ready(
                prior.generate(
                    prompt, negative_prompt, num_images=n_images,
                    steps=int(prior_steps or steps),
                    guidance_scale=float(
                        4.0 if guidance_scale is None else guidance_scale
                    ),
                    height=height, width=width, rng=prior_rng,
                )
            )
            steps = 10  # reference decoder stage step count
            timings["prior_s"] = round(time.perf_counter() - t0, 3)
        embeds = jnp.asarray(embeds)
        n_images = int(embeds.shape[0])

        key = (lh, lw, n_images, steps, embeds.shape[1], embeds.shape[2])
        program = self._program(key)
        t0 = time.perf_counter()
        pixels = jax.block_until_ready(program(params, dec_rng, embeds))
        timings["denoise_decode_s"] = round(time.perf_counter() - t0, 3)

        images = [Image.fromarray(img) for img in np.asarray(pixels)]
        pipeline_config = {
            "model": self.model_name,
            "pipeline": pipeline_type,
            "scheduler": "DDPMScheduler",
            "mode": "txt2img",
            "steps": steps,
            "size": [width, height],
            "timings": timings,
        }
        return images, pipeline_config


@register_family("cascade")
def _build_cascade(model_name, chipset, **variant):
    return CascadePipeline(model_name, chipset, **variant)


@register_family("cascade_prior")
def _build_cascade_prior(model_name, chipset, **variant):
    return CascadePriorPipeline(model_name, chipset, **variant)
