"""Stable Cascade (Wuerstchen v3) serving: prior (stage C) -> latent
decoder (stage B) -> Paella VQGAN pixel decode (stage A).

Reference behavior replaced: swarm/diffusion/pipeline_steps.py:70-90 chains
`StableCascadeDecoderPipeline.from_pretrained` after a prior main pipeline,
feeding `image_embeddings` with `num_inference_steps=10, guidance_scale=0`;
the hive schedules the prior as the main pipeline and rides a `decoder`
parameter dict (model_name / pipeline_type / variant).

TPU redesign: both stages are resident jitted programs built on the TRUE
`StableCascadeUNet` architecture (models/cascade_unet.py) with weights
converted from the diffusers checkpoints (models/conversion.py::
convert_cascade_unet — geometry inferred from the state dict). Stage C
denoises the 16-channel ~42.67x-compressed latent under one `lax.scan`
with the ratio-space Wuerstchen scheduler and CFG as a batch of 2,
conditioned on CLIP-bigG pre-LN hidden states + projected pooled embeds
(attention-masked, diffusers parity); stage B denoises the 4-channel VQ
latent conditioned on the stage-C latent through `effnet_mapper`, unguided
per the reference default; stage A is the converted Paella VQGAN decoder.
"""

from __future__ import annotations

import json
import logging
import math
import threading
import time
import zlib
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np
from PIL import Image

from ..models.cascade_unet import (
    TINY_CASCADE_B,
    TINY_CASCADE_C,
    StableCascadeUNet,
)
from ..models.clip import CLIPTextConfig, CLIPTextEncoder
from ..models.paella_vq import TINY_PAELLA_VQ, PaellaVQDecoder
from ..models.tokenizer import load_tokenizer
from ..parallel.mesh import make_mesh, replicated
from ..registry import register_family
from ..schedulers import get_scheduler
from ..weights import is_test_model, require_weights_present

logger = logging.getLogger(__name__)

_NO_WEIGHTS_HINT = (
    "Download the Stable Cascade checkpoints (prior + decoder repos) with "
    "`python -m chiaswarm_tpu.initialize --download` so they convert at load."
)

# diffusers pipeline constants (StableCascadePrior/DecoderPipeline configs)
PRIOR_COMPRESSION = 42.67  # resolution_multiple: 1024 -> 24
LATENT_DIM_SCALE = 10.67  # stage-C grid -> stage-B latent grid (24 -> 256)
PRIOR_CHANNELS = 16  # the "effnet" latent space both stages agree on

_is_tiny = is_test_model


# stage-C conditioning tower for tiny jobs (matches TINY_CASCADE_C's
# text/pooled widths); real jobs read geometry from the checkpoint
_TINY_CASCADE_CLIP = CLIPTextConfig(
    vocab_size=1000,
    hidden_size=16,
    num_layers=2,
    num_heads=4,
    max_positions=77,
    projection_dim=16,
    apply_final_norm=False,
)


def _decoder_name_for(prior_name: str) -> str:
    if _is_tiny(prior_name):
        return "test/tiny-cascade"
    if "prior" in prior_name:
        return prior_name.replace("-prior", "")
    return "stabilityai/stable-cascade"


def _prior_name_for(decoder_name: str) -> str:
    if _is_tiny(decoder_name):
        return "test/tiny-cascade-prior"
    return decoder_name + "-prior"


def _clip_cfg_from_json(tj: dict) -> CLIPTextConfig:
    """CLIPTextModelWithProjection geometry (laion bigG for the released
    checkpoints) with Stable Cascade's pre-LN conditioning semantics."""
    return CLIPTextConfig(
        vocab_size=int(tj.get("vocab_size", 49408)),
        hidden_size=int(tj.get("hidden_size", 1280)),
        num_layers=int(tj.get("num_hidden_layers", 32)),
        num_heads=int(tj.get("num_attention_heads", 20)),
        max_positions=int(tj.get("max_position_embeddings", 77)),
        hidden_act=str(tj.get("hidden_act", "gelu")),
        projection_dim=int(tj.get("projection_dim", 1280)),
        apply_final_norm=False,
    )


def _load_converted_cascade(model_name: str, model_dir=None,
                            stage: str | None = None):
    """-> {"unet_cfg","unet","text","clip_cfg"[,"vqgan_cfg","vqgan"]} or
    None (not downloaded). Prior repos carry a `prior/` subfolder, decoder
    repos `decoder/` + `vqgan/`; both carry `text_encoder/`. `stage`
    ("prior"/"decoder") pins which repo kind the caller can serve — a
    pipeline pointed at the WRONG stage's repo must fail diagnosably, not
    load the other stage's UNet."""
    if _is_tiny(model_name):
        return None
    if model_dir is None:
        from ..weights import model_dir_for

        model_dir = model_dir_for(model_name)
    if model_dir is None:
        return None
    from ..models.conversion import (
        convert_cascade_unet,
        convert_clip,
        convert_paella_vq,
        load_torch_state_dict,
    )
    from ..weights import MissingWeightsError

    def read_json(sub):
        p = model_dir / sub / "config.json"
        return json.loads(p.read_text()) if p.is_file() else {}

    stage_sub = "prior" if (model_dir / "prior").is_dir() else "decoder"
    if stage is not None and stage != stage_sub:
        if (model_dir / stage_sub).is_dir():
            raise MissingWeightsError(
                f"'{model_name}' is a Stable Cascade {stage_sub} repo but "
                f"this pipeline serves the {stage} stage — point the job at "
                f"the matching repo (prior jobs chain the decoder via the "
                f"`decoder` parameter)."
            )
        return None  # neither subfolder present: not downloaded
    try:
        unet_cfg, unet = convert_cascade_unet(
            load_torch_state_dict(model_dir, stage_sub), read_json(stage_sub)
        )
        out = {
            "unet_cfg": unet_cfg,
            "unet": unet,
            "clip_cfg": _clip_cfg_from_json(read_json("text_encoder")),
            "text": convert_clip(load_torch_state_dict(model_dir, "text_encoder")),
            "model_dir": model_dir,
        }
        if stage_sub == "decoder":
            vq_cfg, vq = convert_paella_vq(
                load_torch_state_dict(model_dir, "vqgan"), read_json("vqgan")
            )
            out["vqgan_cfg"] = vq_cfg
            out["vqgan"] = vq
        return out
    except (FileNotFoundError, OSError):
        return None
    except Exception as e:
        raise MissingWeightsError(
            f"checkpoint under {model_dir} could not be converted for "
            f"'{model_name}': {e}"
        ) from e


def _attention_mask(ids: np.ndarray, eos_id: int) -> np.ndarray:
    """1 through the first EOS, 0 for the EOS-padding tail (the tokenizer
    pads with EOS; diffusers' cascade pipelines mask padding)."""
    first_eos = np.argmax(ids == eos_id, axis=-1)
    pos = np.arange(ids.shape[1])[None, :]
    return (pos <= first_eos[:, None]).astype(np.int32)


def _encode_text(tokenizer, clip_cfg, text_encoder, text_params,
                 texts: list[str]):
    """Shared masked CLIP encode for both cascade stages -> (hiddens
    zeroed past EOS, pooled-projected [B, 1, D])."""
    ids = np.asarray(tokenizer(texts))
    mask = _attention_mask(ids, clip_cfg.vocab_size - 1)
    out = text_encoder.apply(
        {"params": text_params},
        jnp.asarray(ids),
        attention_mask=jnp.asarray(mask),
    )
    # keep padding from injecting garbage keys: the UNet cross-attends
    # every token, so zero the masked positions like diffusers' masked
    # encode leaves them attended-nowhere
    hiddens = out["hidden_states"] * jnp.asarray(mask)[:, :, None].astype(
        out["hidden_states"].dtype
    )
    return hiddens, out["pooled"][:, None, :]


class CascadePriorPipeline:
    """Resident stage-C prior; produces `image_embeddings` (the compressed
    spatial latent). The hive schedules THIS as the main pipeline
    (reference diffusion_func.py:151-161 takes `.image_embeddings` from the
    main pipeline output), so `run()` chains into the decoder named by the
    job's `decoder` parameter."""

    def __init__(self, model_name: str, chipset=None,
                 allow_random_init: bool = False):
        self.model_name = model_name
        self.chipset = chipset
        conv = _load_converted_cascade(model_name, stage="prior")
        if conv is None:
            require_weights_present(
                model_name, None, allow_random_init,
                component="Cascade prior", hint=_NO_WEIGHTS_HINT,
            )
            self.config = TINY_CASCADE_C
            clip_cfg = _TINY_CASCADE_CLIP
            self.compression = 8.0
            self.default_size = 64
        else:
            self.config = conv["unet_cfg"]
            clip_cfg = conv["clip_cfg"]
            self.compression = PRIOR_COMPRESSION
            self.default_size = 1024
        on_tpu = jax.default_backend() == "tpu"
        self.dtype = jnp.bfloat16 if on_tpu else jnp.float32
        self.clip_cfg = clip_cfg
        self.unet = StableCascadeUNet(self.config, dtype=self.dtype)
        self.text_encoder = CLIPTextEncoder(clip_cfg, dtype=self.dtype)
        self.tokenizer = load_tokenizer(
            conv and conv.get("model_dir"), vocab_size=clip_cfg.vocab_size
        )
        self.mesh = (
            chipset.mesh() if chipset is not None else make_mesh(jax.devices()[:1])
        )

        if conv is None:
            rng = jax.random.key(zlib.crc32(model_name.encode()))
            k1, k2 = jax.random.split(rng)
            with jax.default_device(jax.local_devices(backend="cpu")[0]):
                unet_params = self.unet.init(
                    k1,
                    jnp.zeros((1, 8, 8, self.config.in_channels)),
                    jnp.zeros((1,)),
                    jnp.zeros((1, 1, self.config.clip_text_pooled_in_channels)),
                    clip_text=jnp.zeros(
                        (1, 77, self.config.clip_text_in_channels)
                    ),
                    clip_img=jnp.zeros(
                        (1, 1, self.config.clip_image_in_channels)
                    ),
                )["params"]
                text_params = self.text_encoder.init(
                    k2, jnp.zeros((1, 77), jnp.int32)
                )["params"]
            tree = {"unet": unet_params, "text": text_params}
        else:
            tree = {"unet": conv["unet"], "text": conv["text"]}
        cast = lambda x: jnp.asarray(x, self.dtype)
        self.params = jax.device_put(
            jax.tree_util.tree_map(cast, tree), replicated(self.mesh)
        )
        # insertion-ordered so the program_cache_max bound below can evict
        # least-recently-used first (SW007; same knob as the SD family)
        self._programs: OrderedDict = OrderedDict()
        self._lock = threading.Lock()

    def release(self):
        self.params = None
        self._programs.clear()

    def _program(self, key: tuple):
        with self._lock:
            if key in self._programs:
                self._programs.move_to_end(key)
                return self._programs[key]
        ch, cw, batch, steps = key
        scheduler = get_scheduler("DDPMWuerstchenScheduler")
        schedule = scheduler.schedule(steps)
        unet = self.unet
        cfg = self.config

        def run(params, rng, text_hiddens, text_pooled, guidance):
            """text rows are [uncond | cond] stacked (CFG 2N)."""
            latents = jax.random.normal(
                rng, (batch, ch, cw, cfg.in_channels), jnp.float32
            )
            ratios = jnp.asarray(schedule.timesteps)
            clip_img = jnp.zeros(
                (2 * batch, 1, cfg.clip_image_in_channels), self.dtype
            )

            def body(carry, i):
                latents, _ = carry
                model_in = jnp.concatenate([latents, latents], axis=0)
                r = jnp.broadcast_to(ratios[i], (2 * batch,))
                pred = unet.apply(
                    {"params": params["unet"]},
                    model_in.astype(self.dtype),
                    r,
                    text_pooled,
                    clip_text=text_hiddens,
                    clip_img=clip_img,
                ).astype(jnp.float32)
                pred_u, pred_c = jnp.split(pred, 2, axis=0)
                pred = pred_u + guidance * (pred_c - pred_u)
                noise = jax.random.normal(
                    jax.random.fold_in(rng, i), latents.shape, jnp.float32
                )
                _, latents = scheduler.step(
                    schedule, (), i, latents, pred, noise
                )
                return (latents, ()), ()

            (latents, _), _ = jax.lax.scan(
                body, (latents, ()), jnp.arange(steps)
            )
            return latents

        program = jax.jit(run)
        with self._lock:
            self._programs[key] = program
            from .common import PROGRAM_EVICTED, program_cache_cap

            cap = program_cache_cap()
            while cap and len(self._programs) > cap:
                self._programs.popitem(last=False)
                PROGRAM_EVICTED.inc(kind="program")
        return program

    def generate(self, prompt: str, negative_prompt: str = "",
                 num_images: int = 1, steps: int = 20,
                 guidance_scale: float = 4.0, height: int | None = None,
                 width: int | None = None, rng=None):
        """-> image_embeddings [N, ch, cw, 16] (stage-C latents)."""
        params = self.params
        if params is None:
            raise Exception(f"prior {self.model_name} was evicted; resubmit")
        if rng is None:
            rng = jax.random.key(0)
        height = int(height or self.default_size)
        width = int(width or self.default_size)
        ch = max(4, math.ceil(height / self.compression))
        cw = max(4, math.ceil(width / self.compression))
        texts = [negative_prompt] * num_images + [prompt] * num_images
        hiddens, pooled = _encode_text(
            self.tokenizer, self.clip_cfg, self.text_encoder, params["text"],
            texts,
        )
        return self._program((ch, cw, num_images, steps))(
            params, rng, hiddens, pooled, jnp.float32(guidance_scale)
        )

    def run(self, prompt="", negative_prompt="",
            pipeline_type="StableCascadePriorPipeline", **kwargs):
        params = self.params
        if params is None:
            raise Exception(
                f"pipeline {self.model_name} was evicted; resubmit the job"
            )
        timings: dict[str, float] = {}
        steps = int(kwargs.pop("num_inference_steps", 20))
        guidance_scale = float(kwargs.pop("guidance_scale", 4.0))
        n_images = int(kwargs.pop("num_images_per_prompt", 1))
        height = kwargs.pop("height", None)
        width = kwargs.pop("width", None)
        rng = kwargs.pop("rng", None)
        chipset = kwargs.pop("chipset", None)
        decoder = kwargs.pop("decoder", None) or {}
        kwargs.pop("scheduler_type", None)

        if rng is None:
            rng = jax.random.key(0)
        prior_rng, dec_rng = jax.random.split(rng)

        # resolve (and weight-check) the decoder BEFORE the prior denoise
        # so a missing-weights failure doesn't cost the whole stage-C run
        # (reference pipeline_steps.py:70-90: decoder stage consumes the
        # embeddings with 10 steps, guidance 0)
        from ..registry import get_pipeline

        decoder_name = decoder.get(
            "model_name", _decoder_name_for(self.model_name)
        )
        if _is_tiny(self.model_name):
            # tiny-model jobs must stay hermetic end to end
            decoder_name = _decoder_name_for(self.model_name)
        decoder_pipe = get_pipeline(
            decoder_name,
            pipeline_type=decoder.get(
                "pipeline_type", "StableCascadeDecoderPipeline"
            ),
            chipset=chipset,
        )

        t0 = time.perf_counter()
        embeds = jax.block_until_ready(
            self.generate(
                prompt, negative_prompt, num_images=n_images, steps=steps,
                guidance_scale=guidance_scale, height=height, width=width,
                rng=prior_rng,
            )
        )
        timings["prior_s"] = round(time.perf_counter() - t0, 3)
        images, pipeline_config = decoder_pipe.run(
            prompt=prompt,
            image_embeddings=embeds,
            num_inference_steps=int(decoder.get("num_inference_steps", 10)),
            height=height,
            width=width,
            rng=dec_rng,
        )
        pipeline_config["prior"] = {
            "model": self.model_name,
            "pipeline": pipeline_type,
            "steps": steps,
            "guidance_scale": guidance_scale,
        }
        pipeline_config.setdefault("timings", {}).update(timings)
        return images, pipeline_config


class CascadePipeline:
    """Resident stage-B decoder serving StableCascadeDecoderPipeline wire
    names; turns `image_embeddings` into pixels (runs the prior internally
    when a job arrives with only a prompt)."""

    def __init__(self, model_name: str, chipset=None,
                 allow_random_init: bool = False):
        self.model_name = model_name
        self.chipset = chipset
        conv = _load_converted_cascade(model_name, stage="decoder")
        if conv is None:
            require_weights_present(
                model_name, None, allow_random_init,
                component="Cascade decoder", hint=_NO_WEIGHTS_HINT,
            )
            self.config = TINY_CASCADE_B
            self.vq_cfg = TINY_PAELLA_VQ
            clip_cfg = _TINY_CASCADE_CLIP
            self.default_size = 64
            self.latent_dim_scale = 2.0
        else:
            self.config = conv["unet_cfg"]
            self.vq_cfg = conv["vqgan_cfg"]
            clip_cfg = conv["clip_cfg"]
            self.default_size = 1024
            self.latent_dim_scale = LATENT_DIM_SCALE
        on_tpu = jax.default_backend() == "tpu"
        self.dtype = jnp.bfloat16 if on_tpu else jnp.float32
        self.clip_cfg = clip_cfg
        self.unet = StableCascadeUNet(self.config, dtype=self.dtype)
        self.vqgan = PaellaVQDecoder(self.vq_cfg, dtype=self.dtype)
        self.text_encoder = CLIPTextEncoder(clip_cfg, dtype=self.dtype)
        self.tokenizer = load_tokenizer(
            conv and conv.get("model_dir"), vocab_size=clip_cfg.vocab_size
        )
        self.mesh = (
            chipset.mesh() if chipset is not None else make_mesh(jax.devices()[:1])
        )

        if conv is None:
            seed = zlib.crc32(model_name.encode())
            k1, k2, k3 = jax.random.split(jax.random.key(seed), 3)
            with jax.default_device(jax.local_devices(backend="cpu")[0]):
                unet_params = self.unet.init(
                    k1,
                    jnp.zeros((1, 8, 8, self.config.in_channels)),
                    jnp.zeros((1,)),
                    jnp.zeros((1, 1, self.config.clip_text_pooled_in_channels)),
                    effnet=jnp.zeros(
                        (1, 4, 4, self.config.effnet_in_channels)
                    ),
                )["params"]
                vq_params = self.vqgan.init(
                    k2, jnp.zeros((1, 4, 4, self.vq_cfg.latent_channels))
                )["params"]
                text_params = self.text_encoder.init(
                    k3, jnp.zeros((1, 77), jnp.int32)
                )["params"]
            tree = {
                "unet": unet_params, "vqgan": vq_params, "text": text_params,
            }
        else:
            tree = {
                "unet": conv["unet"],
                "vqgan": conv["vqgan"],
                "text": conv["text"],
            }
        cast = lambda x: jnp.asarray(x, self.dtype)
        self.params = jax.device_put(
            jax.tree_util.tree_map(cast, tree), replicated(self.mesh)
        )
        # insertion-ordered so the program_cache_max bound below can evict
        # least-recently-used first (SW007; same knob as the SD family)
        self._programs: OrderedDict = OrderedDict()
        self._lock = threading.Lock()

    def release(self):
        self.params = None
        self._programs.clear()

    def _program(self, key: tuple):
        with self._lock:
            if key in self._programs:
                self._programs.move_to_end(key)
                return self._programs[key]
        lh, lw, batch, steps, eh, ew = key
        scheduler = get_scheduler("DDPMWuerstchenScheduler")
        schedule = scheduler.schedule(steps)
        unet = self.unet
        vqgan = self.vqgan
        cfg = self.config
        scale_factor = self.vq_cfg.scale_factor

        def run(params, rng, embeds, pooled):
            """Unguided (reference decoder stage runs guidance_scale=0)."""
            latents = jax.random.normal(
                rng, (batch, lh, lw, cfg.in_channels), jnp.float32
            )
            ratios = jnp.asarray(schedule.timesteps)
            effnet = embeds.astype(self.dtype)

            def body(carry, i):
                latents, _ = carry
                r = jnp.broadcast_to(ratios[i], (batch,))
                pred = unet.apply(
                    {"params": params["unet"]},
                    latents.astype(self.dtype),
                    r,
                    pooled,
                    effnet=effnet,
                ).astype(jnp.float32)
                noise = jax.random.normal(
                    jax.random.fold_in(rng, i), latents.shape, jnp.float32
                )
                _, latents = scheduler.step(
                    schedule, (), i, latents, pred, noise
                )
                return (latents, ()), ()

            (latents, _), _ = jax.lax.scan(
                body, (latents, ()), jnp.arange(steps)
            )
            pixels = vqgan.apply(
                {"params": params["vqgan"]},
                (latents * scale_factor).astype(self.dtype),
            )
            # Paella decodes to [0, 1] (diffusers clamps there, not [-1, 1])
            return (
                pixels.astype(jnp.float32) * 255.0
            ).clip(0.0, 255.0).round().astype(jnp.uint8)

        program = jax.jit(run)
        with self._lock:
            self._programs[key] = program
            from .common import PROGRAM_EVICTED, program_cache_cap

            cap = program_cache_cap()
            while cap and len(self._programs) > cap:
                self._programs.popitem(last=False)
                PROGRAM_EVICTED.inc(kind="program")
        return program

    def run(self, prompt="", negative_prompt="",
            pipeline_type="StableCascadeDecoderPipeline", **kwargs):
        params = self.params
        if params is None:
            raise Exception(
                f"pipeline {self.model_name} was evicted; resubmit the job"
            )
        timings: dict[str, float] = {}
        steps = int(kwargs.pop("num_inference_steps", 10))
        n_images = int(kwargs.pop("num_images_per_prompt", 1))
        # the decoder stage itself is unguided (reference passes
        # guidance_scale=0); on prompt-only/combined jobs the job's guidance
        # and step count belong to the internal prior stage instead
        guidance_scale = kwargs.pop("guidance_scale", None)
        prior_steps = kwargs.pop("prior_timesteps", None)
        kwargs.pop("scheduler_type", None)
        rng = kwargs.pop("rng", None)
        if rng is None:
            rng = jax.random.key(0)
        chipset = kwargs.pop("chipset", None)

        height = int(kwargs.pop("height", None) or self.default_size)
        width = int(kwargs.pop("width", None) or self.default_size)
        height, width = (max(64, (d // 64) * 64) for d in (height, width))

        embeds = kwargs.pop("image_embeddings", None)
        rng, prior_rng, dec_rng = jax.random.split(rng, 3)
        if embeds is None:
            from ..registry import get_pipeline

            t0 = time.perf_counter()
            prior = get_pipeline(
                _prior_name_for(self.model_name),
                pipeline_type="StableCascadePriorPipeline",
                chipset=chipset,
            )
            # combined-job semantics: the job's steps/guidance steer the
            # prior (the reference's MAIN pipeline); the decoder stage keeps
            # its fixed reference default of 10 unguided steps
            embeds = jax.block_until_ready(
                prior.generate(
                    prompt, negative_prompt, num_images=n_images,
                    steps=int(prior_steps or steps),
                    guidance_scale=float(
                        4.0 if guidance_scale is None else guidance_scale
                    ),
                    height=height, width=width, rng=prior_rng,
                )
            )
            steps = 10  # reference decoder stage step count
            timings["prior_s"] = round(time.perf_counter() - t0, 3)
        embeds = jnp.asarray(embeds)
        n_images = int(embeds.shape[0])
        eh, ew = int(embeds.shape[1]), int(embeds.shape[2])

        # stage-B latent grid follows the stage-C grid (diffusers
        # latent_dim_scale, truncating int like the reference pipeline:
        # 24 -> int(24*10.67) = 256), NOT the pixel size directly; odd
        # grids survive via the up-path bilinear skip alignment
        lh = 2 * (int(eh * self.latent_dim_scale) // 2)
        lw = 2 * (int(ew * self.latent_dim_scale) // 2)

        # pooled text conditioning (decoder uses pooled only)
        _, pooled = _encode_text(
            self.tokenizer, self.clip_cfg, self.text_encoder, params["text"],
            [prompt] * n_images,
        )

        key = (lh, lw, n_images, steps, eh, ew)
        program = self._program(key)
        t0 = time.perf_counter()
        pixels = jax.block_until_ready(
            program(params, dec_rng, embeds, pooled)
        )
        timings["denoise_decode_s"] = round(time.perf_counter() - t0, 3)

        images = [Image.fromarray(img) for img in np.asarray(pixels)]
        pipeline_config = {
            "model": self.model_name,
            "pipeline": pipeline_type,
            "scheduler": "DDPMWuerstchenScheduler",
            "mode": "txt2img",
            "steps": steps,
            "size": [images[0].width, images[0].height] if images else [0, 0],
            "timings": timings,
        }
        return images, pipeline_config


@register_family("cascade")
def _build_cascade(model_name, chipset, **variant):
    return CascadePipeline(model_name, chipset, **variant)


@register_family("cascade_prior")
def _build_cascade_prior(model_name, chipset, **variant):
    return CascadePriorPipeline(model_name, chipset, **variant)
