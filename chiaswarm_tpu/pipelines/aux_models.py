"""Auxiliary perception models (depth today; pose/segmentation to come).

Reference behavior replaced: swarm/pre_processors/controlnet.py:94-119
(transformers DPT pipeline for the `depth` preprocessor) and
swarm/pre_processors/depth_estimator.py:8-24 (Kandinsky depth hint). TPU
redesign: one resident flax DPT model, jitted per canvas bucket; weights
convert from Intel/dpt-* checkpoints under the model root (weights.py
policy: tiny/test names random-init, real names fail loudly when absent).
"""

from __future__ import annotations

import logging
import threading
import zlib
from pathlib import Path

import numpy as np

logger = logging.getLogger(__name__)

DEFAULT_DEPTH_MODEL = "Intel/dpt-large"
# ImageNet normalization (DPT image processor)
_MEAN = np.asarray([0.485, 0.456, 0.406], np.float32)
_STD = np.asarray([0.229, 0.224, 0.225], np.float32)

_DEPTH: dict[str, "DepthEstimator"] = {}
_DEPTH_LOCK = threading.Lock()


class DepthEstimator:
    def __init__(self, model_name: str = DEFAULT_DEPTH_MODEL,
                 allow_random_init: bool = False):
        import jax
        import jax.numpy as jnp

        from ..models.depth import DPTConfig, DPTDepthModel, TINY_DPT
        from ..settings import load_settings
        from ..weights import is_test_model, require_weights_present

        self.model_name = model_name
        self.config = TINY_DPT if is_test_model(model_name) else DPTConfig()
        on_tpu = jax.default_backend() == "tpu"
        self.dtype = jnp.bfloat16 if on_tpu else jnp.float32
        self.model = DPTDepthModel(self.config, dtype=self.dtype)

        root = Path(load_settings().model_root_dir).expanduser()
        model_dir = root / model_name
        params = None
        if model_dir.is_dir():
            try:
                from ..models.conversion import convert_dpt, load_torch_state_dict

                params = convert_dpt(load_torch_state_dict(model_dir))
            except FileNotFoundError:
                params = None
        if params is None:
            require_weights_present(
                model_name, model_dir if model_dir.is_dir() else None,
                allow_random_init, component="depth model",
            )
            size = self.config.image_size
            params = self.model.init(
                jax.random.key(zlib.crc32(model_name.encode())),
                jnp.zeros((1, size, size, 3)),
            )["params"]
        cast = lambda x: jnp.asarray(x, self.dtype)
        self.params = jax.tree_util.tree_map(cast, params)
        self._program = jax.jit(
            lambda p, px: self.model.apply({"params": p}, px)
        )

    def __call__(self, image) -> np.ndarray:
        """PIL -> inverse-depth map [H, W] float32 normalized to [0, 1]."""
        import jax.numpy as jnp
        from PIL import Image

        size = self.config.image_size
        original = image.size
        rgb = image.convert("RGB").resize((size, size), Image.BICUBIC)
        arr = (np.asarray(rgb, np.float32) / 255.0 - _MEAN) / _STD
        depth = np.asarray(
            self._program(self.params, jnp.asarray(arr[None], self.dtype)),
            np.float32,
        )[0]
        lo, hi = float(depth.min()), float(depth.max())
        depth = (depth - lo) / (hi - lo) if hi > lo else np.zeros_like(depth)
        if original != (size, size):
            # resize in float (mode "F") — a uint8 detour would band smooth
            # depth gradients into 1/255 stair-steps
            depth = np.asarray(
                Image.fromarray(depth.astype(np.float32), mode="F").resize(
                    original, Image.BICUBIC
                ),
                np.float32,
            )
        return np.clip(depth, 0.0, 1.0).astype(np.float32)


def get_depth_estimator(model_name: str | None = None) -> DepthEstimator:
    if model_name is None:
        from ..settings import load_settings

        model_name = load_settings().depth_model or DEFAULT_DEPTH_MODEL
    # construction happens under the lock: a concurrent cold start would
    # otherwise double-load and double-place the full DPT checkpoint
    with _DEPTH_LOCK:
        est = _DEPTH.get(model_name)
        if est is None:
            est = DepthEstimator(model_name)
            _DEPTH[model_name] = est
        return est


def estimate_depth(image, model_name: str | None = None) -> np.ndarray:
    """PIL image -> [H, W] float32 inverse depth in [0, 1]."""
    return get_depth_estimator(model_name)(image)


# --- pose (openpose preprocessor backend) ---

_POSE: dict[str, "PoseEstimator"] = {}
_POSE_LOCK = threading.Lock()

DEFAULT_POSE_MODEL = "lllyasviel/ControlNet-openpose"


class PoseEstimator:
    """Resident heatmap pose network (reference controlnet.py:46-47's
    OpenposeDetector). Returns COCO-18 keypoints in original pixel space."""

    def __init__(self, model_name: str = DEFAULT_POSE_MODEL,
                 allow_random_init: bool = False):
        import jax
        import jax.numpy as jnp

        from ..models.pose import TINY_POSE, PoseConfig, PoseNet
        from ..weights import is_test_model, require_weights_present

        self.model_name = model_name
        self.config = TINY_POSE if is_test_model(model_name) else PoseConfig()
        on_tpu = jax.default_backend() == "tpu"
        self.dtype = jnp.bfloat16 if on_tpu else jnp.float32
        self.model = PoseNet(self.config, dtype=self.dtype)
        # no pose-weight conversion path exists yet: real names fail loudly
        require_weights_present(
            model_name, None, allow_random_init, component="pose model",
            hint=(
                "This worker cannot serve real openpose weights yet; only "
                "the test/tiny pose network is available."
            ),
        )
        size = self.config.image_size
        params = self.model.init(
            jax.random.key(zlib.crc32(model_name.encode())),
            jnp.zeros((1, size, size, 3)),
        )["params"]
        cast = lambda x: jnp.asarray(x, self.dtype)
        self.params = jax.tree_util.tree_map(cast, params)
        self._program = jax.jit(
            lambda p, px: self.model.apply({"params": p}, px)
        )

    def __call__(self, image) -> np.ndarray:
        """PIL -> [18, 3] float32 rows (x_px, y_px, confidence) in the
        ORIGINAL image's pixel coordinates."""
        import jax.numpy as jnp
        from PIL import Image

        size = self.config.image_size
        w, h = image.size
        rgb = image.convert("RGB").resize((size, size), Image.BICUBIC)
        arr = np.asarray(rgb, np.float32) / 127.5 - 1.0
        heat = np.asarray(
            self._program(self.params, jnp.asarray(arr[None], self.dtype)),
            np.float32,
        )[0]  # [S', S', K]
        hs, ws, k = heat.shape
        flat = heat.reshape(hs * ws, k)
        idx = flat.argmax(axis=0)
        conf = flat[idx, np.arange(k)]
        ys, xs = np.divmod(idx, ws)
        out = np.stack(
            [
                (xs + 0.5) / ws * w,
                (ys + 0.5) / hs * h,
                conf,
            ],
            axis=-1,
        )
        return out.astype(np.float32)


def get_pose_estimator(model_name: str | None = None) -> PoseEstimator:
    if model_name is None:
        from ..settings import load_settings

        model_name = getattr(load_settings(), "pose_model", None) \
            or DEFAULT_POSE_MODEL
    with _POSE_LOCK:
        est = _POSE.get(model_name)
        if est is None:
            est = PoseEstimator(model_name)
            _POSE[model_name] = est
        return est


def estimate_pose(image, model_name: str | None = None) -> np.ndarray:
    """PIL image -> [18, 3] (x, y, confidence) keypoints."""
    return get_pose_estimator(model_name)(image)


# --- HED edges (scribble / softedge preprocessor backend) ---

_HED: dict[str, "HEDDetector"] = {}
_HED_LOCK = threading.Lock()

DEFAULT_HED_MODEL = "lllyasviel/Annotators"
_HED_SIZE = 512  # fully convolutional; fixed processing canvas = one program


class HEDDetector:
    """Resident HED edge net (reference controlnet.py:51-57's HEDdetector).
    Returns soft edge probabilities [H, W] in [0, 1] at the ORIGINAL size."""

    def __init__(self, model_name: str = DEFAULT_HED_MODEL,
                 allow_random_init: bool = False):
        import jax
        import jax.numpy as jnp

        from ..models.hed import HEDConfig, HEDNet, TINY_HED
        from ..settings import load_settings
        from ..weights import is_test_model, require_weights_present

        self.model_name = model_name
        self.config = TINY_HED if is_test_model(model_name) else HEDConfig()
        on_tpu = jax.default_backend() == "tpu"
        self.dtype = jnp.bfloat16 if on_tpu else jnp.float32
        self.model = HEDNet(self.config, dtype=self.dtype)

        root = Path(load_settings().model_root_dir).expanduser()
        model_dir = root / model_name
        params = None
        if model_dir.is_dir():
            try:
                params = self._load_converted(model_dir)
            except FileNotFoundError:
                params = None
        if params is None:
            require_weights_present(
                model_name, model_dir if model_dir.is_dir() else None,
                allow_random_init, component="HED edge model",
            )
            params = self.model.init(
                jax.random.key(zlib.crc32(model_name.encode())),
                jnp.zeros((1, 64, 64, 3)),
            )["params"]
        cast = lambda x: jnp.asarray(x, self.dtype)
        self.params = jax.tree_util.tree_map(cast, params)
        self._program = jax.jit(
            lambda p, px: self.model.apply({"params": p}, px)
        )

    @staticmethod
    def _load_converted(model_dir: Path):
        """The Annotators repo ships ControlNetHED as a torch .pth pickle
        (no safetensors) — convert whichever is present."""
        from ..models.conversion import convert_hed, load_torch_state_dict

        try:
            return convert_hed(load_torch_state_dict(model_dir))
        except FileNotFoundError:
            for p in sorted(model_dir.glob("*HED*.pth")):
                import torch

                sd = torch.load(str(p), map_location="cpu", weights_only=True)
                return convert_hed(
                    {k: v.numpy() for k, v in sd.items()}
                )
            raise

    def __call__(self, image) -> np.ndarray:
        import jax.numpy as jnp
        from PIL import Image

        original = image.size
        rgb = image.convert("RGB").resize((_HED_SIZE, _HED_SIZE), Image.BICUBIC)
        px = jnp.asarray(
            np.asarray(rgb, np.float32)[None], self.dtype
        )
        logits = self._program(self.params, px)
        maps = []
        for m in logits:
            arr = np.asarray(m.astype(jnp.float32))[0, :, :, 0]
            maps.append(
                np.asarray(
                    Image.fromarray(arr).resize(original, Image.BILINEAR),
                    np.float32,
                )
            )
        edge = 1.0 / (1.0 + np.exp(-np.mean(np.stack(maps), axis=0)))
        return edge.astype(np.float32)


def get_hed_detector(model_name: str | None = None,
                     allow_random_init: bool = False) -> "HEDDetector":
    name = model_name or DEFAULT_HED_MODEL
    with _HED_LOCK:
        det = _HED.get(name)
        if det is None:
            det = HEDDetector(name, allow_random_init=allow_random_init)
            _HED[name] = det
        return det


def hed_edges(image, model_name: str | None = None):
    """PIL -> [H, W] float32 soft-edge probabilities, or None when no
    converted HED weights are on this worker (callers degrade to the
    classical heuristic with a logged warning)."""
    from ..weights import MissingWeightsError

    try:
        return get_hed_detector(model_name)(image)
    except MissingWeightsError:
        return None
