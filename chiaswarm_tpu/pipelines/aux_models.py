"""Auxiliary perception models (depth, pose, segmentation) for preprocessors."""

from __future__ import annotations


def estimate_depth(image):
    raise Exception("depth estimation is not yet available on this worker.")
