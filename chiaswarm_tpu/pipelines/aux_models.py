"""Auxiliary perception models (depth today; pose/segmentation to come).

Reference behavior replaced: swarm/pre_processors/controlnet.py:94-119
(transformers DPT pipeline for the `depth` preprocessor) and
swarm/pre_processors/depth_estimator.py:8-24 (Kandinsky depth hint). TPU
redesign: one resident flax DPT model, jitted per canvas bucket; weights
convert from Intel/dpt-* checkpoints under the model root (weights.py
policy: tiny/test names random-init, real names fail loudly when absent).
"""

from __future__ import annotations

import logging
import threading
import zlib
from pathlib import Path

import numpy as np

logger = logging.getLogger(__name__)

DEFAULT_DEPTH_MODEL = "Intel/dpt-large"
# ImageNet normalization (DPT image processor)
_MEAN = np.asarray([0.485, 0.456, 0.406], np.float32)
_STD = np.asarray([0.229, 0.224, 0.225], np.float32)

_DEPTH: dict[str, "DepthEstimator"] = {}
_DEPTH_LOCK = threading.Lock()


def _model_dir_stamp(name: str) -> float:
    """mtime of the model directory under the model root (-1 if absent).

    Negative detector caches key on this: a worker started before
    `initialize --download` completed must pick the weights up on the next
    job instead of serving degraded fallbacks for its whole lifetime
    (ADVICE r04)."""
    from ..settings import load_settings

    d = Path(load_settings().model_root_dir).expanduser() / name
    try:
        return d.stat().st_mtime
    except OSError:
        return -1.0


def _cached_detector(cache: dict, name: str, builder, label: str,
                     exceptions: tuple):
    """Build-or-fetch a resident detector with mtime-aware negative
    caching. cache maps name -> (detector_or_None, dir_stamp); a cached
    None is honored only while the checkpoint directory is unchanged.
    Caller must hold the cache's lock."""
    hit = cache.get(name)
    if hit is not None:
        det, stamp = hit
        if det is not None or stamp == _model_dir_stamp(name):
            return det
        logger.info("%s checkpoint dir changed; re-probing weights", label)
    # stamp BEFORE building: if a download completes between the failed
    # build and the stamp read, the stale stamp must not match the
    # now-complete directory (that would re-freeze the negative cache)
    stamp = _model_dir_stamp(name)
    try:
        det = builder()
    except exceptions as e:
        logger.info("no converted %s weights (%s)", label, e)
        cache[name] = (None, stamp)
        return None
    cache[name] = (det, 0.0)
    return det


class DepthEstimator:
    def __init__(self, model_name: str = DEFAULT_DEPTH_MODEL,
                 allow_random_init: bool = False):
        import jax
        import jax.numpy as jnp

        from ..models.depth import DPTConfig, DPTDepthModel, TINY_DPT
        from ..settings import load_settings
        from ..weights import is_test_model, require_weights_present

        self.model_name = model_name
        self.config = TINY_DPT if is_test_model(model_name) else DPTConfig()
        on_tpu = jax.default_backend() == "tpu"
        self.dtype = jnp.bfloat16 if on_tpu else jnp.float32
        self.model = DPTDepthModel(self.config, dtype=self.dtype)

        root = Path(load_settings().model_root_dir).expanduser()
        model_dir = root / model_name
        params = None
        if model_dir.is_dir():
            try:
                from ..models.conversion import convert_dpt, load_torch_state_dict

                params = convert_dpt(load_torch_state_dict(model_dir))
            except FileNotFoundError:
                params = None
        if params is None:
            require_weights_present(
                model_name, model_dir if model_dir.is_dir() else None,
                allow_random_init, component="depth model",
            )
            size = self.config.image_size
            params = self.model.init(
                jax.random.key(zlib.crc32(model_name.encode())),
                jnp.zeros((1, size, size, 3)),
            )["params"]
        cast = lambda x: jnp.asarray(x, self.dtype)
        self.params = jax.tree_util.tree_map(cast, params)
        self._program = jax.jit(
            lambda p, px: self.model.apply({"params": p}, px)
        )

    def __call__(self, image) -> np.ndarray:
        """PIL -> inverse-depth map [H, W] float32 normalized to [0, 1]."""
        import jax.numpy as jnp
        from PIL import Image

        size = self.config.image_size
        original = image.size
        rgb = image.convert("RGB").resize((size, size), Image.BICUBIC)
        arr = (np.asarray(rgb, np.float32) / 255.0 - _MEAN) / _STD
        depth = np.asarray(
            self._program(self.params, jnp.asarray(arr[None], self.dtype)),
            np.float32,
        )[0]
        lo, hi = float(depth.min()), float(depth.max())
        depth = (depth - lo) / (hi - lo) if hi > lo else np.zeros_like(depth)
        if original != (size, size):
            # resize in float (mode "F") — a uint8 detour would band smooth
            # depth gradients into 1/255 stair-steps
            depth = np.asarray(
                Image.fromarray(depth.astype(np.float32), mode="F").resize(
                    original, Image.BICUBIC
                ),
                np.float32,
            )
        return np.clip(depth, 0.0, 1.0).astype(np.float32)


def get_depth_estimator(model_name: str | None = None) -> DepthEstimator:
    if model_name is None:
        from ..settings import load_settings

        model_name = load_settings().depth_model or DEFAULT_DEPTH_MODEL
    # construction happens under the lock: a concurrent cold start would
    # otherwise double-load and double-place the full DPT checkpoint
    with _DEPTH_LOCK:
        est = _DEPTH.get(model_name)
        if est is None:
            est = DepthEstimator(model_name)
            _DEPTH[model_name] = est
        return est


def estimate_depth(image, model_name: str | None = None) -> np.ndarray:
    """PIL image -> [H, W] float32 inverse depth in [0, 1]."""
    return get_depth_estimator(model_name)(image)


# --- pose (openpose preprocessor backend) ---

_POSE: dict[str, "PoseEstimator"] = {}
_POSE_LOCK = threading.Lock()

DEFAULT_POSE_MODEL = "lllyasviel/ControlNet-openpose"


def decode_openpose(paf: np.ndarray, heat: np.ndarray,
                    out_w: int, out_h: int,
                    peak_thresh: float = 0.1,
                    paf_thresh: float = 0.05) -> np.ndarray:
    """Openpose PAF grouping: (paf [h,w,38], heat [h,w,19]) -> people
    [P, 18, 3] with (x, y, conf) scaled to (out_w, out_h).

    The standard pipeline: per-channel peak detection on the smoothed
    heatmaps, candidate limb scoring by line integrals of the part
    affinity fields, greedy per-limb assignment, then assembling limbs
    into per-person keypoint sets (reference: the OpenposeDetector the
    reference runs, swarm/pre_processors/controlnet.py:46-47)."""
    from scipy.ndimage import gaussian_filter

    from ..models.pose import LIMB_SEQ, PAF_IDX

    h, w = heat.shape[:2]
    sx, sy = out_w / w, out_h / h

    # 1. peaks per keypoint channel
    all_peaks: list[list[tuple]] = []
    peak_id = 0
    for k in range(18):
        m = gaussian_filter(heat[:, :, k], sigma=2)
        peaks = (
            (m > np.roll(m, 1, 0)) & (m > np.roll(m, -1, 0))
            & (m > np.roll(m, 1, 1)) & (m > np.roll(m, -1, 1))
            & (m > peak_thresh)
        )
        ys, xs = np.nonzero(peaks)
        rows = []
        for x, y in zip(xs, ys):
            rows.append((float(x), float(y), float(heat[y, x, k]), peak_id))
            peak_id += 1
        all_peaks.append(rows)

    # 2. score candidate limbs by PAF line integral
    connections: list[list[tuple]] = []
    for (a, b), (c1, c2) in zip(LIMB_SEQ, PAF_IDX):
        cand_a, cand_b = all_peaks[a], all_peaks[b]
        scored = []
        for i, pa in enumerate(cand_a):
            for j, pb in enumerate(cand_b):
                vec = np.array([pb[0] - pa[0], pb[1] - pa[1]], np.float32)
                norm = float(np.linalg.norm(vec))
                if norm < 1e-4:
                    continue
                u = vec / norm
                xs = np.linspace(pa[0], pb[0], 10)
                ys = np.linspace(pa[1], pb[1], 10)
                px = paf[
                    np.clip(np.round(ys).astype(int), 0, h - 1),
                    np.clip(np.round(xs).astype(int), 0, w - 1),
                ]
                scores = px[:, c1] * u[0] + px[:, c2] * u[1]
                # distance prior like the reference implementation
                prior = min(0.5 * h / norm - 1.0, 0.0)
                mean = float(scores.mean()) + prior
                if (scores > paf_thresh).sum() > 0.8 * len(scores) and mean > 0:
                    scored.append((i, j, mean))
        scored.sort(key=lambda t: -t[2])
        used_a, used_b, conn = set(), set(), []
        for i, j, s in scored:
            if i not in used_a and j not in used_b:
                used_a.add(i)
                used_b.add(j)
                conn.append((cand_a[i][3], cand_b[j][3], s, i, j))
        connections.append(conn)

    # 3. assemble limbs into people, keyed by global peak id
    flat_peaks = [p for rows in all_peaks for p in rows]
    subsets: list[dict] = []  # {kp_index: peak_id}, "score", "n"
    for limb, ((a, b), conn) in enumerate(zip(LIMB_SEQ, connections)):
        for pid_a, pid_b, score, _, _ in conn:
            placed = False
            for s in subsets:
                if s.get(a) == pid_a or s.get(b) == pid_b:
                    s[a] = pid_a
                    s[b] = pid_b
                    s["score"] += score
                    placed = True
                    break
            if not placed:
                subsets.append({a: pid_a, b: pid_b, "score": score})

    people = []
    for s in subsets:
        kps = [k for k in s if isinstance(k, int)]
        if len(kps) < 4 or s["score"] / max(len(kps), 1) < 0.2:
            continue  # spurious fragments, openpose's subset pruning
        row = np.zeros((18, 3), np.float32)
        for k in kps:
            x, y, conf, _ = flat_peaks[s[k]]
            row[k] = ((x + 0.5) * sx, (y + 0.5) * sy, conf)
        people.append(row)
    if not people:
        return np.zeros((0, 18, 3), np.float32)
    return np.stack(people)


def load_openpose_checkpoint(model_dir):
    """body_pose_model as safetensors or the upstream .pth pickle, from an
    EXPLICIT directory — shared by PoseEstimator and initialize --check so
    a green check means exactly what serving loads. None when absent."""
    from ..models.conversion import (
        convert_openpose_body,
        load_torch_state_dict,
    )

    try:
        return convert_openpose_body(load_torch_state_dict(model_dir))
    except FileNotFoundError:
        for p in sorted(model_dir.glob("*body_pose*.pth")):
            import torch

            sd = torch.load(str(p), map_location="cpu", weights_only=True)
            return convert_openpose_body(
                {k: v.numpy() for k, v in sd.items()}
            )
    return None


class PoseEstimator:
    """Resident body-pose network (reference controlnet.py:46-47's
    OpenposeDetector). Returns per-person COCO-18 keypoints [P, 18, 3] in
    original pixel space.

    Real model names load the converted CMU 6-stage network
    (models.pose.OpenposeBody <- lllyasviel body_pose_model.pth) and
    decode multi-person poses through PAF grouping; tiny/test names keep
    the compact single-person heatmap stand-in."""

    # fixed square canvas: one jitted program (aspect handled by coordinate
    # mapping; the CPM trunk is fully convolutional)
    CANVAS = 368

    def __init__(self, model_name: str = DEFAULT_POSE_MODEL,
                 allow_random_init: bool = False):
        import jax
        import jax.numpy as jnp

        from ..models.pose import OpenposeBody, TINY_POSE, PoseNet
        from ..weights import is_test_model, require_weights_present

        self.model_name = model_name
        on_tpu = jax.default_backend() == "tpu"
        self.dtype = jnp.bfloat16 if on_tpu else jnp.float32
        self.real = not is_test_model(model_name)
        converted = self._load_converted(model_name) if self.real else None
        if self.real and converted is None:
            require_weights_present(
                model_name, None, allow_random_init, component="pose model",
            )
            # allow_random_init bring-up on the real architecture
            self.model = OpenposeBody(dtype=self.dtype)
            params = self.model.init(
                jax.random.key(zlib.crc32(model_name.encode())),
                jnp.zeros((1, 64, 64, 3)),
            )["params"]
        elif self.real:
            from ..models.conversion import checked_converted

            self.model = OpenposeBody(dtype=self.dtype)
            params = checked_converted(
                self.model, (jnp.zeros((1, 64, 64, 3)),), converted,
                "openpose_body", jax.random.key(0),
            )
        else:
            self.config = TINY_POSE
            self.model = PoseNet(self.config, dtype=self.dtype)
            size = self.config.image_size
            params = self.model.init(
                jax.random.key(zlib.crc32(model_name.encode())),
                jnp.zeros((1, size, size, 3)),
            )["params"]
        cast = lambda x: jnp.asarray(x, self.dtype)
        self.params = jax.tree_util.tree_map(cast, params)
        self._program = jax.jit(
            lambda p, px: self.model.apply({"params": p}, px)
        )

    @staticmethod
    def _load_converted(model_name: str):
        from ..weights import model_dir_for

        model_dir = model_dir_for(model_name)
        return None if model_dir is None else load_openpose_checkpoint(
            model_dir
        )

    def __call__(self, image) -> np.ndarray:
        """PIL -> [P, 18, 3] float32 (x_px, y_px, confidence) per person
        in the ORIGINAL image's pixel coordinates."""
        import jax.numpy as jnp
        from PIL import Image

        w, h = image.size
        if self.real:
            size = self.CANVAS
            rgb = image.convert("RGB").resize((size, size), Image.BICUBIC)
            # pytorch-openpose normalization: x/256 - 0.5
            arr = np.asarray(rgb, np.float32) / 256.0 - 0.5
            paf, heat = self._program(
                self.params, jnp.asarray(arr[None], self.dtype)
            )
            return decode_openpose(
                np.asarray(paf, np.float32)[0],
                np.asarray(heat, np.float32)[0], w, h,
            )
        size = self.config.image_size
        rgb = image.convert("RGB").resize((size, size), Image.BICUBIC)
        arr = np.asarray(rgb, np.float32) / 127.5 - 1.0
        heat = np.asarray(
            self._program(self.params, jnp.asarray(arr[None], self.dtype)),
            np.float32,
        )[0]  # [S', S', K]
        hs, ws, k = heat.shape
        flat = heat.reshape(hs * ws, k)
        idx = flat.argmax(axis=0)
        conf = flat[idx, np.arange(k)]
        ys, xs = np.divmod(idx, ws)
        out = np.stack(
            [(xs + 0.5) / ws * w, (ys + 0.5) / hs * h, conf], axis=-1
        )
        return out.astype(np.float32)[None]  # [1, 18, 3]


def get_pose_estimator(model_name: str | None = None) -> PoseEstimator:
    if model_name is None:
        from ..settings import load_settings

        model_name = getattr(load_settings(), "pose_model", None) \
            or DEFAULT_POSE_MODEL
    with _POSE_LOCK:
        est = _POSE.get(model_name)
        if est is None:
            est = PoseEstimator(model_name)
            _POSE[model_name] = est
        return est


def estimate_pose(image, model_name: str | None = None) -> np.ndarray:
    """PIL image -> [P, 18, 3] (x, y, confidence) keypoints per person."""
    return get_pose_estimator(model_name)(image)


# --- HED edges (scribble / softedge preprocessor backend) ---

_HED: dict[str, "HEDDetector"] = {}
_HED_LOCK = threading.Lock()

DEFAULT_HED_MODEL = "lllyasviel/Annotators"
_HED_SIZE = 512  # fully convolutional; fixed processing canvas = one program


class HEDDetector:
    """Resident HED edge net (reference controlnet.py:51-57's HEDdetector).
    Returns soft edge probabilities [H, W] in [0, 1] at the ORIGINAL size."""

    def __init__(self, model_name: str = DEFAULT_HED_MODEL,
                 allow_random_init: bool = False):
        import jax
        import jax.numpy as jnp

        from ..models.hed import HEDConfig, HEDNet, TINY_HED
        from ..settings import load_settings
        from ..weights import is_test_model, require_weights_present

        self.model_name = model_name
        self.config = TINY_HED if is_test_model(model_name) else HEDConfig()
        on_tpu = jax.default_backend() == "tpu"
        self.dtype = jnp.bfloat16 if on_tpu else jnp.float32
        self.model = HEDNet(self.config, dtype=self.dtype)

        root = Path(load_settings().model_root_dir).expanduser()
        model_dir = root / model_name
        params = None
        if model_dir.is_dir():
            try:
                params = self._load_converted(model_dir)
            except FileNotFoundError:
                params = None
        if params is None:
            require_weights_present(
                model_name, model_dir if model_dir.is_dir() else None,
                allow_random_init, component="HED edge model",
            )
            params = self.model.init(
                jax.random.key(zlib.crc32(model_name.encode())),
                jnp.zeros((1, 64, 64, 3)),
            )["params"]
        cast = lambda x: jnp.asarray(x, self.dtype)
        self.params = jax.tree_util.tree_map(cast, params)
        self._program = jax.jit(
            lambda p, px: self.model.apply({"params": p}, px)
        )

    @staticmethod
    def _load_converted(model_dir: Path):
        """The Annotators repo ships ControlNetHED as a torch .pth pickle
        (no safetensors) — convert whichever is present."""
        from ..models.conversion import convert_hed, load_torch_state_dict

        try:
            return convert_hed(load_torch_state_dict(model_dir))
        except FileNotFoundError:
            for p in sorted(model_dir.glob("*HED*.pth")):
                import torch

                sd = torch.load(str(p), map_location="cpu", weights_only=True)
                return convert_hed(
                    {k: v.numpy() for k, v in sd.items()}
                )
            raise

    def __call__(self, image) -> np.ndarray:
        import jax.numpy as jnp
        from PIL import Image

        original = image.size
        rgb = image.convert("RGB").resize((_HED_SIZE, _HED_SIZE), Image.BICUBIC)
        px = jnp.asarray(
            np.asarray(rgb, np.float32)[None], self.dtype
        )
        logits = self._program(self.params, px)
        maps = []
        for m in logits:
            arr = np.asarray(m.astype(jnp.float32))[0, :, :, 0]
            maps.append(
                np.asarray(
                    Image.fromarray(arr).resize(original, Image.BILINEAR),
                    np.float32,
                )
            )
        edge = 1.0 / (1.0 + np.exp(-np.mean(np.stack(maps), axis=0)))
        return edge.astype(np.float32)


def get_hed_detector(model_name: str | None = None,
                     allow_random_init: bool = False) -> "HEDDetector":
    name = model_name or DEFAULT_HED_MODEL
    with _HED_LOCK:
        det = _HED.get(name)
        if det is None:
            det = HEDDetector(name, allow_random_init=allow_random_init)
            _HED[name] = det
        return det


def hed_edges(image, model_name: str | None = None):
    """PIL -> [H, W] float32 soft-edge probabilities, or None when no
    converted HED weights are on this worker (callers degrade to the
    classical heuristic with a logged warning)."""
    from ..weights import MissingWeightsError

    try:
        return get_hed_detector(model_name)(image)
    except MissingWeightsError:
        return None


# --- UperNet segmentation (segmentation preprocessor backend) ---

_SEG: dict[str, "Segmenter"] = {}
_SEG_LOCK = threading.Lock()

DEFAULT_SEGMENTATION_MODEL = "openmmlab/upernet-convnext-small"
_SEG_SIZE = 512


class Segmenter:
    """Resident UperNet+ConvNeXt segmenter (the learned detector the
    reference's `segmentation` annotator runs,
    swarm/pre_processors/controlnet.py:122-141). Converted weights only —
    construction raises when the checkpoint is absent so the preprocessor
    can fall back to its classical stand-in (and flag the job degraded)."""

    def __init__(self, model_name: str = DEFAULT_SEGMENTATION_MODEL):
        import json

        import jax
        import jax.numpy as jnp

        from ..models.conversion import (
            checked_converted,
            convert_upernet,
            load_torch_state_dict,
        )
        from ..models.segmentation import (
            UperNetSegmenter,
            upernet_config_from_json,
        )
        from ..weights import MissingWeightsError, model_dir_for

        model_dir = model_dir_for(model_name)
        if model_dir is None:
            raise MissingWeightsError(
                f"segmentation weights for '{model_name}' are not present"
            )
        p = model_dir / "config.json"
        cfg = upernet_config_from_json(
            json.loads(p.read_text()) if p.is_file() else None
        )
        self.config = cfg
        on_tpu = jax.default_backend() == "tpu"
        self.dtype = jnp.bfloat16 if on_tpu else jnp.float32
        self.model = UperNetSegmenter(cfg, dtype=self.dtype)
        converted = convert_upernet(load_torch_state_dict(model_dir))
        params = checked_converted(
            self.model, (jnp.zeros((1, 64, 64, 3)),), converted,
            "segmentation", jax.random.key(0),
        )
        cast = lambda x: jnp.asarray(x, self.dtype)
        self.params = jax.tree_util.tree_map(cast, params)
        self._program = jax.jit(
            lambda p, px: self.model.apply({"params": p}, px).argmax(-1)
        )

    def __call__(self, image) -> np.ndarray:
        """PIL -> [H, W] int32 ADE label map at the original size."""
        import jax.numpy as jnp
        from PIL import Image

        w, h = image.size
        rgb = image.convert("RGB").resize((_SEG_SIZE, _SEG_SIZE), Image.BILINEAR)
        px = (np.asarray(rgb, np.float32) / 255.0 - _MEAN) / _STD
        labels = np.asarray(
            self._program(self.params, jnp.asarray(px[None], self.dtype)),
            np.int32,
        )[0]
        return np.asarray(
            Image.fromarray(labels.astype(np.uint8)).resize(
                (w, h), Image.NEAREST
            ),
            np.int32,
        )


def get_segmenter(model_name: str | None = None):
    """The resident segmenter, or None when no converted checkpoint is
    available (callers fall back to the classical stand-in)."""
    from ..weights import MissingWeightsError

    name = model_name or DEFAULT_SEGMENTATION_MODEL
    with _SEG_LOCK:
        return _cached_detector(
            _SEG, name, lambda: Segmenter(name), "segmentation",
            (MissingWeightsError, FileNotFoundError, OSError),
        )


# --- M-LSD line detector (mlsd preprocessor backend) ---

_MLSD: dict[str, "MLSDDetector"] = {}
_MLSD_LOCK = threading.Lock()

DEFAULT_MLSD_MODEL = "lllyasviel/Annotators"
_MLSD_SIZE = 512  # upstream processing canvas; TP map comes out at /2


class MLSDDetector:
    """Resident MobileV2-MLSD-Large line detector (the learned annotator
    the reference's `mlsd` preprocessor runs, swarm/pre_processors/
    controlnet.py:31). BatchNorms fold at conversion; the TP-map decode
    (sigmoid center NMS + displacement endpoints) runs host-side like the
    pose PAF grouping."""

    def __init__(self, model_name: str = DEFAULT_MLSD_MODEL):
        import jax
        import jax.numpy as jnp

        from ..models.mlsd import MLSDNet
        from ..settings import load_settings

        self.model_name = model_name
        on_tpu = jax.default_backend() == "tpu"
        self.dtype = jnp.bfloat16 if on_tpu else jnp.float32
        self.model = MLSDNet(dtype=self.dtype)
        root = Path(load_settings().model_root_dir).expanduser()
        params = self._load_converted(root / model_name)
        cast = lambda x: jnp.asarray(x, self.dtype)
        self.params = jax.tree_util.tree_map(cast, params)
        self._program = jax.jit(
            lambda p, px: self.model.apply({"params": p}, px)
        )

    @staticmethod
    def _load_converted(model_dir: Path):
        """The Annotators repo ships mlsd_large_512_fp32.pth (a raw torch
        pickle); accept any mlsd*.pth / safetensors layout present."""
        from ..models.conversion import convert_mlsd, load_torch_state_dict

        if not model_dir.is_dir():
            raise FileNotFoundError(f"no checkpoint directory {model_dir}")
        try:
            return convert_mlsd(load_torch_state_dict(model_dir))
        except (FileNotFoundError, KeyError):
            # KeyError: the shared Annotators dir can hold OTHER
            # annotators' safetensors — fall through to the mlsd .pth
            for p in sorted(model_dir.glob("*mlsd*.pth")):
                import torch

                sd = torch.load(str(p), map_location="cpu",
                                weights_only=True)
                return convert_mlsd({k: v.numpy() for k, v in sd.items()})
            raise

    def __call__(self, image, score_thr: float = 0.1,
                 dist_thr: float = 0.1) -> np.ndarray:
        """PIL -> [N, 4] float32 line segments (x1, y1, x2, y2) in the
        ORIGINAL image's pixel coordinates."""
        import cv2
        import jax.numpy as jnp
        from PIL import Image

        w, h = image.size
        rgb = image.convert("RGB").resize(
            (_MLSD_SIZE, _MLSD_SIZE), Image.BILINEAR
        )
        arr = np.concatenate(
            [np.asarray(rgb, np.float32),
             np.ones((_MLSD_SIZE, _MLSD_SIZE, 1), np.float32)],
            axis=-1,
        ) / 127.5 - 1.0
        tp = np.asarray(
            self._program(self.params, jnp.asarray(arr[None], self.dtype))
            .astype(jnp.float32)
        )[0]
        center, disp = tp[:, :, 0], tp[:, :, 1:5]
        heat = 1.0 / (1.0 + np.exp(-center))
        # 5x5 NMS window to match upstream controlnet_aux's pred_lines
        # decode (max-pool ksize=5) — a 3x3 window kept near-duplicate
        # peaks the reference annotator suppresses (ADVICE r04)
        hmax = cv2.dilate(heat, np.ones((5, 5), np.uint8))
        heat = np.where(heat >= hmax, heat, 0.0)
        flat = heat.ravel()
        top = np.argsort(flat)[::-1][:200]
        ys, xs = np.unravel_index(top, heat.shape)
        lines = []
        for y, x in zip(ys, xs):
            if heat[y, x] <= score_thr:
                break
            x1 = x + disp[y, x, 0]
            y1 = y + disp[y, x, 1]
            x2 = x + disp[y, x, 2]
            y2 = y + disp[y, x, 3]
            if np.hypot(x2 - x1, y2 - y1) > dist_thr:
                lines.append((x1, y1, x2, y2))
        if not lines:
            return np.zeros((0, 4), np.float32)
        # TP map is at canvas/2; scale 2x to the canvas then to the
        # original image
        seg = np.asarray(lines, np.float32) * 2.0
        seg[:, 0::2] *= w / _MLSD_SIZE
        seg[:, 1::2] *= h / _MLSD_SIZE
        return seg


def get_mlsd_detector(model_name: str | None = None):
    """The resident MLSD detector, or None when no converted checkpoint
    is available (callers fall back to the Hough stand-in)."""
    from ..weights import MissingWeightsError

    name = model_name or DEFAULT_MLSD_MODEL
    with _MLSD_LOCK:
        return _cached_detector(
            _MLSD, name, lambda: MLSDDetector(name), "MLSD",
            (MissingWeightsError, FileNotFoundError, OSError, KeyError),
        )


# --- LineArt generator (lineart preprocessor backend) ---

_LINEART: dict[str, "LineartDetector"] = {}
_LINEART_LOCK = threading.Lock()

DEFAULT_LINEART_MODEL = "lllyasviel/Annotators"
_LINEART_SIZE = 512


class LineartDetector:
    """Resident informative-drawings sketch generator (the learned
    annotator the reference's `lineart` preprocessor runs,
    swarm/pre_processors/controlnet.py:43)."""

    def __init__(self, model_name: str = DEFAULT_LINEART_MODEL):
        import jax
        import jax.numpy as jnp

        from ..models.lineart import LineartGenerator
        from ..settings import load_settings

        self.model_name = model_name
        on_tpu = jax.default_backend() == "tpu"
        self.dtype = jnp.bfloat16 if on_tpu else jnp.float32
        root = Path(load_settings().model_root_dir).expanduser()
        cfg, params = self._load_converted(root / model_name)
        self.model = LineartGenerator(cfg, dtype=self.dtype)
        cast = lambda x: jnp.asarray(x, self.dtype)
        self.params = jax.tree_util.tree_map(cast, params)
        self._program = jax.jit(
            lambda p, px: self.model.apply({"params": p}, px)
        )

    @staticmethod
    def _load_converted(model_dir: Path):
        """sk_model.pth (fine, the reference's default); sk_model2.pth is
        the coarse variant of the same graph."""
        from ..models.conversion import convert_lineart, load_torch_state_dict

        if not model_dir.is_dir():
            raise FileNotFoundError(f"no checkpoint directory {model_dir}")
        try:
            return convert_lineart(load_torch_state_dict(model_dir))
        except (FileNotFoundError, KeyError):
            for p in sorted(model_dir.glob("sk_model*.pth")):
                import torch

                sd = torch.load(str(p), map_location="cpu",
                                weights_only=True)
                return convert_lineart(
                    {k: v.numpy() for k, v in sd.items()}
                )
            raise FileNotFoundError(f"no sk_model*.pth under {model_dir}")

    def __call__(self, image) -> np.ndarray:
        """PIL -> [H, W] float32 stroke intensity in [0, 1] (white lines
        on black, the conditioning convention — already inverted)."""
        import jax.numpy as jnp
        from PIL import Image

        original = image.size
        rgb = image.convert("RGB").resize(
            (_LINEART_SIZE, _LINEART_SIZE), Image.BILINEAR
        )
        px = jnp.asarray(
            np.asarray(rgb, np.float32)[None] / 255.0, self.dtype
        )
        sketch = np.asarray(
            self._program(self.params, px).astype(jnp.float32)
        )[0, :, :, 0]
        inverted = 1.0 - sketch  # dark-on-white sketch -> white-on-black
        return np.asarray(
            Image.fromarray((inverted * 255).astype(np.uint8)).resize(
                original, Image.BILINEAR
            ),
            np.float32,
        ) / 255.0


def get_lineart_detector(model_name: str | None = None):
    """The resident LineArt generator, or None when no converted
    checkpoint is available (callers fall back to the DoG stand-in)."""
    from ..weights import MissingWeightsError

    name = model_name or DEFAULT_LINEART_MODEL
    with _LINEART_LOCK:
        return _cached_detector(
            _LINEART, name, lambda: LineartDetector(name), "LineArt",
            (MissingWeightsError, FileNotFoundError, OSError, KeyError),
        )


# --- PiDiNet soft-edge (softedge preprocessor backend) ---

_PIDI: dict[str, "PidinetDetector"] = {}
_PIDI_LOCK = threading.Lock()

DEFAULT_PIDINET_MODEL = "lllyasviel/Annotators"
_PIDI_SIZE = 512


class PidinetDetector:
    """Resident table5 PiDiNet (the learned detector the reference's
    `softedge` preprocessor runs, swarm/pre_processors/controlnet.py:56).
    Pixel-difference kernels re-parameterize to vanilla convs at
    conversion."""

    def __init__(self, model_name: str = DEFAULT_PIDINET_MODEL):
        import jax
        import jax.numpy as jnp

        from ..models.pidinet import PiDiNet
        from ..settings import load_settings

        self.model_name = model_name
        on_tpu = jax.default_backend() == "tpu"
        self.dtype = jnp.bfloat16 if on_tpu else jnp.float32
        self.model = PiDiNet(dtype=self.dtype)
        root = Path(load_settings().model_root_dir).expanduser()
        params = self._load_converted(root / model_name)
        cast = lambda x: jnp.asarray(x, self.dtype)
        self.params = jax.tree_util.tree_map(cast, params)
        self._program = jax.jit(
            lambda p, px: self.model.apply({"params": p}, px)
        )

    @staticmethod
    def _load_converted(model_dir: Path):
        from ..models.conversion import (
            convert_pidinet,
            load_torch_state_dict,
        )

        if not model_dir.is_dir():
            raise FileNotFoundError(f"no checkpoint directory {model_dir}")
        try:
            return convert_pidinet(load_torch_state_dict(model_dir))
        except (FileNotFoundError, KeyError):
            for p in sorted(model_dir.glob("*pidinet*.pth")):
                import torch

                sd = torch.load(str(p), map_location="cpu",
                                weights_only=True)
                if isinstance(sd, dict) and "state_dict" in sd:
                    sd = sd["state_dict"]
                return convert_pidinet(
                    {k: np.asarray(v) for k, v in sd.items()}
                )
            raise FileNotFoundError(
                f"no *pidinet*.pth under {model_dir}"
            )

    def __call__(self, image) -> np.ndarray:
        """PIL -> [H, W] float32 soft-edge probabilities in [0, 1]."""
        import jax.numpy as jnp
        from PIL import Image

        original = image.size
        rgb = image.convert("RGB").resize(
            (_PIDI_SIZE, _PIDI_SIZE), Image.BILINEAR
        )
        px = jnp.asarray(
            np.asarray(rgb, np.float32)[None] / 255.0, self.dtype
        )
        edge = np.asarray(
            self._program(self.params, px).astype(jnp.float32)
        )[0, :, :, 0]
        return np.asarray(
            Image.fromarray((edge * 255).astype(np.uint8)).resize(
                original, Image.BILINEAR
            ),
            np.float32,
        ) / 255.0


def get_pidinet_detector(model_name: str | None = None):
    """The resident PiDiNet, or None when no converted checkpoint is
    available (softedge falls back to HED, then the classical
    heuristic)."""
    from ..weights import MissingWeightsError

    name = model_name or DEFAULT_PIDINET_MODEL
    with _PIDI_LOCK:
        return _cached_detector(
            _PIDI, name, lambda: PidinetDetector(name), "PiDiNet",
            (MissingWeightsError, FileNotFoundError, OSError, KeyError),
        )


# --- ZoeDepth metric depth (zoe preprocessor backend) ---

_ZOE: dict[str, "ZoeEstimator"] = {}
_ZOE_LOCK = threading.Lock()

DEFAULT_ZOE_MODEL = "Intel/zoedepth-nyu"


class ZoeEstimator:
    """Resident ZoeDepth (the metric-depth model the reference's
    `zoe depth` preprocessor runs, swarm/pre_processors/zoe_depth.py:8-13)
    — BEiT backbone + metric-bins head with EXACT transformers parity
    (tests/test_zoedepth.py). Serves a fixed square canvas equal to the
    trained window so the relative-position tables index directly."""

    def __init__(self, model_name: str = DEFAULT_ZOE_MODEL):
        import json

        import jax
        import jax.numpy as jnp

        from ..models.conversion import convert_zoedepth, load_torch_state_dict
        from ..models.zoedepth import ZoeDepthModel
        from ..settings import load_settings

        self.model_name = model_name
        root = Path(load_settings().model_root_dir).expanduser()
        model_dir = root / model_name
        if not model_dir.is_dir():
            raise FileNotFoundError(f"no checkpoint directory {model_dir}")
        cfg_json = {}
        p = model_dir / "config.json"
        if p.is_file():
            cfg_json = json.loads(p.read_text())
        cfg, params = convert_zoedepth(
            load_torch_state_dict(model_dir), cfg_json
        )
        self.config = cfg
        on_tpu = jax.default_backend() == "tpu"
        self.dtype = jnp.bfloat16 if on_tpu else jnp.float32
        self.model = ZoeDepthModel(cfg, dtype=self.dtype)
        cast = lambda x: jnp.asarray(x, self.dtype)
        self.params = jax.tree_util.tree_map(cast, params)
        self._program = jax.jit(
            lambda p, px: self.model.apply({"params": p}, px)
        )

    def __call__(self, image) -> np.ndarray:
        """PIL -> [H, W] float32 metric depth (meters) at the ORIGINAL
        canvas."""
        import jax.numpy as jnp
        from PIL import Image

        size = self.config.image_size
        original = image.size
        rgb = image.convert("RGB").resize((size, size), Image.BICUBIC)
        arr = (np.asarray(rgb, np.float32) / 255.0 - 0.5) / 0.5
        depth = np.asarray(
            self._program(
                self.params, jnp.asarray(arr[None], self.dtype)
            ).astype(jnp.float32)
        )[0]
        return np.asarray(
            Image.fromarray(depth, mode="F").resize(
                original, Image.BICUBIC
            ),
            np.float32,
        )


def get_zoe_estimator(model_name: str | None = None):
    """The resident ZoeDepth, or None when no converted checkpoint is
    available (zoe falls back to the DPT stand-in, flagged degraded)."""
    from ..weights import MissingWeightsError

    name = model_name or DEFAULT_ZOE_MODEL
    with _ZOE_LOCK:
        return _cached_detector(
            _ZOE, name, lambda: ZoeEstimator(name), "ZoeDepth",
            (MissingWeightsError, FileNotFoundError, OSError, KeyError,
             ValueError),
        )
