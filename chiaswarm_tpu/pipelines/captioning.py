"""BLIP-style captioning/VQA (reference swarm/captioning/caption_image.py)."""

from __future__ import annotations


def caption_image(image, model_name: str, prompt=None, processor_type=None, model_type=None) -> str:
    raise Exception(
        f"img2txt is not yet available on this worker (model {model_name})."
    )
