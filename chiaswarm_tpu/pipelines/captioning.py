"""BLIP captioning / VQA pipeline (reference swarm/captioning/caption_image.py).

Reference behavior: per-job `from_pretrained` of transformers BLIP classes
named in the job JSON (caption_image.py:12-17), conditional captioning when
a prompt rides along (:21-26). TPU redesign:

- one resident Flax module pair per model (vision ViT + BERT-style causal
  decoder, models/blip.py), weights converted once from the HF safetensors
  (models/conversion.py convert_blip) and kept on-device;
- the vision encode is one jitted program; the greedy decode is a jitted
  fixed-length `lax.scan` (static shapes — XLA-friendly, no per-token
  Python), cached per prompt-prefix length bucket;
- prompt-conditioned captioning == the reference's conditional branch: the
  prompt becomes the decode prefix after [DEC].
"""

from __future__ import annotations

import logging
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

from ..models.bert_tokenizer import HashBertTokenizer, load_bert_tokenizer
from ..models.blip import TINY_BLIP, BlipConfig, TextDecoder, VisionEncoder, greedy_decode
from ..parallel.mesh import make_mesh, replicated
from ..registry import register_family
from ..settings import load_settings
from ..weights import MissingWeightsError, is_test_model, require_weights_present

logger = logging.getLogger(__name__)

# CLIP normalization constants (BLIP's image preprocessor uses them too)
_IMAGE_MEAN = np.asarray([0.48145466, 0.4578275, 0.40821073], np.float32)
_IMAGE_STD = np.asarray([0.26862954, 0.26130258, 0.27577711], np.float32)


def _load_special_tokens(model_dir: Path | None) -> dict:
    """Special-token table emitted at conversion (initialize.py) — the
    authoritative ids for a converted checkpoint; {} when absent."""
    import json

    if model_dir is None:
        return {}
    p = Path(model_dir) / "special_tokens.json"
    if not p.is_file():
        return {}
    try:
        return {k: int(v) for k, v in json.loads(p.read_text()).items()}
    except (OSError, ValueError):
        logger.warning("unreadable special_tokens.json under %s", model_dir)
        return {}


def _blip_configs(model_name: str) -> BlipConfig:
    name = model_name.lower()
    if is_test_model(model_name):
        return TINY_BLIP
    if "large" in name:
        # blip-image-captioning-large: ViT-L/16 vision tower, same BERT text
        # side (cross-attn k/v project 1024 -> 768)
        return BlipConfig(vision_hidden=1024, vision_layers=24, vision_heads=16)
    return BlipConfig()


class CaptionPipeline:
    """One resident BLIP bundle per (model, slice) — lives in the same
    registry as the diffusion families (LRU eviction, per-key build locks,
    chipset placement) rather than a private cache."""

    def __init__(self, model_name: str, chipset=None,
                 allow_random_init: bool = False):
        self.model_name = model_name
        self.chipset = chipset
        self.config = _blip_configs(model_name)
        # VQA checkpoints add a question encoder; the answer decoder then
        # cross-attends the encoded question instead of the raw image
        # (HF BlipForQuestionAnswering, reference caption_image.py:21-26)
        self.vqa = "vqa" in model_name.lower()
        on_tpu = jax.default_backend() == "tpu"
        self.dtype = jnp.bfloat16 if on_tpu else jnp.float32
        self.vision = VisionEncoder(self.config, dtype=self.dtype)
        self.decoder = TextDecoder(self.config, dtype=self.dtype)
        if self.vqa:
            from ..models.blip import TextEncoder

            self.question_encoder = TextEncoder(self.config, dtype=self.dtype)
        self.mesh = (
            chipset.mesh() if chipset is not None else make_mesh(jax.devices()[:1])
        )

        root = Path(load_settings().model_root_dir).expanduser()
        model_dir = root / model_name
        t0 = time.perf_counter()
        # converted checkpoints carry their special-token ids (emitted by
        # initialize.py from vocab.txt); config constants are the fallback
        import dataclasses

        toks = _load_special_tokens(model_dir if model_dir.is_dir() else None)
        overrides = {
            k: toks[k]
            for k in ("bos_token_id", "eos_token_id", "pad_token_id")
            if k in toks
        }
        if overrides:
            self.config = dataclasses.replace(self.config, **overrides)
        self.cls_token_id = toks.get("cls_token_id")
        self.sep_token_id = toks.get("sep_token_id")
        self.params = self._load_params(model_dir if model_dir.is_dir() else None,
                                        allow_random_init)
        self.tokenizer = load_bert_tokenizer(
            model_dir if model_dir.is_dir() else None, self.config.vocab_size
        )
        if self.cls_token_id is None:
            vocab = getattr(self.tokenizer, "vocab", None)
            if vocab:
                self.cls_token_id = vocab.get("[CLS]")
                self.sep_token_id = vocab.get("[SEP]")
        if self._real_weights and isinstance(self.tokenizer, HashBertTokenizer):
            # real weights decoded through the hash stand-in would emit
            # garbage token strings as a "successful" job — fail loudly
            raise MissingWeightsError(
                f"model '{model_name}' has converted weights but no "
                f"vocab.txt under {model_dir}; captions cannot be decoded. "
                f"Re-download the model including its tokenizer files."
            )
        logger.info("%s caption pipeline resident in %.1fs", model_name,
                    time.perf_counter() - t0)

        self._encode_program = jax.jit(
            lambda p, px: self.vision.apply({"params": p}, px)
        )
        self._decode_programs: dict[int, callable] = {}

    def _load_params(self, model_dir: Path | None, allow_random_init: bool):
        self._real_weights = False
        if model_dir is not None:
            try:
                from ..models.conversion import convert_blip, load_torch_state_dict

                state = load_torch_state_dict(model_dir)
                params = convert_blip(state)
                if self.vqa and not params.get("qenc"):
                    # a VQA checkpoint without text_encoder weights would
                    # answer with a random-init question encoder — refuse
                    raise MissingWeightsError(
                        f"checkpoint under {model_dir} has no text_encoder "
                        f"(question encoder) weights; '{self.model_name}' "
                        "cannot serve VQA from it. Re-download the model."
                    )
                if not self.vqa:
                    params.pop("qenc", None)
                if params["vision"] and params["text"]:
                    self._check_converted_shapes(params, model_dir)
                    self._real_weights = True
                    cast = lambda x: jnp.asarray(x, self.dtype)
                    params = jax.tree_util.tree_map(cast, params)
                    return jax.device_put(params, replicated(self.mesh))
            except FileNotFoundError:
                pass
        require_weights_present(self.model_name, model_dir, allow_random_init)
        import zlib

        cfg = self.config
        rng = jax.random.key(zlib.crc32(self.model_name.encode()))
        k1, k2 = jax.random.split(rng)
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            n_patches = (cfg.image_size // cfg.patch_size) ** 2
            vision = self.vision.init(
                k1, jnp.zeros((1, cfg.image_size, cfg.image_size, 3))
            )["params"]
            # VQA: the answer decoder's cross-attention context is the
            # question states [*, L, text_hidden], not the image embeds
            ctx_dim = cfg.text_hidden if self.vqa else cfg.vision_hidden
            ctx_len = cfg.max_caption_len if self.vqa else n_patches + 1
            text = self.decoder.init(
                k2,
                jnp.zeros((1, cfg.max_caption_len), jnp.int32),
                jnp.zeros((1, ctx_len, ctx_dim)),
            )["params"]
            tree = {"vision": vision, "text": text}
            if self.vqa:
                tree["qenc"] = self.question_encoder.init(
                    jax.random.fold_in(rng, 2),
                    jnp.zeros((1, cfg.max_caption_len), jnp.int32),
                    jnp.zeros((1, n_patches + 1, cfg.vision_hidden)),
                )["params"]
        cast = lambda x: jnp.asarray(x, self.dtype)
        params = jax.tree_util.tree_map(cast, tree)
        return jax.device_put(params, replicated(self.mesh))

    def _check_converted_shapes(self, params: dict, model_dir: Path) -> None:
        """Cheap eval_shape validation at residency time: a checkpoint whose
        geometry doesn't match the supported config fails cleanly here, not
        with an opaque einsum error inside jit mid-job."""
        from ..models.conversion import assert_tree_shapes_match

        cfg = self.config
        n_patches = (cfg.image_size // cfg.patch_size) ** 2
        try:
            vision_exp = jax.eval_shape(
                self.vision.init, jax.random.key(0),
                jnp.zeros((1, cfg.image_size, cfg.image_size, 3)),
            )["params"]
            assert_tree_shapes_match(params["vision"], vision_exp, prefix="vision")
            # VQA: the answer decoder cross-attends question states
            # [*, L, text_hidden]; captioning cross-attends image embeds
            ctx_dim = cfg.text_hidden if self.vqa else cfg.vision_hidden
            ctx_len = cfg.max_caption_len if self.vqa else n_patches + 1
            text_exp = jax.eval_shape(
                self.decoder.init, jax.random.key(0),
                jnp.zeros((1, cfg.max_caption_len), jnp.int32),
                jnp.zeros((1, ctx_len, ctx_dim)),
            )["params"]
            assert_tree_shapes_match(params["text"], text_exp, prefix="text")
            if self.vqa:
                qenc_exp = jax.eval_shape(
                    self.question_encoder.init, jax.random.key(0),
                    jnp.zeros((1, cfg.max_caption_len), jnp.int32),
                    jnp.zeros((1, n_patches + 1, cfg.vision_hidden)),
                )["params"]
                assert_tree_shapes_match(params["qenc"], qenc_exp, prefix="qenc")
        except ValueError as e:
            raise MissingWeightsError(
                f"checkpoint under {model_dir} does not match the supported "
                f"BLIP architecture for '{self.model_name}': {e}"
            ) from None

    def _decode_program(self, prefix_len: int):
        if prefix_len in self._decode_programs:
            return self._decode_programs[prefix_len]
        cfg = self.config

        def apply(params, ids, image_embeds):
            return self.decoder.apply({"params": params}, ids, image_embeds)

        def run(text_params, image_embeds, prefix_ids):
            return greedy_decode(
                apply, text_params, image_embeds, cfg,
                prefix_ids=prefix_ids if prefix_len else None,
            )

        program = jax.jit(run)
        self._decode_programs[prefix_len] = program
        return program

    def _preprocess(self, image) -> np.ndarray:
        from PIL import Image

        size = self.config.image_size
        image = image.convert("RGB")
        if image.size != (size, size):
            image = image.resize((size, size), Image.BICUBIC)
        arr = np.asarray(image, np.float32) / 255.0
        return ((arr - _IMAGE_MEAN) / _IMAGE_STD)[None]

    def run(self, image, prompt: str | None = None) -> tuple[str, dict]:
        params = self.params
        if params is None:
            raise Exception(
                f"caption pipeline {self.model_name} was evicted; resubmit"
            )
        cfg = self.config
        t0 = time.perf_counter()
        pixels = jnp.asarray(self._preprocess(image), self.dtype)
        embeds = self._encode_program(params["vision"], pixels)

        if self.vqa:
            return self._run_vqa(params, embeds, prompt, t0)

        prefix_ids = None
        prefix_len = 0
        if prompt:
            enc = self.tokenizer.encode(prompt)[: cfg.max_caption_len - 2]
            prefix_len = len(enc)
            prefix_ids = jnp.asarray([enc], jnp.int32) if enc else None
            prefix_len = 0 if prefix_ids is None else prefix_len
        ids = self._decode_program(prefix_len)(
            params["text"], embeds,
            prefix_ids if prefix_ids is not None else jnp.zeros((1, 0), jnp.int32),
        )
        text = self._decode_ids(np.asarray(jax.block_until_ready(ids))[0])
        config = {
            "model": self.model_name,
            "prompt_conditioned": bool(prefix_len),
            "timings": {"caption_s": round(time.perf_counter() - t0, 3)},
        }
        return text, config

    def _decode_ids(self, ids: np.ndarray) -> str:
        """[max_len] greedy ids -> text: strip [DEC], truncate at EOS on
        the host (the scan is fixed-length for XLA)."""
        body = ids[1:]
        eos = np.nonzero(body == self.config.eos_token_id)[0]
        if eos.size:
            body = body[: eos[0]]
        return self.tokenizer.decode(body)

    def _run_vqa(self, params, image_embeds, prompt, t0) -> tuple[str, dict]:
        """Question -> encoded-against-image states -> greedy answer."""
        if not prompt:
            raise ValueError(
                "BLIP VQA requires a question; send it as the job prompt."
            )
        cfg = self.config
        enc = self.tokenizer.encode(prompt)
        if self.cls_token_id is not None and self.sep_token_id is not None:
            # HF BlipProcessor parity: the question reaches the encoder as
            # [CLS] q [SEP] (HF's generate passes it through unchanged —
            # no [ENC] substitution; see models/blip.py TextEncoder note)
            enc = (
                [self.cls_token_id]
                + enc[: cfg.max_caption_len - 2]
                + [self.sep_token_id]
            )
        else:
            enc = enc[: cfg.max_caption_len - 1]
        q_ids = np.full((1, cfg.max_caption_len), cfg.pad_token_id, np.int32)
        q_ids[0, : len(enc)] = enc
        q_mask = np.zeros((1, cfg.max_caption_len), np.float32)
        q_mask[0, : len(enc)] = 1.0
        program = self._vqa_program()
        ids = np.asarray(
            jax.block_until_ready(
                program(params, jnp.asarray(q_ids), jnp.asarray(q_mask),
                        image_embeds)
            )
        )[0]
        text = self._decode_ids(ids)
        config = {
            "model": self.model_name,
            "vqa": True,
            "timings": {"caption_s": round(time.perf_counter() - t0, 3)},
        }
        return text, config

    def _vqa_program(self):
        if "vqa" in self._decode_programs:
            return self._decode_programs["vqa"]
        cfg = self.config
        qenc = self.question_encoder
        decoder = self.decoder

        def run(params, q_ids, q_mask, image_embeds):
            question_states = qenc.apply(
                {"params": params["qenc"]}, q_ids, image_embeds,
                attention_mask=q_mask,
            )

            def apply(text_params, ids, context):
                # padded question positions are masked out of the answer
                # decoder's cross-attention
                return decoder.apply(
                    {"params": text_params}, ids, context, context_mask=q_mask
                )

            return greedy_decode(apply, params["text"], question_states, cfg)

        program = jax.jit(run)
        self._decode_programs["vqa"] = program
        return program

    def release(self):
        self.params = None
        self._decode_programs.clear()


@register_family("blip")
def _build_blip(model_name, chipset, **variant):
    return CaptionPipeline(model_name, chipset, **variant)


def reject_unsupported_blip(model_name: str, model_type: str | None) -> None:
    """VQA routes by MODEL NAME (CaptionPipeline builds the question
    encoder for 'vqa' names); a VQA-typed job whose model name doesn't
    identify as VQA would silently serve the captioning stack, so it
    still fails cleanly."""
    if model_type == "BlipForQuestionAnswering" and "vqa" not in model_name.lower():
        raise Exception(
            f"BlipForQuestionAnswering was requested but '{model_name}' is "
            f"not a VQA checkpoint (use Salesforce/blip-vqa-base)."
        )


def get_caption_pipeline(model_name: str, chipset=None,
                         model_type: str | None = None) -> CaptionPipeline:
    from ..registry import get_pipeline

    reject_unsupported_blip(model_name, model_type)
    return get_pipeline(
        model_name, pipeline_type="BlipForConditionalGeneration", chipset=chipset
    )


def caption_image(image, model_name: str, prompt=None, processor_type=None,
                  model_type=None, chipset=None) -> str:
    """Reference-signature entry (swarm/captioning/caption_image.py:12).

    processor_type is the reference's reflection class name for the image
    processor; the registry design resolves preprocessing by model family,
    so it is accepted and ignored. model_type gates unsupported variants.
    """
    pipe = get_caption_pipeline(model_name, chipset=chipset, model_type=model_type)
    text, _ = pipe.run(image, prompt=prompt)
    return text
