"""Resident, jitted Stable-Diffusion pipelines (SD1.x / SD2.x / SDXL).

Replaces reference swarm/diffusion/diffusion_func.py:15-167. Key design
inversions for TPU:

- Weights are loaded ONCE per (model, mesh) and stay in HBM; the reference
  runs `from_pretrained` per job (diffusion_func.py:103).
- The whole denoise loop is ONE jitted program: `lax.scan` over steps,
  classifier-free guidance as a batch-of-2N (uncond rows stacked before
  cond rows), scheduler state carried functionally. No Python per step.
- The image batch (CFG-doubled) shards over the ChipSet mesh's `data` axis
  when it divides evenly; otherwise it stays replicated — same program
  either way, XLA inserts the collectives.
- txt2img / img2img / inpaint are modes of one bundle (shared weights),
  where the reference loaded a separate diffusers pipeline class per wire
  name (swarm/job_arguments.py:260-327).

Jitted programs are cached per shape bucket (H, W, steps, batch, scheduler,
mode); `initialize --download`'s analog warms these up ahead of jobs.
"""

from __future__ import annotations

import logging
import threading
import time
import zlib
from collections import OrderedDict
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
from PIL import Image

from .. import costs, embed_cache, programs
from ..models import configs as cfgs
from ..models.clip import CLIPTextEncoder
from ..models.tokenizer import load_tokenizer
from ..models.unet2d import UNet2DConditionModel
from ..models.vae import AutoencoderKL
from ..parallel.mesh import (
    batch_sharding,
    make_mesh,
    repeat_rows,
    replicated,
    stack_rows,
)
from ..registry import register_family
from ..schedulers import get_scheduler
from ..schedulers.common import SchedulerConfig
from ..settings import load_settings
from ..telemetry import Span, counter as telemetry_counter

logger = logging.getLogger(__name__)

# jitted-program cache effectiveness: a "miss" pays a full XLA trace +
# compile; the shape-bucket design lives or dies by this ratio
_COMPILE_CACHE = telemetry_counter(
    "swarm_compile_cache_total",
    "Denoise-program cache lookups by outcome (miss = trace + XLA compile)",
    ("event",),
)

# ISSUE 15 (SW007 headline): the program/runner variant caches gained an
# unbounded growth axis with runtime-delta LoRA — one compiled variant
# per (slot-bucket, rank-bucket, targeted-module-path-set), and the
# path-set fan-out is census-dependent. `program_cache_max` bounds both
# caches per pipeline; evictions (with the compiled executable freed via
# clear_cache) are counted here so a thrashing fleet is visible
_PROGRAM_EVICTED = telemetry_counter(
    "swarm_program_cache_evicted_total",
    "Compiled denoise programs / assembled runners evicted LRU at the "
    "program_cache_max bound, by kind",
    ("kind",),
)

# padded-vs-real rows through run_batched: how much of each coalesced
# pass was real work vs power-of-two padding (batching ROI, per PR 1)
_BATCH_ROWS = telemetry_counter(
    "swarm_batch_pass_rows_total",
    "Image rows through coalesced passes, real vs padding",
    ("kind",),
)

# per-pass slice-geometry accounting (ISSUE 12): one count per denoise
# pass, labelled by the mesh view it ran under — "replicated" (data-only
# mesh, today's coalescing view), "tensorN"/"seqN"/"tensorN_seqM" for
# sharded passes. The class-aware scheduler's whole point is that this
# distribution shifts with the traffic mix.
_SHARDED_PASSES = telemetry_counter(
    "swarm_sharded_passes_total",
    "Denoise passes by slice geometry (replicated | tensorN | seqN ...)",
    ("geometry",),
)

# merged-tree LoRA fallback LRU (each entry pins a FULL UNet copy in
# HBM). Small by design since ISSUE 13: the serving path applies
# adapters as runtime per-row deltas against the ONE resident base tree
# (pipelines/lora_runtime.py + the byte-capped factor cache in
# lora_cache.py); merged trees remain only for adapters the delta
# cannot express
MAX_RESIDENT_LORAS = 2
MAX_RESIDENT_TI = 4
MAX_RESIDENT_VAES = 2
# placed param copies per pipeline beyond the default view: each sharded
# geometry pins ~1/tensor of the model per chip next to the replicated
# copy, so the LRU stays tiny
MAX_RESIDENT_GEOMETRIES = 2


def geometry_label(tensor: int, seq: int) -> str:
    """Canonical metric label for a mesh view (swarm_sharded_passes_total).
    Any data-only view is "replicated" regardless of its data degree —
    the batch shards, the model does not."""
    if tensor <= 1 and seq <= 1:
        return "replicated"
    parts = []
    if tensor > 1:
        parts.append(f"tensor{tensor}")
    if seq > 1:
        parts.append(f"seq{seq}")
    return "_".join(parts)


def load_learned_embeddings(ref) -> list[dict]:
    """Textual-inversion file -> [{"tokens": [alias, ...], "vectors":
    [[k, D] float32, ...]}] groups (aliases share one id run; multiple
    vectors cover SDXL's per-encoder embeds).

    Accepts a direct path, a model-root entry, or a lora-root entry;
    handled formats: diffusers (one key per placeholder token), kohya
    `emb_params`, and the SDXL dual-encoder `clip_l`/`clip_g` layout. The
    file-named formats register both the bare stem and `<stem>` as
    triggers (prompts conventionally use either). Reference behavior
    replaced: diffusers load_textual_inversion per job
    (swarm/diffusion/diffusion_func.py:105-111).
    """
    from safetensors import safe_open

    settings = load_settings()
    candidates: list[Path] = []
    for base in (
        Path(str(ref)).expanduser(),
        Path(settings.model_root_dir).expanduser() / str(ref),
        Path(settings.lora_root_dir).expanduser() / str(ref),
    ):
        if base.is_file():
            candidates.append(base)
        elif base.is_dir():
            candidates.extend(sorted(base.glob("*.safetensors")))
    for f in candidates:
        try:
            with safe_open(str(f), framework="np") as sf:
                state = {k: sf.get_tensor(k) for k in sf.keys()}
        except Exception:  # noqa: BLE001 — try the next candidate
            continue
        if not state:
            continue
        as2d = lambda v: np.atleast_2d(np.asarray(v, np.float32))
        keys = set(state)
        stem_aliases = [f.stem, f"<{f.stem}>"]
        if keys == {"emb_params"}:
            return [{"tokens": stem_aliases,
                     "vectors": [as2d(state["emb_params"])]}]
        if keys <= {"clip_l", "clip_g"} and keys:
            return [{
                "tokens": stem_aliases,
                "vectors": [as2d(v) for v in state.values()],
            }]
        return [
            {"tokens": [token], "vectors": [as2d(v)]}
            for token, v in state.items()
        ]
    raise ValueError(
        f"Could not load textual inversion {ref}: no embedding safetensors "
        f"found (looked at {[str(c) for c in candidates] or 'no candidates'})"
    )



def _config_prediction_type(model_name: str) -> str | None:
    """`prediction_type` from the downloaded scheduler config JSON —
    authoritative over any name heuristic (a v-prediction fine-tune named
    without '768' would otherwise silently get epsilon and produce garbage
    with real weights). None when the checkpoint isn't local."""
    import json
    from pathlib import Path

    from ..settings import load_settings

    try:
        root = Path(load_settings().model_root_dir).expanduser() / model_name
    except Exception:
        return None
    p = root / "scheduler" / "scheduler_config.json"
    if p.is_file():
        try:
            pred = json.loads(p.read_text()).get("prediction_type")
            if pred:
                return str(pred)
        except (OSError, ValueError):
            pass
    return None


def _family_configs(model_name: str):
    """(unet_cfg, [clip_cfgs], vae_cfg, default_size, prediction_type)."""
    import dataclasses

    name = model_name.lower()
    if "tiny" in name:
        if "xl" in name:
            out = (
                cfgs.TINY_XL_UNET,
                [cfgs.TINY_CLIP, cfgs.TINY_CLIP_2],
                cfgs.TINY_VAE,
                64,
                "epsilon",
            )
        else:
            out = (cfgs.TINY_UNET, [cfgs.TINY_CLIP], cfgs.TINY_VAE, 64, "epsilon")
    else:
        family = cfgs.model_family(model_name)
        if family == "sdxl":
            out = (cfgs.SDXL_UNET, [cfgs.SDXL_CLIP_1, cfgs.SDXL_CLIP_2],
                   cfgs.SDXL_VAE, 1024, "epsilon")
        elif family == "sdxl_refiner":
            out = (cfgs.SDXL_REFINER_UNET, [cfgs.SDXL_CLIP_2], cfgs.SDXL_VAE,
                   1024, "epsilon")
        elif family == "sd21":
            # SD2.1-768 is v-prediction; the 512 base is epsilon. The hive
            # sends full model names, so key off the canonical 768 name.
            pred = (
                "v_prediction" if "768" in name or name.endswith("2-1") else "epsilon"
            )
            out = (cfgs.SD21_UNET, [cfgs.SD21_CLIP], cfgs.SD_VAE, 768, pred)
        else:
            out = (cfgs.SD15_UNET, [cfgs.SD15_CLIP], cfgs.SD_VAE, 512, "epsilon")
    unet_cfg, clip_cfgs, vae_cfg, size, pred = out
    cfg_pred = _config_prediction_type(model_name)
    if cfg_pred is not None:
        pred = cfg_pred
    if "pix2pix" in name or "ip2p" in name:
        # edit-tuned checkpoints (timbrooks/instruct-pix2pix and the SDXL
        # variant, reference swarm/job_arguments.py:299-305) take the start-
        # image latents on the channel dim: 8-channel UNet input
        unet_cfg = dataclasses.replace(
            unet_cfg, in_channels=2 * vae_cfg.latent_channels
        )
    elif "inpaint" in name:
        # dedicated inpaint checkpoints (runwayml/stable-diffusion-inpainting
        # family): 9-channel input = latents + mask + masked-image latents
        unet_cfg = dataclasses.replace(
            unet_cfg, in_channels=2 * vae_cfg.latent_channels + 1
        )
    return unet_cfg, clip_cfgs, vae_cfg, size, pred


def _pil_to_array(image: Image.Image, width: int, height: int) -> np.ndarray:
    """PIL -> float32 [H, W, 3] in [-1, 1], resized to the job canvas."""
    image = image.convert("RGB")
    if image.size != (width, height):
        image = image.resize((width, height), Image.LANCZOS)
    arr = np.asarray(image, np.float32) / 127.5 - 1.0
    return arr


def _mask_to_latent_array(mask: Image.Image, width: int, height: int,
                          factor: int) -> np.ndarray:
    """Mask PIL -> float32 [H/f, W/f, 1]; 1 = repaint, 0 = keep."""
    mask = mask.convert("L").resize((width // factor, height // factor), Image.NEAREST)
    return (np.asarray(mask, np.float32)[..., None] / 255.0 > 0.5).astype(np.float32)


def dummy_added_cond(unet_cfg, b: int):
    """Zero SDXL micro-conditioning inputs for init/eval_shape; None for SD."""
    if unet_cfg.addition_embed_dim <= 0:
        return None
    pooled_dim = unet_cfg.addition_embed_dim - 6 * unet_cfg.addition_time_embed_dim
    return {
        "text_embeds": jnp.zeros((b, pooled_dim)),
        "time_ids": jnp.zeros((b, 6)),
    }


def _to_pil(batch: np.ndarray) -> list[Image.Image]:
    """[B, H, W, 3] uint8 (or legacy [-1, 1] float) -> PIL images."""
    arr = np.asarray(batch)
    if arr.dtype == np.uint8:  # quantized on device: 4x smaller transfer
        return [Image.fromarray(img) for img in arr]
    arr = np.clip(arr.astype(np.float32) * 0.5 + 0.5, 0.0, 1.0)
    return [Image.fromarray((img * 255).round().astype(np.uint8)) for img in arr]


class SDPipeline:
    """One model family resident on one ChipSet; serves all SD wire names."""

    # the chunked runner's boundary doubles as a checkpoint/resume seam
    # (ISSUE 18); workflows gate the checkpoint kwargs on this attribute
    # the same way geometry kwargs gate on resolve_geometry
    supports_checkpoint = True

    def __init__(self, model_name: str, chipset=None, dtype=None,
                 allow_random_init: bool = False):
        self.model_name = model_name
        self.chipset = chipset
        self.allow_random_init = allow_random_init
        unet_cfg, clip_cfgs, vae_cfg, self.default_size, pred = _family_configs(
            model_name
        )
        self.prediction_type = pred
        if dtype is None:
            dtype = jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
        self.dtype = dtype
        self.is_xl = unet_cfg.addition_embed_dim > 0

        self.unet = UNet2DConditionModel(unet_cfg, dtype=dtype)
        self.text_encoders = [CLIPTextEncoder(c, dtype=dtype) for c in clip_cfgs]
        self.vae = AutoencoderKL(vae_cfg, dtype=dtype)

        # VAE spatial reduction: one 2x downsample per block transition
        self.latent_factor = 2 ** (len(vae_cfg.block_out_channels) - 1)
        self.latent_channels = vae_cfg.latent_channels
        # edit-tuned (instruct-pix2pix) checkpoints concat start-image latents
        # on the channel dim; dedicated inpaint checkpoints add a mask plane;
        # detect both by architecture, not by name
        self.is_pix2pix = unet_cfg.in_channels == 2 * vae_cfg.latent_channels
        self.is_inpaint_unet = (
            unet_cfg.in_channels == 2 * vae_cfg.latent_channels + 1
        )
        self.mesh = (
            chipset.mesh() if chipset is not None else make_mesh(jax.devices()[:1])
        )
        self.data_parts = self.mesh.shape.get("data", 1)
        self.tensor_parts = self.mesh.shape.get("tensor", 1)
        # the slice's construction-time view; per-pass `geometry` requests
        # resolve against it (default_geometry passes run exactly the
        # pre-ISSUE-12 programs, byte for byte)
        self.default_geometry = (self.tensor_parts, self.mesh.shape.get("seq", 1))
        # lazily-built alternate views over the SAME chips: geometry ->
        # (mesh, placed base params). LRU-bounded — each sharded entry
        # pins ~1/tensor of the model per chip next to the default copy.
        self._geometries: OrderedDict[tuple, tuple] = OrderedDict()

        t0 = time.perf_counter()
        self.params = self._load_params()
        self.tokenizers = [
            load_tokenizer(self._model_dir(), vocab_size=c.vocab_size)
            for c in clip_cfgs
        ]
        self.load_s = round(time.perf_counter() - t0, 3)
        logger.info("%s resident in %.1fs (dtype=%s)", model_name, self.load_s, dtype)

        self._jit_lock = threading.Lock()
        # LRU-bounded (program_cache_max; _trim_program_caches): the
        # runtime-delta adapter path compiles one variant per signature
        # and the signature space is census-dependent, so the cache must
        # evict — executables included — instead of growing forever
        self._programs: OrderedDict[tuple, callable] = OrderedDict()
        # assembled denoise runners (fused wrapper or chunked set) keyed
        # (bucket key, chunk size): a warm pass is one dict lookup, not a
        # scheduler rebuild + per-sub-program cache probe
        self._runner_cache: OrderedDict[tuple, callable] = OrderedDict()
        # jitted aux programs — ONE device dispatch for text encode and VAE
        # encode instead of op-by-op applies (each unjitted op is a separate
        # host->device round trip; round 1 measured >50% of job time on the
        # host side, VERDICT weak #2). jit retraces per shape bucket.
        self._encode_program = programs.instrument(
            jax.jit(self._encode_impl), model=model_name, kind="encode")
        # text-encoder-LoRA twin (ISSUE 16): the TE delta operands ride
        # as traced ARGUMENTS, so swapping adapters never retraces —
        # jit retraces per operand structure (sig), like _encode_program
        # retraces per shape bucket
        self._encode_delta_program = programs.instrument(
            jax.jit(self._encode_delta_impl), model=model_name,
            kind="encode_delta")
        # per-pass operand-residency stats for the envelope (ISSUE 16):
        # set by _lora_operands, reset at pass start by run/run_batched
        self.last_operand_stats = None
        self._vae_encode_program = programs.instrument(
            jax.jit(
                lambda vae_params, px: self.vae.apply(
                    {"params": vae_params}, px, method=self.vae.encode
                ).astype(jnp.float32)
            ),
            model=model_name, kind="vae_encode")
        # weights-free 2x: encode -> bilinear latent resize -> decode.
        # Kept as the explicit `upscale` fallback when the learned sd-x2
        # upscaler has no converted weights (otherwise every production
        # upscale job would die on MissingWeightsError)
        self._latent2x_program = programs.instrument(
            jax.jit(self._latent2x_impl), model=model_name, kind="latent2x")
        # resident ControlNet branches keyed by controlnet model name
        self._controlnets: dict[str, tuple] = {}
        # param trees with LoRAs merged, keyed by (lora ref, scale); LRU-
        # bounded — each entry pins a full UNet copy in HBM
        self._lora_cache: OrderedDict[tuple, dict] = OrderedDict()
        # textual inversions: (extended text params, wrapped tokenizers)
        self._ti_cache: OrderedDict[str, tuple] = OrderedDict()
        # per-job custom VAEs (reference diffusion_func.py:46-49)
        self._vae_cache: OrderedDict[str, dict] = OrderedDict()

    # --- weights ---

    def _model_dir(self) -> Path | None:
        root = Path(load_settings().model_root_dir).expanduser()
        d = root / self.model_name
        return d if d.is_dir() else None

    def _load_params(self) -> dict:
        """Converted weights when the model ships locally; otherwise fail
        loudly — random init is reserved for test/tiny models and explicit
        `allow_random_init` opt-in (benchmarks). See weights.py policy."""
        from ..weights import require_weights_present

        model_dir = self._model_dir()
        if model_dir is not None:
            try:
                return self._convert_params(model_dir)
            except FileNotFoundError:
                require_weights_present(
                    self.model_name, model_dir, self.allow_random_init
                )
                logger.warning(
                    "no safetensors under %s; falling back to random init", model_dir
                )
        else:
            require_weights_present(self.model_name, None, self.allow_random_init)
        # NOT hash(): str hash is salted per process; weights must agree
        # across workers for the same model name
        seed = zlib.crc32(self.model_name.encode())
        rng = jax.random.key(seed)
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            k1, k2, k3 = jax.random.split(rng, 3)
            # param shapes don't depend on the canvas — init at the smallest
            # spatial size the block stack can downsample (a full-res init
            # forward on host CPU would take minutes for SDXL)
            n_down = len(self.unet.config.block_out_channels) - 1
            sample_hw = 2 ** max(n_down, 2)
            unet_vars = self.unet.init(
                k1,
                jnp.zeros((1, sample_hw, sample_hw, self.unet.config.in_channels)),
                jnp.zeros((1,)),
                jnp.zeros((1, 77, self.unet.config.cross_attention_dim)),
                added_cond=self._dummy_added_cond(1),
            )
            text_vars = [
                enc.init(k2, jnp.zeros((1, 77), jnp.int32)) for enc in self.text_encoders
            ]
            vae_vars = self.vae.init(
                k3,
                jnp.zeros(
                    (1, sample_hw * self.latent_factor,
                     sample_hw * self.latent_factor, 3)
                ),
            )
        params = {
            "unet": unet_vars["params"],
            "text": [tv["params"] for tv in text_vars],
            "vae": vae_vars["params"],
        }
        return self._place(params)

    def _convert_params(self, model_dir: Path) -> dict:
        from ..models.conversion import (
            convert_clip,
            convert_unet,
            convert_vae,
            load_torch_state_dict,
        )

        params = {
            "unet": convert_unet(load_torch_state_dict(model_dir, "unet")),
            "vae": convert_vae(load_torch_state_dict(model_dir, "vae")),
            "text": [],
        }
        for sub in ("text_encoder", "text_encoder_2")[: len(self.text_encoders)]:
            params["text"].append(
                convert_clip(load_torch_state_dict(model_dir, sub))
            )
        return self._place(params)

    def _place(self, params, mesh=None, tensor_parts=None):
        """Cast to the serving dtype and place on the mesh.

        Data-only mesh: everything replicated (the batch shards instead).
        Tensor-parallel mesh: UNet / text-encoder / ControlNet kernels shard
        Megatron-style per parallel/tensor.py partition rules — XLA inserts
        the psums where row-parallel matmuls contract. The VAE stays
        replicated; its decode shards over `data` via the batch sharding.

        `mesh`/`tensor_parts` default to the pipeline's construction-time
        view; the elastic-geometry path (params_for) passes an alternate
        mesh over the same chips.
        """
        mesh = self.mesh if mesh is None else mesh
        if tensor_parts is None:
            tensor_parts = mesh.shape.get("tensor", 1)
        cast = lambda x: jnp.asarray(x, self.dtype)
        params = jax.tree_util.tree_map(cast, params)
        if tensor_parts <= 1:
            return jax.device_put(params, replicated(mesh))
        from ..parallel.tensor import shard_params

        def place_component(name, tree):
            if name == "vae":
                return jax.device_put(tree, replicated(mesh))
            if isinstance(tree, list):
                return [shard_params(mesh, t) for t in tree]
            return shard_params(mesh, tree)

        return {k: place_component(k, v) for k, v in params.items()}

    # --- elastic slice geometry (ISSUE 12) ---

    def resolve_geometry(self, geometry) -> tuple[int, int]:
        """A per-pass geometry request -> validated (tensor, seq) over
        this pipeline's chipset; anything that cannot mesh (no chipset,
        bad divisor, single chip) falls back to the default view so a
        malformed request degrades to the classic pass, never fails it.
        Accepts a dict ({"tensor": t, "seq": s}), a (tensor, seq) tuple,
        or None/"default"."""
        if geometry is None or geometry == "default" or self.chipset is None:
            return self.default_geometry
        try:
            if isinstance(geometry, dict):
                tensor = geometry.get("tensor")
                seq = geometry.get("seq")
            else:
                tensor, seq = geometry
            resolved = self.chipset.resolve_geometry(tensor, seq)
        except (TypeError, ValueError):
            resolved = None
        if resolved is None:
            logger.warning(
                "geometry request %r does not fit slice %s; serving the "
                "default view", geometry,
                getattr(self.chipset, "identifier", lambda: "?")())
            return self.default_geometry
        return resolved

    def _geometry_view(self, geo: tuple[int, int]):
        """(mesh, placed base params) for one validated geometry over the
        slice's chips. The default view is the construction-time mesh +
        self.params (no copy); alternates are placed lazily from the
        resident tree — a reshard over ICI, not a reload — and kept in a
        tiny LRU. Thread-safe under the jit lock: geometry swaps happen on
        executor threads."""
        if geo == self.default_geometry:
            return self.mesh, self.params
        with self._jit_lock:
            if geo in self._geometries:
                self._geometries.move_to_end(geo)
                return self._geometries[geo]
        tensor, seq = geo
        mesh = self.chipset.mesh(tensor=tensor, seq=seq)
        base = self.params
        if base is None:
            raise Exception(
                f"pipeline {self.model_name} was evicted; resubmit the job")
        placed = self._place(base, mesh=mesh, tensor_parts=tensor)
        with self._jit_lock:
            self._geometries[geo] = (mesh, placed)
            self._geometries.move_to_end(geo)
            while len(self._geometries) > MAX_RESIDENT_GEOMETRIES:
                self._geometries.popitem(last=False)
        if self.chipset is not None:
            from ..chips.allocator import note_resident

            note_resident(self.model_name, self.chipset.slice_id)
        return mesh, placed

    def _dummy_added_cond(self, b):
        return dummy_added_cond(self.unet.config, b) if self.is_xl else None

    def _xl_time_ids(self, pooled_dim: int, height: int, width: int,
                     aesthetic_score: float = 6.0) -> list:
        """SDXL micro-conditioning id vector for this canvas. ONE
        implementation for the solo and batched paths — the 5-id refiner
        layout carries the aesthetic score (SDXL paper appendix)."""
        cfg = self.unet.config
        n_ids = (cfg.addition_embed_dim - pooled_dim) // (
            cfg.addition_time_embed_dim
        )
        if n_ids == 5:
            return [height, width, 0, 0, float(aesthetic_score)]
        return [height, width, 0, 0, height, width][:n_ids]

    def _place_batch(self, x, mesh=None):
        """Shard a leading-batch array over the mesh's data axis when the
        batch divides it evenly; replicate otherwise (rank-preserving
        placeholders, odd batches). Shared by solo and batched paths;
        `mesh` defaults to the construction-time view."""
        mesh = self.mesh if mesh is None else mesh
        data_parts = mesh.shape.get("data", 1)
        if data_parts > 1 and x.shape[0] % data_parts == 0:
            return jax.device_put(x, batch_sharding(mesh, x.ndim))
        return jax.device_put(x, replicated(mesh))

    def release(self):
        """Drop device references so HBM frees on registry eviction."""
        from .. import lora_operands

        # device-resident operand stacks for this model free WITH it
        # (their buffers were placed for this pipeline's mesh)
        lora_operands.invalidate_model(self.model_name)
        self.params = None
        self._programs.clear()
        self._runner_cache.clear()
        self._geometries.clear()
        self._controlnets.clear()
        self._lora_cache.clear()
        self._ti_cache.clear()
        self._vae_cache.clear()

    def _note_base_residency(self) -> None:
        """Residency event for an ADAPTER pass, keyed on the BASE model
        (ISSUE 13 satellite): a LoRA-heavy tenant's traffic must warm the
        same slice affinity as plain traffic — the registry's load/hit
        events fire at get_pipeline, but the adapter resolution inside a
        pass is a residency signal of its own (the factors, programs,
        and base tree all live here now)."""
        if self.chipset is None:
            return
        try:
            from ..chips.allocator import note_resident

            note_resident(self.model_name, self.chipset.slice_id)
        except Exception:  # placement is advisory; never fail a job over it
            logger.debug("adapter residency note failed", exc_info=True)

    def _adapter_delta_factors(self, lora: dict) -> dict | None:
        """Matched, delta-eligible factors for one adapter reference —
        the runtime per-row path (ISSUE 13) — or None when the adapter
        must fall back to the merged-tree path: runtime deltas disabled
        (Settings.lora_runtime_delta), modules the per-row Dense delta
        cannot express (conv/LoCon, shape-mismatched), or a rank past
        Settings.lora_rank_max (the padded stack would dwarf the batch).
        Load failures raise ValueError (fatal job error, reference
        contract). Resolution goes through the process-wide byte-capped
        factor cache (lora_cache.py) either way."""
        settings = load_settings()
        if not bool(getattr(settings, "lora_runtime_delta", True)):
            return None
        from .. import lora_cache
        from .lora_runtime import adapter_rank

        factors, derived = lora_cache.resolve_entry(lora, self.model_name)
        self._note_base_residency()
        # the Dense match walks the whole UNet param tree — fully
        # determined by (adapter, model), so it memoizes in the cache
        # entry's derived slot (same lifetime as the factors; the
        # rank-cap gate below stays per-call so a settings flip applies
        # to resident adapters too)
        memo_key = ("dense_match", self.model_name)
        verdict = derived.get(memo_key) if derived is not None else None
        if verdict is None:
            from ..models.lora import (match_dense_factors,
                                       match_te_dense_factors)

            matched, unmatched = match_dense_factors(
                factors, self.params["unet"])
            # text-encoder factors (te{i}:-namespaced, ISSUE 16) match
            # against the encoder trees and ride the SAME operand dict —
            # the ':' in their keys keeps the UNet interceptor away
            te_matched, te_unmatched = match_te_dense_factors(
                factors, self.params.get("text") or [])
            matched = {**matched, **te_matched}
            unmatched += te_unmatched
            if not matched:
                raise ValueError(
                    f"Could not load lora {lora}: no modules matched "
                    f"{self.model_name}'s parameter tree"
                )
            if unmatched:
                # the adapter carries content the per-row Dense delta
                # can't express (conv/LoCon) — route it to the merged
                # tree, the one conservative path for such adapters.
                # KNOWN GAP (ROADMAP): _merge_deltas currently also
                # skips shape-mismatched modules with a warning, so
                # today both paths drop the conv content; the fallback
                # keeps these adapters on the path where a real LoCon
                # conv merge lands when implemented, rather than baking
                # partial-delta semantics into the gang vocabulary
                logger.info(
                    "adapter %s has %d non-Dense module(s); merged-tree "
                    "fallback", lora.get("lora"), unmatched)
            verdict = (None if unmatched else matched,
                       adapter_rank(matched))
            if derived is not None:
                derived[memo_key] = verdict
        matched, rank = verdict
        if matched is None:
            return None
        rank_cap = int(getattr(settings, "lora_rank_max", 128) or 0)
        if rank_cap and rank > rank_cap:
            logger.info(
                "adapter %s rank %d exceeds lora_rank_max=%d; merged-tree "
                "fallback", lora.get("lora"), rank, rank_cap)
            return None
        return matched

    @staticmethod
    def _require_runtime_delta() -> None:
        """The kill switch: delta serving disabled means adapter groups
        refuse (the solo fallback serves each member via the merged
        tree). Shared by run_batched and the multi-chunk prescan so the
        refusal (and the message callers match on) cannot drift."""
        if not bool(getattr(load_settings(), "lora_runtime_delta", True)):
            raise ValueError(
                "runtime LoRA deltas are disabled "
                "(lora_runtime_delta=0); serving members individually")

    @staticmethod
    def _adapter_slots_cap(lora_slots_max: int | None) -> int:
        return int(lora_slots_max
                   or getattr(load_settings(), "lora_slots_max", 8)
                   or 8)

    def _scan_adapter_specs(self, specs) -> tuple[dict, set, list]:
        """One pass's adapter eligibility scan: resolve every DISTINCT
        adapter once (factor-cache backed; the match verdict memoizes
        in the entry's derived slot) -> (factors_of by adapter key,
        distinct eligible keys, ineligible member job_ids). Load
        FAILURES raise plain ValueError: the classic whole-group
        fallback reproduces the fatal error with per-job attribution.
        Shared by run_batched and prescan_adapter_chunks."""
        from .. import lora_cache

        factors_of: dict[tuple, dict | None] = {}
        distinct: set = set()
        ineligible: list = []
        for spec in specs:
            lora = spec.get("lora")
            if not lora:
                continue
            akey = lora_cache.adapter_key(lora)
            if akey not in factors_of:
                factors_of[akey] = self._adapter_delta_factors(lora)
            if factors_of[akey] is None:
                ineligible.append(spec.get("job_id"))
            else:
                distinct.add(akey)
        return factors_of, distinct, ineligible

    def prescan_adapter_chunks(self, chunks: list[list[dict]],
                               lora_slots_max: int | None = None) -> None:
        """Raise every adapter refusal run_batched would hit in ANY pass
        of a multi-pass group — the kill switch, delta-ineligible
        adapters (DeltaIneligibleError naming every affected member),
        the per-pass distinct-adapter slots cap — BEFORE the first pass
        runs. A group split across passes otherwise wastes work: a
        LATER chunk's refusal discards earlier chunks' finished denoise
        output and re-counts their row metrics on the worker's
        re-batch. Built from the same scan run_batched uses per call,
        so the two cannot desynchronize."""
        if not any(s.get("lora") for chunk in chunks for s in chunk):
            return
        from .lora_runtime import DeltaIneligibleError

        self._require_runtime_delta()
        slots_cap = self._adapter_slots_cap(lora_slots_max)
        ineligible: list = []
        overflow = False
        for chunk in chunks:
            _factors, distinct, inel = self._scan_adapter_specs(chunk)
            ineligible.extend(inel)
            overflow = overflow or len(distinct) > slots_cap
        # ineligibility outranks the cap, as in run_batched (its slot
        # assignment never starts when the eligibility scan refuses)
        if ineligible:
            raise DeltaIneligibleError(ineligible)
        if overflow:
            raise ValueError(
                f"group carries more than {slots_cap} distinct adapters "
                "in one pass; serving members individually")

    def _lora_operands(self, adapters: list[dict], row_slots: list[int],
                       row_gains: list[float],
                       adapter_keys: tuple | None = None):
        """Stack matched factors into the jitted program's lora operand,
        replicated over the pass mesh (the stacks are weights-like: a
        few MiB against the batch, and the slot dim must never be
        mistaken for a batch dim by the data-axis sharder).

        Operand residency (ISSUE 16): with `adapter_keys` (the factor-
        cache keys in SLOT ORDER — the stack recipe), the device-resident
        operand cache (lora_operands.py) is consulted FIRST; a hit skips
        assembly and upload entirely — steady state is a dict lookup
        handing jit the resident stacks plus this pass's tiny slot/gain
        vectors. Sets `self.last_operand_stats` for the envelope."""
        from .. import lora_operands
        from .lora_runtime import build_stacks, row_operands, stacks_sig

        sig = stacks_sig(adapters)
        cache = lora_operands.get_cache()
        key = None
        if cache is not None and adapter_keys is not None:
            key = (self.model_name, tuple(adapter_keys), sig,
                   np.dtype(self.dtype).name, self.default_geometry)
        a_map = b_map = None
        hits, bytes_saved = 0, 0
        if key is not None:
            entry = cache.lookup(key)
            if entry is not None:
                (a_map, b_map), nbytes = entry
                hits, bytes_saved = 1, int(nbytes)
        if a_map is None:
            a_map, b_map, nbytes = build_stacks(adapters, self.dtype, sig)
            if self.mesh.devices.size > 1:
                a_map = jax.device_put(a_map, replicated(self.mesh))
                b_map = jax.device_put(b_map, replicated(self.mesh))
            if key is not None:
                cache.put(key, (a_map, b_map), nbytes)
        operands = row_operands(a_map, b_map, row_slots, row_gains)
        if self.mesh.devices.size > 1:
            operands["slot"] = jax.device_put(
                operands["slot"], replicated(self.mesh))
            operands["gain"] = jax.device_put(
                operands["gain"], replicated(self.mesh))
        self.last_operand_stats = {"hits": hits, "misses": 1 - hits,
                                   "bytes_saved": bytes_saved}
        return operands, sig

    def _lora_params(self, base_params: dict, lora: dict, scale: float) -> dict:
        """Base params with a LoRA merged into the UNet — the FALLBACK
        path (ISSUE 13): adapters the runtime per-row delta cannot
        express still work, at the old cost of a full UNet copy. Merges
        from the byte-capped factor cache (lora_cache.py), so the
        safetensors parse is shared with the delta path; the merged
        trees themselves keep only a tiny LRU (each entry pins a full
        UNet copy in HBM — the very cost the delta path removes).
        Load failures raise ValueError -> fatal job error, matching the
        reference's "incompatible lora" contract.
        """
        key = (lora.get("lora"), lora.get("weight_name"), lora.get("subfolder"),
               round(scale, 4))
        if key in self._lora_cache:
            self._lora_cache.move_to_end(key)
            return self._lora_cache[key]
        from .. import lora_cache
        from ..models.lora import merge_factors, merge_te_factors

        factors = lora_cache.resolve(lora, self.model_name)
        self._note_base_residency()
        ref = str(lora.get("lora"))
        merged_unet, matched = merge_factors(
            base_params["unet"], factors, scale, ref)
        # text-encoder factors merge into encoder-tree copies (ISSUE
        # 16); swapping params["text"] off the resident list makes the
        # prompt-embedding cache's identity check bypass automatically
        merged_text, te_matched = merge_te_factors(
            base_params.get("text") or [], factors, scale, ref)
        if matched + te_matched == 0:
            raise ValueError(
                f"Could not load lora {lora}: no modules matched "
                f"{self.model_name}'s parameter tree"
            )
        logger.info(
            "merged LoRA %s into %s (%d unet + %d text modules, "
            "scale %.2f)",
            lora.get("lora"), self.model_name, matched, te_matched, scale,
        )
        params = dict(base_params)
        if matched:
            params["unet"] = self._place({"unet": merged_unet})["unet"]
        if te_matched:
            params["text"] = self._place({"text": merged_text})["text"]
        self._lora_cache[key] = params
        while len(self._lora_cache) > MAX_RESIDENT_LORAS:
            self._lora_cache.popitem(last=False)
        return params

    def _ti_apply(self, ti_ref) -> tuple[list, list]:
        """-> (per-encoder extra-embedding tables, tokenizers with the
        placeholder tokens). Cached per ref; vectors route to whichever
        encoder's hidden width they match (SDXL ships per-encoder embeds).
        The placeholder vectors ride as *inputs* to the encoders (ids past
        vocab_size index into them), leaving the resident params untouched.
        """
        key = str(ti_ref)
        if key in self._ti_cache:
            self._ti_cache.move_to_end(key)
            return self._ti_cache[key]
        from ..models.tokenizer import PlaceholderTokenizer

        groups = load_learned_embeddings(ti_ref)
        extras = []
        tokenizers = []
        applied = False
        for enc, tok in zip(self.text_encoders, self.tokenizers):
            dim = enc.config.hidden_size
            vocab = enc.config.vocab_size
            placeholders = {}
            rows = []
            next_id = vocab
            for group in groups:
                vec = next(
                    (v for v in group["vectors"] if v.shape[-1] == dim), None
                )
                if vec is None:
                    continue
                ids = list(range(next_id, next_id + vec.shape[0]))
                for alias in group["tokens"]:
                    placeholders[alias] = ids
                rows.append(vec)
                next_id += vec.shape[0]
            if not rows:
                extras.append(None)
                tokenizers.append(tok)
                continue
            extras.append(
                jax.device_put(
                    jnp.asarray(np.concatenate(rows, axis=0), self.dtype),
                    replicated(self.mesh),
                )
            )
            tokenizers.append(PlaceholderTokenizer(tok, placeholders))
            applied = True
            logger.info(
                "textual inversion %s: %d group(s) for %s's encoder %d",
                ti_ref, len(rows), self.model_name, len(extras) - 1,
            )
        if not applied:
            dims = sorted({
                v.shape[-1] for g in groups for v in g["vectors"]
            })
            raise ValueError(
                f"Textual inversion {ti_ref} is incompatible with "
                f"{self.model_name}: embedding widths {dims} match no "
                f"text encoder"
            )
        self._ti_cache[key] = (extras, tokenizers)
        while len(self._ti_cache) > MAX_RESIDENT_TI:
            self._ti_cache.popitem(last=False)
        return extras, tokenizers

    def _custom_vae(self, name: str) -> dict:
        """Converted per-job VAE (reference diffusion_func.py:46-49),
        resident + LRU-bounded; missing weights are a fatal job error."""
        if name in self._vae_cache:
            self._vae_cache.move_to_end(name)
            return self._vae_cache[name]
        from ..models.conversion import convert_vae, load_torch_state_dict

        root = Path(load_settings().model_root_dir).expanduser() / name
        state = None
        for sub in ("", "vae"):
            try:
                state = load_torch_state_dict(root, sub)
                break
            except FileNotFoundError:
                continue
        if state is None:
            raise ValueError(
                f"Could not load custom VAE {name}: no safetensors under "
                f"{root}. Prefetch it with `chiaswarm-tpu-init --download "
                f"--models {name}`."
            )
        params = self._place({"vae": convert_vae(state)})["vae"]
        self._vae_cache[name] = params
        while len(self._vae_cache) > MAX_RESIDENT_VAES:
            self._vae_cache.popitem(last=False)
        return params

    def _get_controlnet(self, name: str):
        """Resident ControlNet branch sharing this model's UNet config.

        Converted weights when `<model_root>/<name>` ships safetensors.
        Missing weights are a fatal job error — a zero-init branch is a
        mathematical no-op that would silently ignore the user's control
        image (VERDICT weak #6); zero-init remains only for test/tiny
        control names and explicit random-init opt-in.
        """
        if name in self._controlnets:
            return self._controlnets[name]
        from ..models.controlnet import ControlNetModel
        from ..weights import require_weights_present

        cn = ControlNetModel(
            self.unet.config, cond_downscale=self.latent_factor, dtype=self.dtype
        )
        root = Path(load_settings().model_root_dir).expanduser() / name
        params = None
        if root.is_dir():
            try:
                from ..models.conversion import (
                    convert_unet,
                    load_torch_state_dict,
                )

                params = self._place(
                    {"cn": convert_unet(load_torch_state_dict(root))}
                )["cn"]
            except FileNotFoundError:
                pass
        if params is None:
            require_weights_present(
                name, root, self.allow_random_init, component="ControlNet"
            )
            logger.warning("no safetensors under %s; zero-init control", root)
            sample_hw = 2 * self.latent_factor  # any valid spatial size
            with jax.default_device(jax.local_devices(backend="cpu")[0]):
                params = cn.init(
                    jax.random.key(zlib.crc32(name.encode())),
                    jnp.zeros((1, sample_hw, sample_hw, self.unet.config.in_channels)),
                    jnp.zeros((1,)),
                    jnp.zeros((1, 77, self.unet.config.cross_attention_dim)),
                    jnp.zeros(
                        (1, sample_hw * self.latent_factor,
                         sample_hw * self.latent_factor, 3)
                    ),
                    added_cond=self._dummy_added_cond(1),
                )["params"]
            params = self._place({"cn": params})["cn"]
        self._controlnets[name] = (cn, params)
        return cn, params

    def _run_qr_two_stage(self, prompt, negative_prompt, pipeline_type,
                          **kwargs):
        """QR-monster chain (reference diffusion_func.py:78-101): a plain
        txt2img prepipeline composes the scene at half resolution, the
        result upscales, and the ControlNet img2img pass imposes the QR
        structure at full size. The reference chained through a raw latent
        2x interpolation; here the handoff is pixel-space (upscale + VAE
        re-encode), preserving the two-stage semantics with one code path.
        """
        kwargs.pop("controlnet_prepipeline_type", None)
        height = int(kwargs.pop("height", None) or self.default_size)
        width = int(kwargs.pop("width", None) or self.default_size)
        strength = float(kwargs.pop("strength", 0.9))
        rng = kwargs.pop("rng", None)
        if rng is None:
            rng = jax.random.key(0)
        rng, stage1_rng, stage2_rng = jax.random.split(rng, 3)

        cn_kwargs = {
            k: kwargs.pop(k)
            for k in (
                "controlnet_model_name", "control_image",
                "controlnet_conditioning_scale", "control_guidance_start",
                "control_guidance_end",
            )
            if k in kwargs
        }
        # the txt2img-ControlNet wire delivers the QR as `image`
        # (job_arguments format_controlnet_args sets args["image"])
        start_image = kwargs.pop("image", None)
        if cn_kwargs.get("control_image") is None and start_image is not None:
            cn_kwargs["control_image"] = start_image
        if cn_kwargs.get("control_image") is None:
            raise ValueError("Controlnet specified but no control image provided")

        stage1_kwargs = dict(kwargs)
        # one composition image is all stage 2 consumes
        stage1_kwargs["num_images_per_prompt"] = 1
        t0 = time.perf_counter()
        stage1, _ = self.run(
            prompt=prompt,
            negative_prompt=negative_prompt,
            pipeline_type=pipeline_type,
            height=max(height // 2, 64),
            width=max(width // 2, 64),
            rng=stage1_rng,
            **stage1_kwargs,
        )
        prepipeline_s = round(time.perf_counter() - t0, 3)

        base = stage1[0].resize((width, height), Image.LANCZOS)
        images, config = self.run(
            prompt=prompt,
            negative_prompt=negative_prompt,
            pipeline_type=pipeline_type,
            image=base,
            strength=strength,
            height=height,
            width=width,
            rng=stage2_rng,
            **cn_kwargs,
            **kwargs,
        )
        config["prepipeline"] = "qr_two_stage"
        config["timings"]["prepipeline_s"] = prepipeline_s
        return images, config

    # --- text conditioning (host + tiny device work, once per job) ---

    def _latent2x_impl(self, vae_params, px):
        """Encode -> bilinear 2x latent resize -> decode, one program.

        The round-1 `upscale: true` behavior, retained as the explicit
        fallback when stabilityai/sd-x2-latent-upscaler has no converted
        weights on this worker (reference chains the learned upscaler at
        swarm/diffusion/diffusion_func.py:163)."""
        z = self.vae.apply(
            {"params": vae_params}, px.astype(self.dtype),
            method=self.vae.encode,
        )
        b, h, w, c = z.shape
        z2 = jax.image.resize(
            z.astype(jnp.float32), (b, 2 * h, 2 * w, c), "bilinear"
        ).astype(self.dtype)
        out = self.vae.apply(
            {"params": vae_params}, z2, method=self.vae.decode
        )
        return (
            (out.astype(jnp.float32) + 1.0) * 127.5
        ).clip(0.0, 255.0).round().astype(jnp.uint8)

    def _encode_impl(self, text_params, ids_list, extras_list):
        """All text encoders fused into one jitted program."""
        hiddens, pooled = [], None
        for enc, p, ids, extra in zip(
            self.text_encoders, text_params, ids_list, extras_list
        ):
            out = enc.apply({"params": p}, ids, extra_embeddings=extra)
            hiddens.append(out["hidden_states"])
            pooled = out["pooled"]  # last encoder's pooled (SDXL: encoder 2)
        context = jnp.concatenate(hiddens, axis=-1) if len(hiddens) > 1 else hiddens[0]
        return context, pooled

    def _encode_delta_impl(self, text_params, ids_list, extras_list,
                           te_operands):
        """_encode_impl with the per-row TE-LoRA delta interceptor
        (ISSUE 16) wrapped around each encoder apply: the resident text
        params and the compiled structure stay untouched — adapter
        identity is data, exactly like the UNet delta path. Each encoder
        only matches stacks under ITS te{i}: namespace."""
        import flax.linen as nn

        from .lora_runtime import make_te_interceptor

        hiddens, pooled = [], None
        for i, (enc, p, ids, extra) in enumerate(zip(
            self.text_encoders, text_params, ids_list, extras_list
        )):
            with nn.intercept_methods(make_te_interceptor(te_operands, i)):
                out = enc.apply({"params": p}, ids, extra_embeddings=extra)
            hiddens.append(out["hidden_states"])
            pooled = out["pooled"]
        context = jnp.concatenate(hiddens, axis=-1) if len(hiddens) > 1 else hiddens[0]
        return context, pooled

    def encode_prompts(self, prompts: list[str], params: dict,
                       tokenizers=None, extra_embeddings=None,
                       te_operands=None):
        """-> (context [B,77,D], pooled [B,P] or None).

        One batched pass over all encoders in a single jitted dispatch —
        callers stack [negatives + prompts] so uncond/cond conditioning is
        one program call, not per-encoder op-by-op applies. `tokenizers` /
        `extra_embeddings` override the residents for textual-inversion
        placeholder tokens.

        Rows are served from the process-wide embedding cache
        (embed_cache.py, keyed (model, text)) whenever nothing job-
        specific perturbs the encoder: no tokenizer/embedding overrides
        and the pipeline's own resident text params. Only the texts the
        cache misses run the encoder — padded to a power-of-two bucket
        so distinct miss counts share one compiled program — so gang
        members and repeat prompts (the shared "" negative above all)
        skip text_encode entirely.
        """
        toks = tokenizers or self.tokenizers
        extras = extra_embeddings or [None] * len(toks)
        # per-pass cache stats for the envelope (the hive's tenant
        # ledger attributes embed-cache hits per job from it); reset
        # here so a bypassed encode reports nothing rather than the
        # previous pass's numbers. Instance state is safe: the slice
        # busy lock serializes passes through one pipeline.
        self.last_encode_stats = None
        cache = embed_cache.get_cache()
        # the resident text params, identity-compared below: a job that
        # swapped them (merged LoRA touching the encoders, custom
        # params) must bypass the cache or a stale row would leak in
        resident_text = (self.params.get("text")
                         if isinstance(self.params, dict) else None)
        if (cache is None or tokenizers is not None
                or extra_embeddings is not None
                or te_operands is not None
                or resident_text is None
                or params.get("text") is not resident_text):
            ids_list = [jnp.asarray(tok(prompts)) for tok in toks]
            if te_operands is not None:
                # TE-LoRA delta rows are adapter-specific: they bypass
                # the (model, text)-keyed embedding cache and run the
                # interceptor-wrapped twin program (ISSUE 16)
                context, pooled = self._encode_delta_program(
                    params["text"], ids_list, extras, te_operands)
            else:
                context, pooled = self._encode_program(
                    params["text"], ids_list, extras)
            return context, (pooled if self.is_xl else None)

        found: dict[str, tuple | None] = {}
        hits = misses = 0
        for text in prompts:
            if text in found:
                # duplicate row in this batch: whether its first
                # occurrence hit or missed, THIS row skips its encoder
                # forward (the batch encodes unique texts once), which
                # is exactly what the hit counter measures
                hits += 1
            else:
                found[text] = cache.lookup((self.model_name, text))
                if found[text] is None:
                    misses += 1
                else:
                    hits += 1
        cache.note_rows(hits, misses)
        self.last_encode_stats = (hits, misses)
        missing = [t for t, v in found.items() if v is None]
        if missing:
            from .common import pad_bucket

            # repeat the last miss into the padding rows: jit retraces
            # per batch shape, and pow2 bucketing keeps distinct miss
            # counts on a handful of compiled programs
            padded = missing + [missing[-1]] * (
                pad_bucket(len(missing)) - len(missing))
            ids_list = [jnp.asarray(tok(padded)) for tok in toks]
            context_m, pooled_m = self._encode_program(
                params["text"], ids_list, extras)
            ctx_np = np.asarray(context_m)
            pooled_np = (np.asarray(pooled_m)
                         if self.is_xl and pooled_m is not None else None)
            for i, text in enumerate(missing):
                # copy the row OUT of the padded batch: a bare ctx_np[i]
                # is a view whose .base pins the whole encode batch, so
                # the cache's byte accounting (row nbytes) would wildly
                # undercount what it actually keeps resident
                value = (np.ascontiguousarray(ctx_np[i]),
                         (np.ascontiguousarray(pooled_np[i])
                          if pooled_np is not None else None))
                found[text] = value
                cache.put((self.model_name, text), value)
        context = jnp.asarray(np.stack([found[t][0] for t in prompts]))
        pooled = None
        if self.is_xl:
            pooled = jnp.asarray(np.stack([found[t][1] for t in prompts]))
        return context, pooled

    # --- the jitted core ---

    def _denoise_parts(self, key, controlnet_module=None, mesh=None):
        """The denoise program's composable pieces for one bucket:
        ``prep`` (initial latents + scheduler state), ``make_steps(n)``
        (n compiled iterations of the shared step body, starting at a
        traced ``offset``), and ``decode`` (VAE decode + on-device uint8
        quantize), plus the loop bounds. ``_denoise_program`` fuses them
        into the classic single jitted program (the zero-cost
        ``denoise_chunk_steps=0`` path); the chunked path jits them
        separately so the executor thread can probe cancel tokens
        (cancel.py) between compiled chunks. Both paths run the exact
        same ops on the same values in the same order, so their outputs
        are bitwise identical (pinned by tests/test_cancel.py).

        key = (mode, lh, lw, batch, steps, scheduler_key, t_start,
               cn_key) where cn_key = (controlnet_name, cg_lo, cg_hi) or None
        """
        mode, lh, lw, batch, steps, sched_key, t_start, cn_key = key
        scheduler = get_scheduler(
            sched_key[0],
            **dict(sched_key[1]),
        )
        # On a multi-chip mesh every jax.random draw inside the program is
        # pinned replicated: GSPMD otherwise propagates the consumers'
        # sharding back into the threefry computation, and this jax's
        # non-partitionable RNG lowering then generates DIFFERENT values
        # per shard layout (the sharded-vs-replicated numerics drift that
        # broke test_parallel/test_seq_parallel_serving). The draw is a
        # few KB of latents against a multi-second denoise, so replicating
        # it costs nothing; single-chip programs keep their exact HLO.
        mesh = self.mesh if mesh is None else mesh
        multichip = mesh.devices.size > 1
        rep_sharding = replicated(mesh) if multichip else None

        def pin(z):
            if multichip:
                return jax.lax.with_sharding_constraint(z, rep_sharding)
            return z

        def draw_normal(rng_key, shape):
            return pin(jax.random.normal(rng_key, shape, jnp.float32))
        schedule = scheduler.schedule(steps)
        # most solvers: one model call per user step; Heun interleaves two
        # and maps the bounds onto its doubled index space
        loop_start, loop_end = scheduler.loop_bounds(schedule, steps, t_start)

        unet_apply = self.unet.apply
        vae = self.vae
        latent_c = self.latent_channels
        # pix2pix runs a 3-way CFG: rows [uncond | image-only | image+text]
        cfg_rows = 3 if mode == "pix2pix" else 2
        # chunked single-chip decode bounds peak decoder activations on big
        # canvases (batch 4 x 1024^2 OOM'd a v5e chip in round 1); on a
        # multi-chip mesh the batch is sharded so the full decode stays
        decode_area = lh * lw
        big_decode = decode_area >= 9216 and batch >= 2 and self.data_parts == 1

        def prep(params, init_rng, image_latents):
            """Initial latents (f32) + scheduler state, pre-step-loop."""
            if mode in ("batched", "batched_i2i"):
                # cross-job coalesced pass: init_rng is a [batch] key
                # array, one per row, each derived only from its own job's
                # seed — a job's images must not depend on its batchmates
                latents = pin(jax.vmap(
                    lambda k: jax.random.normal(k, (lh, lw, latent_c), jnp.float32)
                )(init_rng))
            else:
                latents = draw_normal(init_rng, (batch, lh, lw, latent_c))
            if mode in ("img2img", "batched_i2i", "inpaint"):
                # batched_i2i: image_latents is the [batch] stack of each
                # row's own start-image latents (padding rows zeros);
                # inpaint denoises from the clean image's noised latents
                latents = scheduler.add_noise(
                    schedule, image_latents, latents, loop_start
                )
            else:
                # txt2img and pix2pix both denoise from pure noise; pix2pix's
                # image conditioning rides the UNet's channel dim instead
                latents = latents * jnp.asarray(
                    schedule.init_noise_sigma, latents.dtype
                )
            state = scheduler.init_state(latents.shape, latents.dtype)
            return latents.astype(jnp.float32), state

        def make_steps(length: int):
            """`length` step-body iterations from a traced `offset` (the
            fused program passes loop_start once; the chunked path walks
            the same index sequence in denoise_chunk_steps strides)."""

            def run_steps(params, latents, state, context, added,
                          guidance_scale, image_guidance, image_latents,
                          mask, rng, cn_params, control_cond, cn_scale,
                          lora, offset):
                """context [cfg_rows*B,77,D] (uncond first). `lora` is the
                stacked per-row adapter operand (lora_runtime.py) — an
                EMPTY dict for adapter-free passes, which traces to the
                identical program (zero pytree leaves, no extra HLO)."""
                if lora:
                    from .lora_runtime import make_interceptor

                    lora_interceptor = make_interceptor(lora, cfg_rows)
                if mode == "pix2pix":
                    # per-row channel conditioning: zeros for the uncond
                    # row so image guidance has a true no-image baseline
                    cond_rows = stack_rows(
                        jnp.zeros_like(image_latents), image_latents,
                        image_latents,
                    ).astype(self.dtype)
                if mode == "inpaint":
                    clean = image_latents
                if mode == "inpaint9":
                    # dedicated inpaint UNet: mask plane + masked-image
                    # latents ride the channel dim on both CFG rows
                    cond9 = jnp.concatenate([mask, image_latents], axis=-1)
                    cond9 = repeat_rows(cond9, 2).astype(self.dtype)
                if cn_key is not None:
                    control2 = repeat_rows(control_cond, 2).astype(self.dtype)
                    _, cg_lo, cg_hi = cn_key

                def body(carry, i):
                    latents, state = carry
                    inp = scheduler.scale_model_input(schedule, latents, i)
                    model_in = repeat_rows(inp, cfg_rows).astype(self.dtype)
                    if mode == "pix2pix":
                        # image latents join unscaled: the edit checkpoint was
                        # trained on raw latent-dist modes
                        model_in = jnp.concatenate([model_in, cond_rows], axis=-1)
                    elif mode == "inpaint9":
                        model_in = jnp.concatenate([model_in, cond9], axis=-1)
                    t = jnp.asarray(schedule.timesteps)[i]
                    t_vec = jnp.broadcast_to(t, (model_in.shape[0],))
                    residual_kw = {}
                    if cn_key is not None:
                        # guidance window: the control branch is active only for
                        # steps in [cg_lo, cg_hi) (control_guidance_start/end)
                        eff = cn_scale * ((i >= cg_lo) & (i < cg_hi)).astype(
                            jnp.float32
                        )
                        down_res, mid_res = controlnet_module.apply(
                            {"params": cn_params},
                            model_in,
                            t_vec,
                            context,
                            control2,
                            conditioning_scale=eff,
                            added_cond=added,
                        )
                        residual_kw = {
                            "down_residuals": down_res,
                            "mid_residual": mid_res,
                        }
                    unet_in = (
                        {"params": params["unet"]}, model_in, t_vec, context)
                    if lora:
                        # scoped to the UNet apply alone: the ControlNet
                        # branch above shares module names (down_blocks_*/
                        # attn*), so a body-wide interceptor would apply
                        # the UNet's deltas to the control branch too
                        import flax.linen as fnn

                        with fnn.intercept_methods(lora_interceptor):
                            out = unet_apply(
                                *unet_in, added_cond=added, **residual_kw
                            ).astype(jnp.float32)
                    else:
                        out = unet_apply(
                            *unet_in, added_cond=added, **residual_kw
                        ).astype(jnp.float32)
                    if mode == "pix2pix":
                        # dual guidance (InstructPix2Pix eq. 3): text guidance
                        # pulls away from image-only, image guidance away from
                        # the fully-unconditional row
                        out_u, out_i, out_c = jnp.split(out, 3, axis=0)
                        out = (
                            out_u
                            + guidance_scale * (out_c - out_i)
                            + image_guidance * (out_i - out_u)
                        )
                    else:
                        out_u, out_c = jnp.split(out, 2, axis=0)
                        out = out_u + guidance_scale * (out_c - out_u)

                    if mode in ("batched", "batched_i2i"):
                        # per-row ancestral noise from per-job keys (same
                        # independence argument as the init draw)
                        noise = pin(jax.vmap(lambda k: jax.random.normal(
                            jax.random.fold_in(k, i), (lh, lw, latent_c),
                            jnp.float32))(rng))
                    else:
                        noise = draw_normal(
                            jax.random.fold_in(rng, i), latents.shape
                        )
                    state, latents = scheduler.step(
                        schedule, state, i, latents, out, noise
                    )
                    if mode == "inpaint":
                        # keep the unmasked region on the original image's
                        # noise trajectory (4-channel inpainting)
                        keep = scheduler.add_noise(
                            schedule,
                            clean,
                            draw_normal(
                                jax.random.fold_in(rng, 7919 + i), clean.shape
                            ),
                            jnp.minimum(i + 1, loop_end - 1),
                        )
                        keep = jnp.where(i == loop_end - 1, clean, keep)
                        latents = mask * latents + (1.0 - mask) * keep
                    return (latents, state), ()

                (latents, state), _ = jax.lax.scan(
                    body, (latents, state), jnp.arange(length) + offset
                )
                return latents, state

            return run_steps

        def decode(params, latents):
            latents = latents.astype(self.dtype)
            if big_decode:
                pixels = jax.lax.map(
                    lambda z: vae.apply(
                        {"params": params["vae"]}, z[None], method=vae.decode
                    )[0],
                    latents,
                )
            else:
                pixels = vae.apply(
                    {"params": params["vae"]}, latents, method=vae.decode
                )
            # quantize on device: uint8 transfer is 4x smaller than fp32 and
            # leaves the host with nothing to do but wrap PIL around it
            return (
                (pixels.astype(jnp.float32) + 1.0) * 127.5
            ).clip(0.0, 255.0).round().astype(jnp.uint8)

        return prep, make_steps, decode, (loop_start, loop_end)

    @staticmethod
    def _program_cache_max() -> int:
        """Settings.program_cache_max at call time (env-overridable,
        CHIASWARM_PROGRAM_CACHE_MAX); 0 = unbounded."""
        try:
            return max(int(getattr(
                load_settings(), "program_cache_max", 64) or 0), 0)
        except Exception:
            return 64

    def _trim_program_caches(self) -> None:
        """LRU-bound both variant caches to program_cache_max (caller
        holds _jit_lock). Evicted programs get their compiled executable
        dropped too (PjitFunction.clear_cache) — evicting only the dict
        reference would leak the XLA executable until pipeline release,
        which is exactly the unbounded axis this bound exists to close.
        A runner closure may still reference a cleared program; its next
        call retraces (counted as a compile-cache miss), never breaks."""
        cap = self._program_cache_max()
        if cap <= 0:
            return
        while len(self._programs) > cap:
            _, evicted = self._programs.popitem(last=False)
            clear = getattr(evicted, "clear_cache", None)
            if callable(clear):
                try:
                    clear()
                except Exception:  # freeing best-effort, never fatal
                    logger.debug("clear_cache failed on evicted program",
                                 exc_info=True)
            _PROGRAM_EVICTED.inc(kind="program")
        while len(self._runner_cache) > cap:
            self._runner_cache.popitem(last=False)
            _PROGRAM_EVICTED.inc(kind="runner")

    def _program(self, cache_key, build, kind="program",
                 analytic_flops=None):
        """One jitted program per cache key, sharing the compile-cache
        metrics and the placement-layer residency note across every
        denoise program kind (fused, prep, chunk, decode). Every compile
        registers with the program ledger (programs.py, ISSUE 17);
        `analytic_flops` — supplied by sites that know their program's
        models/flops.py count — arms the analytic-vs-XLA divergence
        cross-check on first call."""
        with self._jit_lock:
            cached = self._programs.get(cache_key)
            if cached is not None:
                self._programs.move_to_end(cache_key)
                _COMPILE_CACHE.inc(event="hit")
                return cached
        _COMPILE_CACHE.inc(event="miss")
        if self.chipset is not None:
            # compile event -> placement layer: refresh this model's
            # residency so the dispatch board keeps routing same-model
            # groups at the slice that owns the jitted programs
            from ..chips.allocator import note_resident

            note_resident(self.model_name, self.chipset.slice_id)
        program = programs.instrument(
            jax.jit(build()), model=self.model_name, kind=kind,
            key=cache_key, analytic_flops=analytic_flops)
        with self._jit_lock:
            self._programs[cache_key] = program
            self._programs.move_to_end(cache_key)
            self._trim_program_caches()
        return program

    def _geo_key(self, key, geo):
        """Program-cache key for one bucket under one geometry. The
        default view keeps the BARE bucket key — byte-identical to the
        pre-geometry cache, so the zero-cost pinning (exactly one
        program per bucket at chunk=0) holds — and alternates suffix it."""
        if geo is None or geo == self.default_geometry:
            return key
        return (key, "geo", geo)

    @staticmethod
    def _sig_key(gkey, lora_sig):
        """Adapter-pass program-cache suffix (ISSUE 13): adapter-free
        passes keep the bare (geometry-suffixed) key so every pre-LoRA
        cache-shape pin holds; runtime-delta passes compile per
        (slot-bucket, rank-bucket, targeted-module-set) signature — adapter
        IDENTITY is data,
        so swapping adapters inside one signature never recompiles."""
        if lora_sig is None:
            return gkey
        return (gkey, "lora", lora_sig)

    def _denoise_program(self, key, controlnet_module=None, geo=None,
                         mesh=None, lora_sig=None, analytic_flops=None):
        """Build (or fetch) the classic fused jitted denoise+decode
        program for one bucket — prep, the full step loop, and decode in
        ONE dispatch. This is the denoise_chunk_steps=0 path, cached
        under the bare bucket key exactly as before the chunked seam
        (geometry-suffixed for non-default mesh views, signature-suffixed
        for runtime-delta adapter passes)."""

        def build():
            prep, make_steps, decode, (lo, hi) = self._denoise_parts(
                key, controlnet_module, mesh=mesh)
            run_steps = make_steps(hi - lo)

            def run(params, init_rng, context, added, guidance_scale,
                    image_guidance, image_latents, mask, rng, cn_params,
                    control_cond, cn_scale, lora):
                latents, state = prep(params, init_rng, image_latents)
                latents, _ = run_steps(
                    params, latents, state, context, added, guidance_scale,
                    image_guidance, image_latents, mask, rng, cn_params,
                    control_cond, cn_scale, lora, jnp.int32(lo))
                return decode(params, latents)

            return run

        return self._program(
            self._sig_key(self._geo_key(key, geo), lora_sig), build,
            kind="fused", analytic_flops=analytic_flops)

    def _denoise_chunk_steps(self) -> int:
        """Settings.denoise_chunk_steps at call time (env-overridable per
        process, CHIASWARM_DENOISE_CHUNK_STEPS); 0 = single fused pass."""
        try:
            return max(int(getattr(
                load_settings(), "denoise_chunk_steps", 0) or 0), 0)
        except Exception:
            return 0

    def _chunk_programs(self, key, controlnet_module, geo, mesh, chunk,
                        lora_sig=None, analytic_flops=None):
        """(prep, chunk_for, decode, lengths, lo) — the compiled program
        set for one bucket under one geometry, plus the chunk walk it
        serves. ``chunk_for(n)`` resolves the compiled length-n step
        chunk — the walk's lengths are resolved eagerly here (so the
        caller's compile span stays honest), while a length the original
        walk never needed (a mid-pass RESUME's remainder chunk, ISSUE 18)
        compiles on first request under the same cache key scheme.
        Shared by the chunked runner and the mid-pass re-shard path
        (which resolves the TARGET geometry's set lazily at the first
        seam that needs it; the walk is bucket-derived, so both
        geometries share it). Adapter passes (lora_sig) suffix only
        the STEP chunks: prep and decode never see the lora operand, so
        adapter and plain passes share those compiled programs."""
        prep_fn, make_steps, decode_fn, (lo, hi) = self._denoise_parts(
            key, controlnet_module, mesh=mesh)
        lengths: list[int] = []
        pos = lo
        while pos < hi:
            lengths.append(min(chunk, hi - pos))
            pos += lengths[-1]
        gkey = self._geo_key(key, geo)
        skey = self._sig_key(gkey, lora_sig)
        prep_prog = self._program((gkey, "prep"), lambda: prep_fn,
                                  kind="prep")
        # the analytic count covers the whole denoise span; a length-n
        # chunk owns its proportional share of the (hi - lo) steps
        per_step = (analytic_flops / (hi - lo)
                    if analytic_flops and hi > lo else None)

        def chunk_for(n: int):
            n = int(n)
            return self._program(
                (skey, "chunk", n), lambda: make_steps(n), kind="chunk",
                analytic_flops=(per_step * n if per_step else None))

        for n in set(lengths):
            chunk_for(n)
        decode_prog = self._program((gkey, "decode"), lambda: decode_fn,
                                    kind="decode")
        return prep_prog, chunk_for, decode_prog, lengths, lo

    def _migrate_operands(self, mesh, operands: tuple) -> tuple:
        """Re-place a chunked pass's live operands onto another mesh view
        of the same chips (the chunk-seam re-shard): leading-batch arrays
        keep their data-axis sharding when divisible, everything else
        replicates. Pure data movement — values are bit-identical, so a
        migrated pass equals an undisturbed one up to the float
        reassociation the geometries themselves differ by."""

        def place(x):
            if getattr(x, "ndim", 0) == 0:
                return jax.device_put(x, replicated(mesh))
            return self._place_batch(x, mesh=mesh)

        # tree_map traverses dicts (added, cn_params), skips Nones, and
        # applies directly to bare arrays (latents, context, rng keys)
        return tuple(jax.tree_util.tree_map(place, op) for op in operands)

    def _rehydrate(self, resume, latents, state, mesh, lo, hi):
        """Swap a freshly-prepped (latents, scheduler state) for a
        checkpoint's arrays (ISSUE 18 resume-on-redelivery): prep
        supplies the pytree STRUCTURE and the placement recipe, the
        checkpoint supplies values, so the resumed chunk programs see
        operands indistinguishable from an undisturbed pass at step K.
        Validates the step against this bucket's denoise span and every
        array against its prepped twin — any mismatch raises and the
        caller degrades to the full pass."""
        at = int(resume.get("step", lo))
        if not (lo < at < hi):
            raise ValueError(
                f"resume step {at} outside the denoise span [{lo}, {hi})")
        ck_latents = np.asarray(resume["latents"])
        if (tuple(ck_latents.shape) != tuple(latents.shape)
                or ck_latents.dtype != np.dtype(latents.dtype)):
            raise ValueError(
                f"checkpoint latents {ck_latents.dtype}{ck_latents.shape} "
                f"do not match this bucket's "
                f"{np.dtype(latents.dtype)}{tuple(latents.shape)}")
        leaves, treedef = jax.tree_util.tree_flatten(state)
        ck_leaves = list(resume.get("state_leaves") or [])
        if len(ck_leaves) != len(leaves):
            raise ValueError(
                f"checkpoint carries {len(ck_leaves)} scheduler leaves, "
                f"this program has {len(leaves)}")

        def place(x):
            if getattr(x, "ndim", 0) == 0:
                return jax.device_put(jnp.asarray(x), replicated(mesh))
            return self._place_batch(jnp.asarray(x), mesh=mesh)

        placed = []
        for fresh, ck in zip(leaves, ck_leaves):
            ck = np.asarray(ck)
            if (tuple(ck.shape) != tuple(getattr(fresh, "shape", ()))
                    or ck.dtype != np.dtype(fresh.dtype)):
                raise ValueError("checkpoint scheduler leaf mismatch")
            placed.append(place(ck))
        return (at, place(ck_latents),
                jax.tree_util.tree_unflatten(treedef, placed))

    def _denoise_runner(self, key, controlnet_module=None, geo=None,
                        lora_sig=None, analytic_flops=None):
        """Resolve the execution strategy for one bucket. Returns
        ``runner(*program_args, cancel_probe=None, reshard_probe=None)
        -> uint8 pixels``.

        denoise_chunk_steps=0: the fused single program — the probe (if
        any) runs once before launch, so a job cancelled while it waited
        for the slice still aborts for free, but a cancel landing
        mid-pass waits out the full pass (the pre-chunking behavior).

        denoise_chunk_steps=N: prep, length-N step chunks (plus one
        remainder chunk), and decode are separate compiled programs; the
        probe runs between every chunk, so a cancelled pass frees the
        slice within one chunk. All programs are resolved (and counted,
        and compiled) HERE, not lazily mid-loop, so the caller's compile
        span stays honest.

        `geo` selects the mesh view ((tensor, seq) over the slice's
        chips; None = the construction default). The chunk boundary is
        also the RE-SHARD seam (ISSUE 12): `reshard_probe`, consulted at
        every boundary next to the cancel probe, may return a different
        validated geometry — the runner then re-places the live latents
        / conditioning onto the new mesh view and continues with that
        geometry's compiled chunk set, so a pass can migrate
        sharded->replicated (or back) mid-denoise when the queue shifts."""
        chunk = self._denoise_chunk_steps()
        geo = self.default_geometry if geo is None else geo
        cache_key = (key, chunk, geo, lora_sig)
        with self._jit_lock:
            cached = self._runner_cache.get(cache_key)
            if cached is not None:
                self._runner_cache.move_to_end(cache_key)
        if cached is not None:
            return cached
        mesh, _ = self._geometry_view(geo)
        if chunk <= 0:
            program = self._denoise_program(
                key, controlnet_module, geo=geo, mesh=mesh,
                lora_sig=lora_sig, analytic_flops=analytic_flops)

            def runner(*args, cancel_probe=None, reshard_probe=None,
                       boundary_cb=None, resume=None):
                # no chunk seams: a fused pass cannot re-shard, cannot
                # checkpoint, and cannot resume mid-flight — boundary_cb
                # and resume are accepted (and ignored) so the caller
                # need not care which strategy resolved
                if cancel_probe is not None:
                    cancel_probe()
                return program(*args)
        else:
            prep_prog, chunk_progs, decode_prog, lengths, lo = \
                self._chunk_programs(key, controlnet_module, geo, mesh,
                                     chunk, lora_sig=lora_sig,
                                     analytic_flops=analytic_flops)

            def runner(params, init_rng, context, added, guidance_scale,
                       image_guidance, image_latents, mask, rng,
                       cn_params, control_cond, cn_scale, lora,
                       cancel_probe=None, reshard_probe=None,
                       boundary_cb=None, resume=None):
                # Each boundary BLOCKS on the previous chunk before
                # probing. This sync is load-bearing, not optional: jax
                # dispatches asynchronously, so without it the host
                # races through every chunk_prog call in milliseconds
                # and all probes fire before the first chunk's compute
                # finishes — a mid-pass cancel could never interject
                # (observed empirically in the e2e drive). Chunks are
                # data-dependent, so no device-side pipelining is lost;
                # the happy-path cost is one host round trip per chunk,
                # microseconds against a multi-second chunk. A pass
                # with no probe (direct pipeline calls) runs free.
                from ..ops.attention import sequence_parallel_scope

                cur_geo, cur_mesh = geo, mesh
                cur_chunks, cur_decode = chunk_progs, decode_prog
                resharded: list[tuple] = []
                if cancel_probe is not None:
                    cancel_probe()
                latents, state = prep_prog(params, init_rng, image_latents)
                at = lo
                hi = lo + sum(lengths)
                walk = lengths
                if resume is not None:
                    # rehydrate at the checkpointed step: prep already
                    # produced the right state STRUCTURE and sharding,
                    # so the checkpointed leaves just replace the fresh
                    # ones. Any mismatch (shape drift, torn blob)
                    # degrades to the full pass — resume is an
                    # optimization, never a gate
                    try:
                        at, latents, state = self._rehydrate(
                            resume, latents, state, cur_mesh, lo, hi)
                    except Exception:
                        logger.warning(
                            "checkpoint rehydration failed; running the "
                            "full pass", exc_info=True)
                        at = lo
                    if at != lo:
                        walk = []
                        pos = at
                        while pos < hi:
                            walk.append(min(chunk, hi - pos))
                            pos += walk[-1]
                self._last_resume_step = at if at != lo else None
                start_at = at
                for n in walk:
                    if at != start_at and (cancel_probe is not None
                                           or reshard_probe is not None
                                           or boundary_cb is not None):
                        jax.block_until_ready(latents)
                        if cancel_probe is not None:
                            cancel_probe()
                        if reshard_probe is not None:
                            target = reshard_probe()
                            if target is not None:
                                target = self.resolve_geometry(target)
                            if target is not None and target != cur_geo:
                                # a cold target program set compiles
                                # HERE, inside the caller's denoise
                                # span — timed so run() can re-attribute
                                # it to the compile stage (a multi-
                                # second XLA compile folded into the
                                # denoise EWMA would trip the PR 11
                                # straggler detector on exactly the
                                # shard-capable workers shard_hold
                                # prefers)
                                t0 = time.perf_counter()
                                cur_mesh, geo_params = self._geometry_view(
                                    target)
                                with sequence_parallel_scope(cur_mesh):
                                    _, cur_chunks, cur_decode, _, _ = \
                                        self._chunk_programs(
                                            key, controlnet_module, target,
                                            cur_mesh, chunk,
                                            lora_sig=lora_sig,
                                            analytic_flops=analytic_flops)
                                compile_s = time.perf_counter() - t0
                                (latents, state, context, added,
                                 image_latents, mask, rng, cn_params,
                                 control_cond) = self._migrate_operands(
                                    cur_mesh,
                                    (latents, state, context, added,
                                     image_latents, mask, rng, cn_params,
                                     control_cond))
                                params = geo_params
                                logger.info(
                                    "re-sharded mid-pass at step %d: "
                                    "%s -> %s", at, cur_geo, target)
                                resharded.append(
                                    (cur_geo, target, at, compile_s))
                                cur_geo = target
                        if boundary_cb is not None:
                            # durability/preview seam (ISSUE 18): hand
                            # the host the live latents + scheduler
                            # state, plus a lazy decode bound to the
                            # CURRENT geometry's program — the callback
                            # decides whether this boundary is due
                            def _decode(latents=latents, params=params,
                                        dec=cur_decode, m=cur_mesh):
                                with sequence_parallel_scope(m):
                                    return dec(params, latents)

                            boundary_cb(at, latents, state, _decode)
                    with sequence_parallel_scope(cur_mesh):
                        latents, state = cur_chunks(n)(
                            params, latents, state, context, added,
                            guidance_scale, image_guidance, image_latents,
                            mask, rng, cn_params, control_cond, cn_scale,
                            lora, jnp.int32(at))
                    at += n
                if cancel_probe is not None:
                    jax.block_until_ready(latents)
                    cancel_probe()
                self._last_reshards = resharded
                with sequence_parallel_scope(cur_mesh):
                    return cur_decode(params, latents)

        with self._jit_lock:
            self._runner_cache[cache_key] = runner
            self._runner_cache.move_to_end(cache_key)
            self._trim_program_caches()
        return runner

    @staticmethod
    def _solo_cancel_probe():
        """Abort probe for a single-job pass: raises JobCancelled when
        the job pinned on this executor thread (the telemetry trace
        context) has been revoked by the hive. None when no job id is
        pinned (direct pipeline calls, tests, tools)."""
        from ..cancel import JobCancelled, cancelled, current_job_ids

        ids = current_job_ids()
        if not ids:
            return None

        def probe():
            if any(cancelled(j) for j in ids):
                raise JobCancelled(ids)

        return probe

    # --- public job API ---

    def run(self, prompt="", negative_prompt="", pipeline_type="DiffusionPipeline",
            **kwargs):
        """Execute one job; returns (list[PIL.Image], pipeline_config).

        `geometry` ({"tensor": t, "seq": s} or (t, s); ISSUE 12) asks for
        a per-pass mesh view over the slice's chips: an interactive job
        fans ONE image's attention heads / sequence blocks across every
        chip for latency instead of the default data-parallel view.
        Requests that cannot mesh — or that arrive with per-job structure
        the sharded placement does not cover (LoRA-merged or custom
        params, ControlNet) — fall back to the default view and the pass
        runs exactly as before. `reshard_probe` (chunked passes only) is
        consulted at every denoise chunk boundary and may return a new
        geometry to migrate the live pass to (the chunk-seam re-shard)."""
        geometry = kwargs.pop("geometry", None)
        reshard_probe = kwargs.pop("reshard_probe", None)
        # preemption-tolerant denoise (ISSUE 18): the worker arms the
        # chunk boundary with these. All default to off/None, so direct
        # pipeline calls and the classic fused path stay byte-identical.
        ckpt_every = int(kwargs.pop("checkpoint_every_chunks", 0) or 0)
        preview_every = int(kwargs.pop("preview_every_chunks", 0) or 0)
        checkpoint_cb = kwargs.pop("checkpoint_cb", None)
        preview_cb = kwargs.pop("preview_cb", None)
        resume_offer = kwargs.pop("resume", None)
        if (
            kwargs.get("controlnet_prepipeline_type")
            and kwargs.get("controlnet_model_name")
            and kwargs.get("mask_image") is None
        ):
            # NB the hive's txt2img-ControlNet wire puts the QR image in
            # `image` (job_arguments.py format_controlnet_args), so the
            # guard must not require image=None; _run_qr_two_stage sorts
            # control vs start image out
            return self._run_qr_two_stage(
                prompt, negative_prompt, pipeline_type, **kwargs
            )
        # snapshot at entry: registry LRU eviction may release() this bundle
        # mid-job from another thread; the snapshot keeps this job's arrays
        # alive (and correct) until it finishes
        base_params = self.params
        if base_params is None:
            raise Exception(
                f"pipeline {self.model_name} was evicted; resubmit the job"
            )
        timings: dict[str, float] = {}
        steps = int(kwargs.pop("num_inference_steps", 30))
        guidance_scale = float(kwargs.pop("guidance_scale", 7.5))
        n_images = int(kwargs.pop("num_images_per_prompt", 1))
        scheduler_type = kwargs.pop("scheduler_type", "DPMSolverMultistepScheduler")
        rng = kwargs.pop("rng", None)
        if rng is None:
            rng = jax.random.key(0)
        kwargs.pop("chipset", None)

        image = kwargs.pop("image", None)
        mask_image = kwargs.pop("mask_image", None)
        strength = float(kwargs.pop("strength", 0.75))
        image_guidance = kwargs.pop("image_guidance_scale", None)

        # chained stages (reference pipeline_steps.py:40-105 semantics)
        refiner = kwargs.pop("refiner", None)
        upscale = bool(kwargs.pop("upscale", False))
        upscaler = None
        upscale_fallback = False
        if upscale:
            # resolve (and weight-check) the upscaler BEFORE spending the
            # denoise: a missing-weights failure must not cost a full job
            from ..registry import get_pipeline
            from ..weights import MissingWeightsError
            from .upscale import upscaler_name_for

            try:
                upscaler = get_pipeline(
                    upscaler_name_for(self.model_name),
                    pipeline_type="StableDiffusionLatentUpscalePipeline",
                    chipset=self.chipset,
                )
            except MissingWeightsError:
                # no converted sd-x2 weights on this worker: serve the job
                # anyway with the latent-resize path and record the
                # degradation in pipeline_config instead of failing
                logger.warning(
                    "sd-x2 upscaler weights missing; falling back to "
                    "latent-resize 2x for this job"
                )
                upscale_fallback = True

        lora = kwargs.pop("lora", None)
        # reference wire: scale rides in cross_attention_kwargs.scale
        # (swarm/job_arguments.py lora path) or a direct lora_scale
        xattn_kwargs = kwargs.pop("cross_attention_kwargs", {}) or {}
        lora_scale = float(kwargs.pop("lora_scale", xattn_kwargs.get("scale", 1.0)))
        kwargs.pop("lora_rank", None)  # advisory coalesce-key hint only
        # adapter routing (ISSUE 13): runtime per-row delta against the
        # ONE resident base tree whenever the adapter is delta-eligible;
        # merged-tree copy only as the fallback. lora_mode feeds the
        # swarm_lora_rows_total counter + the envelope.
        lora_operands, lora_sig, delta_factors = None, None, None
        lora_mode = "none"
        self.last_operand_stats = None  # adapter-free passes stamp nothing
        job_params = base_params
        if lora is not None:
            delta_factors = self._adapter_delta_factors(lora)
            if delta_factors is not None:
                # operands are stacked per ROW further down, once the
                # final row count is known (a list of start images
                # rewrites num_images_per_prompt)
                lora_mode = "delta"
            else:
                job_params = self._lora_params(base_params, lora, lora_scale)
                lora_mode = "merged"

        # per-job conditioning/decoding add-ons (reference
        # diffusion_func.py:46-49 custom VAE, :105-111 textual inversion)
        job_tokenizers = None
        job_extras = None
        ti_ref = kwargs.pop("textual_inversion", None)
        if ti_ref:
            job_extras, job_tokenizers = self._ti_apply(ti_ref)
        vae_ref = kwargs.pop("vae", None)
        if vae_ref:
            job_params = dict(job_params)
            job_params["vae"] = self._custom_vae(str(vae_ref))

        # --- ControlNet wire args (swarm/job_arguments.py:330-397 parity) ---
        controlnet_name = kwargs.pop("controlnet_model_name", None)
        cn_scale = float(kwargs.pop("controlnet_conditioning_scale", 1.0))
        cg_start = float(kwargs.pop("control_guidance_start", 0.0))
        cg_end = float(kwargs.pop("control_guidance_end", 1.0))
        for drop in ("controlnet_model_type", "save_preprocessed_input"):
            kwargs.pop(drop, None)
        kwargs.pop("controlnet_prepipeline_type", None)  # handled at entry
        control_image = kwargs.pop("control_image", None)
        if controlnet_name and control_image is None:
            # diffusers txt2img-ControlNet convention: `image` IS the control
            control_image, image = image, None

        if isinstance(image, (list, tuple)):
            n_images = len(image)  # batch of distinct start images
        height = kwargs.pop("height", None)
        width = kwargs.pop("width", None)
        if height is None and image is not None:
            width, height = (
                image[0].size if isinstance(image, (list, tuple)) else image.size
            )
        if height is None and control_image is not None:
            width, height = control_image.size
        height = int(height or self.default_size)
        width = int(width or self.default_size)
        # XLA static shapes: canvas snaps to the /64 grid the reference also
        # used for condition images (swarm/pre_processors/image_utils.py:43-51)
        height, width = (max(64, (d // 64) * 64) for d in (height, width))
        lh, lw = height // self.latent_factor, width // self.latent_factor

        if mask_image is not None:
            if image is None:
                # without an init image the placeholder zeros would decode as
                # garbage in the unmasked region — job-level error instead
                raise ValueError("inpaint requires an init image. None provided")
            # dedicated inpaint checkpoints take mask + masked-image latents
            # on the channel dim (full denoise); 4-channel models use latent
            # masking along the original's noise trajectory
            mode = "inpaint9" if self.is_inpaint_unet else "inpaint"
        elif image is not None and self.is_pix2pix:
            mode = "pix2pix"
            if controlnet_name:
                raise ValueError(
                    "ControlNet is not supported with instruct-pix2pix models"
                )
            if image_guidance is None:
                image_guidance = 1.5  # edit-checkpoint default
        elif image is not None:
            mode = "img2img"
        else:
            mode = "txt2img"

        t_start = 0
        if mode in ("img2img", "inpaint"):
            t_start = min(max(int(steps * (1.0 - strength)), 0), steps - 1)

        # --- per-row adapter operand (ISSUE 13/16), stacked at the FINAL
        # row count (the start-image list above rewrote it last) and
        # BEFORE text encode, so TE-LoRA factors ride the same resident
        # stacks into the encoder: every row of this job carries slot 1
        te_operands = None
        if delta_factors is not None:
            from .. import lora_cache
            from .lora_runtime import row_operands

            lora_operands, lora_sig = self._lora_operands(
                [delta_factors], [1] * n_images, [lora_scale] * n_images,
                adapter_keys=(lora_cache.adapter_key(lora),))
            if any(":" in p for p in lora_sig[2]):
                # the adapter carries text-encoder content: the encode
                # batch is [negatives*N | prompt*N], every row slot 1
                te_operands = row_operands(
                    lora_operands["a"], lora_operands["b"],
                    [1] * (2 * n_images), [lora_scale] * (2 * n_images))

        # --- conditioning: one batched pass, rows [uncond*N | cond*N];
        # pix2pix duplicates the uncond rows for its image-only CFG row ---
        with Span("text_encode", timings):
            cfg_rows = 3 if mode == "pix2pix" else 2
            texts = [negative_prompt] * n_images + [prompt] * n_images
            context, pooled = self.encode_prompts(
                texts, job_params, tokenizers=job_tokenizers,
                extra_embeddings=job_extras, te_operands=te_operands,
            )
            pooled_u = pooled[:n_images] if pooled is not None else None
            pooled_c = pooled[n_images:] if pooled is not None else None
            if cfg_rows == 3:
                context = jnp.concatenate(
                    [context[:n_images], context], axis=0)

            added = None
            if self.is_xl:
                ids = self._xl_time_ids(
                    pooled_c.shape[-1], height, width,
                    float(kwargs.pop("aesthetic_score", 6.0)),
                )
                time_ids = jnp.asarray(
                    [ids] * (cfg_rows * n_images), jnp.float32)
                pooled_rows = [pooled_u] * (cfg_rows - 1) + [pooled_c]
                added = {
                    "text_embeds": jnp.concatenate(pooled_rows, axis=0),
                    "time_ids": time_ids,
                }

        # --- latents (initial noise is drawn inside the jitted program) ---
        rng, init_rng, step_rng = jax.random.split(rng, 3)
        latent_c = self.latent_channels

        # rank-preserving (1,1,1,C) placeholders when a mode doesn't use an
        # input — no dead full-res buffers riding along (program cache is
        # keyed by mode, so shapes are consistent per bucket)
        image_latents = jnp.zeros((1, 1, 1, latent_c), jnp.float32)
        mask = jnp.zeros((1, 1, 1, 1), jnp.float32)
        if image is not None:
            # one start image broadcast over the batch, or a list of distinct
            # images (e.g. vid2vid frames batched through one program)
            if isinstance(image, (list, tuple)):
                pixels = jnp.stack(
                    [jnp.asarray(_pil_to_array(im, width, height)) for im in image]
                )
            else:
                pixels = jnp.broadcast_to(
                    jnp.asarray(_pil_to_array(image, width, height))[None],
                    (n_images, height, width, 3),
                )
            if mode == "inpaint9":
                # the 9-channel checkpoint conditions on the MASKED image:
                # repaint region blanked before encoding
                mask_px = np.asarray(
                    mask_image.convert("L").resize(
                        (width, height), Image.NEAREST
                    ),
                    np.float32,
                )[None, ..., None] / 255.0
                pixels = pixels * jnp.asarray(mask_px <= 0.5, jnp.float32)
            image_latents = self._vae_encode_program(
                job_params["vae"], pixels.astype(self.dtype)
            )
            if mode == "pix2pix":
                # the edit checkpoint conditions on raw latent-dist modes —
                # undo the sampling scale our encode applies
                image_latents = image_latents / self.vae.config.scaling_factor
        if mask_image is not None:
            m = jnp.asarray(
                _mask_to_latent_array(mask_image, width, height, self.latent_factor)
            )[None]
            mask = jnp.broadcast_to(m, (n_images, lh, lw, 1))

        controlnet_module, cn_params, cn_key = None, {}, None
        control_cond = jnp.zeros((1, 1, 1, 3), jnp.float32)
        if controlnet_name and control_image is None:
            # reference parity: job-level error, not a crash
            # (swarm/job_arguments.py:331 "Controlnet specified but no
            # control image provided")
            raise ValueError("Controlnet specified but no control image provided")
        if controlnet_name:
            controlnet_module, cn_params = self._get_controlnet(controlnet_name)
            # diffusers ControlNet conditioning is [0, 1], not [-1, 1]
            cond = (
                _pil_to_array(control_image, width, height) + 1.0
            ) / 2.0
            control_cond = jnp.broadcast_to(
                jnp.asarray(cond)[None], (n_images, height, width, 3)
            )
            cn_key = (
                controlnet_name,
                int(cg_start * steps),
                max(int(np.ceil(cg_end * steps)), int(cg_start * steps) + 1),
            )

        # --- pick the pass's mesh view (ISSUE 12): sharded geometry only
        # for passes on the resident base params — LoRA-merged / custom
        # trees and ControlNet branches live on the default mesh, and a
        # geometry request for them degrades to the classic pass ---
        geo = self.resolve_geometry(geometry)
        if geo != self.default_geometry and (
                job_params is not base_params or controlnet_module is not None
                or lora_operands is not None):
            logger.info(
                "geometry %s refused for a pass with job-specific params; "
                "serving the default view", geo)
            geo = self.default_geometry
        pass_mesh, geo_params = self._geometry_view(geo)
        if geo != self.default_geometry:
            job_params = geo_params

        # --- shard or replicate over the slice (per array: placeholders
        # with batch dim 1 stay replicated; the CFG-doubled 2N batch shards
        # evenly iff N does) ---
        context, image_latents, mask, control_cond = (
            self._place_batch(x, mesh=pass_mesh)
            for x in (context, image_latents, mask, control_cond)
        )
        if added is not None:
            added = {k: self._place_batch(v, mesh=pass_mesh)
                     for k, v in added.items()}

        # --- compile (cached) + execute ---
        sched_cfg = SchedulerConfig(
            prediction_type=self.prediction_type,
            use_karras_sigmas=bool(kwargs.pop("use_karras_sigmas", False)),
        )
        sched_key = (
            scheduler_type,
            tuple(sorted(dataclass_items(sched_cfg))),
        )
        key = (mode, lh, lw, n_images, steps, sched_key, t_start, cn_key)
        # analytic UNet FLOPs of this pass (models/flops.py) — the cost
        # stamp's numerator AND the program ledger's divergence hint
        from ..models.flops import denoise_flops

        pass_flops_raw = denoise_flops(
            self.unet.config, lh, lw, n_images, steps - t_start,
            cfg_rows=cfg_rows)
        # stage "compile" is program-cache resolution: ~0 on a hit, the
        # full trace+XLA compile on a miss (swarm_compile_cache_total
        # tells the two apart in aggregate). With denoise_chunk_steps>0
        # the runner resolves the whole chunked program set here.
        with Span("compile", timings, key="trace_s"):
            runner = self._denoise_runner(
                key, controlnet_module, geo=geo, lora_sig=lora_sig,
                analytic_flops=pass_flops_raw)

        # --- preemption-tolerant denoise (ISSUE 18): the program
        # signature pins which compiled-program family a checkpoint is
        # valid for — a resume offer cut under a different (model, bucket,
        # dtype, geometry) would feed latents to a program with a
        # different meaning of "step K", so it degrades to a full pass,
        # never an error. boundary_cb turns the chunk seam into the
        # durability/preview seam at the knobbed cadence. ---
        boundary_cb = None
        resume_state = None
        chunk_steps = self._denoise_chunk_steps()
        arm_ckpt = checkpoint_cb is not None and ckpt_every > 0
        arm_preview = preview_cb is not None and preview_every > 0
        if chunk_steps > 0 and (resume_offer is not None
                                or arm_ckpt or arm_preview):
            from .. import checkpoint as _ckpt

            pass_signature = _ckpt.program_signature(
                self.model_name, key, self.dtype, geo)
            if resume_offer is not None:
                if str(resume_offer.get("signature", "")) == pass_signature:
                    resume_state = resume_offer
                else:
                    logger.warning(
                        "resume offer signature %s does not match this "
                        "pass's %s; running the full pass",
                        resume_offer.get("signature"), pass_signature)
            if arm_ckpt or arm_preview:
                boundaries = {"n": 0}

                def boundary_cb(step, latents, state, decode,
                                _sig=pass_signature):
                    boundaries["n"] += 1
                    k = boundaries["n"]
                    if arm_ckpt and k % ckpt_every == 0:
                        leaves = jax.tree_util.tree_leaves(state)
                        checkpoint_cb(
                            int(step), np.asarray(latents),
                            [np.asarray(x) for x in leaves], _sig)
                    if arm_preview and k % preview_every == 0:
                        preview_cb(int(step), np.asarray(decode()))

        # long-sequence self-attention shards over the mesh seq axis (ring
        # attention) when this pass's mesh view carved one out; trace-time
        # routing, so it binds on the first (tracing) call of each bucket
        from ..ops.attention import sequence_parallel_scope

        # a re-shard mid-pass must only swap between BASE-params views —
        # the same gate as the initial geometry above, ControlNet
        # included (its branch params never get geometry placement, so a
        # probe migrating a ControlNet pass onto a sharded mesh would
        # run the exact combination the initial gate refuses)
        if controlnet_module is not None or lora_operands is not None or (
                job_params is not base_params
                and job_params is not geo_params):
            reshard_probe = None
        self._last_reshards = []
        self._last_resume_step = None
        with Span("denoise", timings, key="denoise_decode_s"):
            with sequence_parallel_scope(pass_mesh):
                pixels = runner(
                    job_params,
                    init_rng,
                    context,
                    added,
                    jnp.float32(guidance_scale),
                    jnp.float32(image_guidance or 0.0),
                    image_latents,
                    mask,
                    step_rng,
                    cn_params,
                    control_cond,
                    jnp.float32(cn_scale),
                    # stacked per-row adapter factors (ISSUE 13); the
                    # empty dict traces to the identical adapter-free HLO
                    lora_operands or {},
                    # a hive-revoked job aborts at the next chunk
                    # boundary (JobCancelled propagates to the worker,
                    # which frees the slice and produces no envelope)
                    cancel_probe=self._solo_cancel_probe(),
                    # the chunk boundary doubles as the re-shard seam
                    reshard_probe=reshard_probe,
                    # ... and the durability/preview seam (ISSUE 18)
                    boundary_cb=boundary_cb,
                    resume=resume_state,
                )
            pixels = jax.block_until_ready(pixels)
        # a mid-pass re-shard that had to COMPILE its target program set
        # did so inside the denoise span; move those seconds to the
        # compile stage so the straggler EWMAs see honest denoise time
        reshard_compile = sum(
            entry[3] for entry in self._last_reshards if len(entry) > 3)
        if reshard_compile > 0.01:
            timings["denoise_decode_s"] = round(max(
                timings.get("denoise_decode_s", 0.0) - reshard_compile,
                0.0), 3)
            timings["trace_s"] = round(
                timings.get("trace_s", 0.0) + reshard_compile, 3)
        pass_geometry = {
            "data": pass_mesh.shape.get("data", 1),
            "tensor": pass_mesh.shape.get("tensor", 1),
            "seq": pass_mesh.shape.get("seq", 1),
        }
        _SHARDED_PASSES.inc(geometry=geometry_label(
            pass_geometry["tensor"], pass_geometry["seq"]))
        if self.chipset is not None:
            self.chipset.note_geometry(**pass_geometry)
        from .lora_runtime import LORA_ROWS

        LORA_ROWS.inc(n_images, mode=lora_mode)

        images = _to_pil(np.asarray(pixels))

        if refiner is not None:
            # SDXL refiner stage (reference pipeline_steps.py:40-68): the
            # base output re-enters a second resident pipeline as img2img
            from ..registry import get_pipeline

            refiner_pipe = get_pipeline(
                refiner["model_name"],
                pipeline_type="StableDiffusionXLImg2ImgPipeline",
                chipset=self.chipset,
            )
            t0 = time.perf_counter()
            refiner_kw = dict(
                prompt=prompt,
                negative_prompt=negative_prompt,
                strength=float(refiner.get("strength", 0.3)),
                num_inference_steps=steps,
                guidance_scale=guidance_scale,
                scheduler_type=scheduler_type,
            )
            # one batched refiner call: the whole base batch denoises as a
            # single jitted program with per-image noise (no per-image Python
            # loop, no shared rng trajectory across the batch)
            try:
                images, _ = refiner_pipe.run(
                    image=list(images), rng=rng, **refiner_kw
                )
            except Exception as e:
                if "RESOURCE_EXHAUSTED" not in str(e) and "emory" not in str(e):
                    raise
                # memory-tight slice: fall back to sequential batch-1 calls
                # with per-image keys
                logger.warning("batched refiner OOM; refining sequentially")
                refined = []
                for idx, img in enumerate(images):
                    out, _ = refiner_pipe.run(
                        image=img, rng=jax.random.fold_in(rng, idx), **refiner_kw
                    )
                    refined.extend(out)
                images = refined
            timings["refiner_s"] = round(time.perf_counter() - t0, 3)

        if upscaler is not None:
            # learned SD-x2 latent upscaler stage (reference upscale.py:5-36
            # chained at diffusion_func.py:163; 20 unguided steps)
            t0 = time.perf_counter()
            images = upscaler.upscale(
                list(images), prompt=prompt, negative_prompt=negative_prompt,
                rng=jax.random.fold_in(rng, 0x5d2),
            )
            timings["upscale_s"] = round(time.perf_counter() - t0, 3)
        elif upscale_fallback:
            # per-image calls: the 2x decode has 4x the activation footprint,
            # and a fallback path must not be the thing that OOMs the job
            t0 = time.perf_counter()
            out = []
            for im in images:
                px = jnp.asarray(_pil_to_array(im, width, height))[None]
                up = np.asarray(
                    self._latent2x_program(job_params["vae"], px)
                )
                out.append(Image.fromarray(up[0]))
            images = out
            timings["upscale_s"] = round(time.perf_counter() - t0, 3)

        # resumed passes (ISSUE 18) recomputed only steps >= from_step;
        # the cost stamp (and so the tenant ledger) bills that fraction,
        # not the full pass the FIRST delivery already burned
        resumed_info = None
        resume_at = getattr(self, "_last_resume_step", None)
        if resume_at is not None:
            resumed_info = {
                "from_step": int(resume_at),
                "recomputed_steps": int(steps - resume_at),
            }
        billed_flops = pass_flops_raw
        if resumed_info is not None and steps > t_start:
            billed_flops = int(round(
                pass_flops_raw * resumed_info["recomputed_steps"]
                / (steps - t_start)))
        # per-pass cost figures (ISSUE 17): a solo pass IS its own job,
        # so the job's flops equal the pass flops
        cost = costs.job_cost(
            costs.pass_cost(
                model=self.model_name,
                pass_flops=billed_flops,
                denoise_s=timings.get("denoise_decode_s"),
                chips=(self.chipset.chip_count() if self.chipset is not None
                       else 1),
                device=jax.devices()[0] if jax.devices() else None,
                geometry=geometry_label(pass_geometry["tensor"],
                                        pass_geometry["seq"]),
            ),
            billed_flops,
        )

        pipeline_config = {
            "model": self.model_name,
            "pipeline": pipeline_type,
            "scheduler": scheduler_type,
            "controlnet": controlnet_name,
            "mode": mode,
            "steps": steps,
            "size": [width, height],
            "guidance_scale": guidance_scale,
            **(
                {"image_guidance_scale": image_guidance}
                if mode == "pix2pix"
                else {}
            ),
            # a pix2pix job routed to a non-edit checkpoint degrades to plain
            # img2img — record the approximation so callers can tell
            **(
                {"approximated_as": "img2img"}
                if image_guidance is not None and mode == "img2img"
                else {}
            ),
            # `size` stays the requested canvas (reference parity); the
            # learned upscaler stage doubles the actual output
            **(
                {"output_size": [2 * width, 2 * height], "upscaled": True}
                if upscaler is not None or upscale_fallback
                else {}
            ),
            **(
                {"upscaler": "latent-resize-fallback"}
                if upscale_fallback
                else {}
            ),
            # analytic UNet FLOPs of the denoise loop -> MFU in the bench
            "unet_tflops": round(pass_flops_raw / 1e12, 4),
            # serving-path cost stamp (ISSUE 17): the job's own integer
            # FLOPs plus the pass's achieved TFLOP/s and MFU (null where
            # the platform has no peak entry — CPU smoke)
            "cost": cost,
            # adapter execution path (ISSUE 13): "delta" = runtime
            # per-row low-rank delta on the resident base tree,
            # "merged" = full merged-tree fallback copy
            **({"lora_mode": lora_mode} if lora is not None else {}),
            # per-pass prompt-embedding cache stats (tenant accounting:
            # the hive attributes these hits to the job's submitter)
            **({"embed_cache": {
                "hits": self.last_encode_stats[0],
                "misses": self.last_encode_stats[1]}}
               if getattr(self, "last_encode_stats", None) else {}),
            # operand-residency stats (ISSUE 16): bytes_saved is the
            # host->device upload the resident stacks spared this pass
            # (the tenant ledger attributes it to the job's submitter)
            **({"operand_cache": dict(self.last_operand_stats)}
               if getattr(self, "last_operand_stats", None) else {}),
            # the mesh view this pass STARTED under (ISSUE 12) — the
            # end-to-end proof that the class actually picked the
            # geometry; `resharded` records any chunk-seam migrations as
            # (from_geo, to_geo, step) triples
            "geometry": pass_geometry,
            **({"resharded": [
                {"from": list(f), "to": list(t), "step": int(s),
                 "compile_s": round(c, 3)}
                for f, t, s, c in self._last_reshards]}
               if getattr(self, "_last_reshards", None) else {}),
            # resume-on-redelivery (ISSUE 18): this pass rehydrated a
            # checkpoint at from_step and recomputed only the remainder
            **({"resumed": resumed_info} if resumed_info else {}),
            "timings": timings,
        }
        return images, pipeline_config

    def run_batched(self, requests: list[dict], *, height=None, width=None,
                    num_inference_steps: int = 30, guidance_scale: float = 7.5,
                    scheduler_type: str = "DPMSolverMultistepScheduler",
                    use_karras_sigmas: bool = False,
                    pipeline_type: str = "DiffusionPipeline",
                    strength: float = 0.75,
                    controlnet_model_name: str | None = None,
                    control_image=None,
                    controlnet_conditioning_scale: float = 1.0,
                    control_guidance_start: float = 0.0,
                    control_guidance_end: float = 1.0,
                    lora_slots_max: int | None = None):
        """Coalesced txt2img/img2img: N independent requests, ONE padded
        jitted denoise+decode invocation (batching.py design).

        requests: [{"prompt", "negative_prompt", "rng",
        "num_images_per_prompt", "image"?, "lora"?, "lora_scale"?}] —
        everything that must match across the batch (model, canvas,
        steps, scheduler, guidance, img2img strength, shared ControlNet)
        arrives as shared keyword arguments; the caller
        (workflows/diffusion.diffusion_batched_callback) groups by
        batching.coalesce_key so that invariant holds. When requests
        carry start images (img2img), EVERY request must: each image is
        resized to the shared canvas and VAE-encoded into a per-row stack
        of init latents ("batched_i2i" program variant), so each row
        denoises from ITS OWN image's noised latents — padding rows get
        zero latents and are discarded after decode.

        Adapters ride PER ROW (ISSUE 13): a request's resolved `lora`
        reference becomes a slot in the stacked low-rank factors the
        jitted program applies as runtime deltas — mixed-adapter (and
        adapter-free) requests share one pass with no param-tree copy.
        An adapter the delta path cannot express raises ValueError, so
        the worker's solo fallback serves the group via the merged path.

        A shared ControlNet (ISSUE 13 second rung) arrives as
        `controlnet_model_name` + ONE `control_image` common to the
        whole group (coalesce_key guarantees identity): the control
        residuals are computed once per group per step instead of once
        per job.

        Returns [(images_j, pipeline_config_j)] aligned with requests.
        Every row's noise derives only from its own request's rng (the
        batched program variants draw per-row via vmapped keys), so a
        request's images do not depend on who it was coalesced with. The
        total row count pads up to a power-of-two bucket so coalesce
        factors 3 and 4 share one compiled program; padding rows carry an
        empty prompt and are discarded after decode.
        """
        from .common import (
            clamp_strength,
            img2img_t_start,
            pad_bucket,
            split_by_counts,
        )

        base_params = self.params
        if base_params is None:
            raise Exception(
                f"pipeline {self.model_name} was evicted; resubmit the job"
            )
        timings: dict[str, float] = {}
        start_images = [r.get("image") for r in requests]
        i2i = any(im is not None for im in start_images)
        if i2i and not all(im is not None for im in start_images):
            # a mixed group means the grouping layer broke its invariant;
            # raising routes every member through the solo fallback
            raise ValueError("coalesced img2img group missing a start image")
        if i2i and len({im.size for im in start_images}) > 1:
            # the input path only bounds images DOWN to the job's dims, so
            # same-key jobs can still arrive at different native sizes —
            # and the solo path sizes each job's canvas to ITS image. One
            # shared program can't reproduce that; the solo fallback can.
            raise ValueError(
                "coalesced img2img group has mixed start-image sizes; "
                "serving members individually")
        if height is None and i2i:
            # all start images share one size (checked above), which is
            # the canvas the solo path would use for each of them
            width, height = start_images[0].size
        height = int(height or self.default_size)
        width = int(width or height)
        height, width = (max(64, (d // 64) * 64) for d in (height, width))
        lh, lw = height // self.latent_factor, width // self.latent_factor
        steps = int(num_inference_steps)
        t_start = (
            img2img_t_start(steps, clamp_strength(strength)) if i2i else 0
        )
        counts = [
            max(int(r.get("num_images_per_prompt", 1) or 1), 1)
            for r in requests
        ]
        total = sum(counts)
        padded = pad_bucket(total)
        pad_rows = padded - total

        # --- per-row adapters (ISSUE 13): distinct adapters become slots
        # in one stacked factor operand; rows map to their slot (0 = the
        # zero adapter for adapter-free rows and padding). This block
        # runs BEFORE the row counters: its refusals (deltas disabled,
        # ineligible adapters, slots-cap overflow) re-route members to
        # other paths, which must not read as batched rows — the
        # DeltaIneligible re-batch would double-count its survivors ---
        lora_operands, lora_sig, te_operands = None, None, None
        self.last_operand_stats = None  # adapter-free passes stamp nothing
        row_modes: list[str] = []
        if any(r.get("lora") for r in requests):
            from .. import lora_cache
            from .lora_runtime import DeltaIneligibleError, row_operands

            self._require_runtime_delta()
            slots_cap = self._adapter_slots_cap(lora_slots_max)
            # surface ALL delta-ineligible members in one typed refusal,
            # so the worker re-batches the eligible majority instead of
            # serializing the whole group behind one conv/over-rank
            # adapter
            factors_of, _distinct, ineligible = \
                self._scan_adapter_specs(requests)
            if ineligible:
                raise DeltaIneligibleError(ineligible)
            slot_of: dict[tuple, int] = {}
            adapters: list[dict] = []
            adapter_keys: list[tuple] = []  # slot order — the stack recipe
            row_slots: list[int] = []
            row_gains: list[float] = []
            for r, n in zip(requests, counts):
                lora = r.get("lora")
                if not lora:
                    slot, gain = 0, 0.0
                    row_modes.append("none")
                else:
                    akey = lora_cache.adapter_key(lora)
                    slot = slot_of.get(akey)
                    if slot is None:
                        factors = factors_of[akey]
                        if len(adapters) >= slots_cap:
                            # the grouping layers cap distinct adapters
                            # per gang; a group past the cap fell through
                            # an estimate — solo fallback, never OOM
                            raise ValueError(
                                f"group carries more than {slots_cap} "
                                "distinct adapters; serving members "
                                "individually")
                        adapters.append(factors)
                        adapter_keys.append(akey)
                        slot = slot_of[akey] = len(adapters)
                    gain = float(r.get("lora_scale", 1.0) or 0.0)
                    row_modes.append("delta")
                row_slots.extend([slot] * n)
                row_gains.extend([gain] * n)
            row_slots.extend([0] * pad_rows)
            row_gains.extend([0.0] * pad_rows)
            lora_operands, lora_sig = self._lora_operands(
                adapters, row_slots, row_gains,
                adapter_keys=tuple(adapter_keys))
            if any(":" in p for p in lora_sig[2]):
                # text-encoder content rides the pass (ISSUE 16): the
                # encode batch is [negs+pad | prompts+pad], so the TE
                # slot/gain layout is the row vector twice (pad rows
                # already carry slot 0 / gain 0 at the tail)
                te_operands = row_operands(
                    lora_operands["a"], lora_operands["b"],
                    row_slots + row_slots, row_gains + row_gains)
        else:
            row_modes = ["none"] * len(requests)

        _BATCH_ROWS.inc(total, kind="real")
        if pad_rows:
            _BATCH_ROWS.inc(pad_rows, kind="padding")

        # --- conditioning: rows [uncond*padded | cond*padded]; padding
        # rows are empty prompts whose outputs are discarded ---
        with Span("text_encode", timings):
            negs: list[str] = []
            prompts: list[str] = []
            for r, n in zip(requests, counts):
                negs.extend([r.get("negative_prompt") or ""] * n)
                prompts.extend([r.get("prompt") or ""] * n)
            texts = negs + [""] * pad_rows + prompts + [""] * pad_rows
            context, pooled = self.encode_prompts(
                texts, base_params, te_operands=te_operands)

            added = None
            if self.is_xl:
                ids = self._xl_time_ids(pooled.shape[-1], height, width)
                added = {
                    # already [uncond*padded | cond*padded]
                    "text_embeds": pooled,
                    "time_ids": jnp.asarray(
                        [ids] * (2 * padded), jnp.float32),
                }

        # --- per-row key pairs (init draw + ancestral step noise), each
        # derived only from the owning request's rng ---
        init_keys, step_keys = [], []
        row_sources = [
            (r.get("rng") if r.get("rng") is not None else jax.random.key(0), n)
            for r, n in zip(requests, counts)
        ] + [(jax.random.key(0x9AD), pad_rows)]
        for base, n in row_sources:
            for i in range(n):
                k_init, k_step = jax.random.split(jax.random.fold_in(base, i))
                init_keys.append(k_init)
                step_keys.append(k_step)
        init_rng = jnp.stack(init_keys)
        step_rng = jnp.stack(step_keys)

        # unused-mode placeholders, same rank trick as run()
        latent_c = self.latent_channels
        image_latents = jnp.zeros((1, 1, 1, latent_c), jnp.float32)
        mask = jnp.zeros((1, 1, 1, 1), jnp.float32)
        control_cond = jnp.zeros((1, 1, 1, 3), jnp.float32)
        if i2i:
            # per-row init latents: encode each request's start image
            # ONCE (already at the shared canvas, resized defensively
            # here; plus one zero frame covering every padding row), then
            # gather the latents into the padded row layout — a request
            # with n rows shares one encode instead of paying n, and
            # padding rows don't run the encoder at full resolution
            uniq = [_pil_to_array(im, width, height) for im in start_images]
            # the ENCODE batch pads to a power-of-two bucket too (jit
            # retraces per shape — distinct group sizes would otherwise
            # each pay a VAE-encode compile); the zero frames double as
            # the padding rows' init latents
            need = len(uniq) + (1 if pad_rows else 0)
            while len(uniq) < pad_bucket(need):
                uniq.append(np.zeros((height, width, 3), np.float32))
            uniq_latents = self._vae_encode_program(
                base_params["vae"],
                jnp.asarray(np.stack(uniq)).astype(self.dtype),
            )
            row_index = []
            for i, n in enumerate(counts):
                row_index.extend([i] * n)
            row_index.extend([len(start_images)] * pad_rows)
            image_latents = uniq_latents[jnp.asarray(row_index)]

        # --- shared ControlNet (ISSUE 13 second rung): one control image
        # conditions the whole group, so the branch's residuals are
        # computed once per group per step instead of once per job ---
        controlnet_module, cn_params, cn_key = None, {}, None
        cn_scale = float(controlnet_conditioning_scale)
        if controlnet_model_name:
            if control_image is None:
                raise ValueError(
                    "Controlnet specified but no control image provided")
            controlnet_module, cn_params = self._get_controlnet(
                controlnet_model_name)
            cond = (_pil_to_array(control_image, width, height) + 1.0) / 2.0
            control_cond = jnp.broadcast_to(
                jnp.asarray(cond)[None], (padded, height, width, 3))
            cg_lo = int(float(control_guidance_start) * steps)
            cn_key = (
                controlnet_model_name,
                cg_lo,
                max(int(np.ceil(float(control_guidance_end) * steps)),
                    cg_lo + 1),
            )

        context, image_latents, mask, control_cond = map(
            self._place_batch, (context, image_latents, mask, control_cond)
        )
        if added is not None:
            added = {k: self._place_batch(v) for k, v in added.items()}

        sched_cfg = SchedulerConfig(
            prediction_type=self.prediction_type,
            use_karras_sigmas=bool(use_karras_sigmas),
        )
        sched_key = (scheduler_type, tuple(sorted(dataclass_items(sched_cfg))))
        key = ("batched_i2i" if i2i else "batched",
               lh, lw, padded, steps, sched_key, t_start, cn_key)
        # analytic UNet FLOPs of the whole PADDED pass (padding rows
        # burn chip time too — the pass-level figure owns them; per-job
        # stamps below count only each job's real rows)
        from ..models.flops import denoise_flops

        pass_flops_raw = denoise_flops(
            self.unet.config, lh, lw, padded, steps - t_start, cfg_rows=2)
        with Span("compile", timings, key="trace_s"):
            runner = self._denoise_runner(
                key, controlnet_module, lora_sig=lora_sig,
                analytic_flops=pass_flops_raw)
        # coalesced passes ALWAYS run the default data-parallel view:
        # throughput traffic keeps the coalescing geometry while
        # interactive solos may shard (the class-aware split, ISSUE 12).
        # Counted AFTER the pass succeeds (below), like run(): a failed
        # batched pass falls back to solo runs that count themselves,
        # and a phantom batched count would skew the sharded_rate
        # exactly when an operator is debugging a misbehaving fleet.
        pass_geometry = {
            "data": self.mesh.shape.get("data", 1),
            "tensor": self.mesh.shape.get("tensor", 1),
            "seq": self.mesh.shape.get("seq", 1),
        }

        # per-ROW cancel tokens (ISSUE 10): each request carries its
        # job_id, so a hive revocation of ONE member marks just that row
        # — batchmates finish unharmed (the padded program's shapes are
        # fixed; the cancelled row keeps computing, its result is simply
        # flagged and never packaged). When EVERY member is cancelled
        # the probe aborts the whole pass, freeing the slice within one
        # denoise_chunk_steps boundary.
        row_ids = [r.get("job_id") for r in requests]
        cancelled_rows: set[int] = set()
        probe = None
        if any(row_ids):
            from ..cancel import JobCancelled, cancelled as _row_cancelled

            def probe():
                for idx, jid in enumerate(row_ids):
                    if (jid and idx not in cancelled_rows
                            and _row_cancelled(jid)):
                        cancelled_rows.add(idx)
                        logger.warning(
                            "coalesced row for job %s cancelled; "
                            "batchmates continue", jid)
                if cancelled_rows and len(cancelled_rows) == len(requests):
                    raise JobCancelled([j for j in row_ids if j])

        from ..ops.attention import sequence_parallel_scope

        with Span("denoise", timings, key="denoise_decode_s"):
            with sequence_parallel_scope(self.mesh):
                pixels = runner(
                    base_params,
                    init_rng,
                    context,
                    added,
                    jnp.float32(guidance_scale),
                    jnp.float32(0.0),
                    image_latents,
                    mask,
                    step_rng,
                    cn_params,
                    control_cond,
                    jnp.float32(cn_scale),
                    lora_operands or {},
                    cancel_probe=probe,
                )
            pixels = jax.block_until_ready(pixels)
        _SHARDED_PASSES.inc(geometry=geometry_label(
            pass_geometry["tensor"], pass_geometry["seq"]))
        if self.chipset is not None:
            self.chipset.note_geometry(**pass_geometry)
        from .lora_runtime import LORA_ROWS

        for mode, n in zip(row_modes, counts):
            LORA_ROWS.inc(n, mode=mode)

        groups = split_by_counts(_to_pil(np.asarray(pixels)), counts)

        # pass-level cost figures (ISSUE 17), counted ONCE for the
        # coalesced pass; each envelope below derives its own stamp with
        # its job's real-row FLOPs
        pass_cost_figures = costs.pass_cost(
            model=self.model_name,
            pass_flops=pass_flops_raw,
            denoise_s=timings.get("denoise_decode_s"),
            chips=(self.chipset.chip_count() if self.chipset is not None
                   else 1),
            device=jax.devices()[0] if jax.devices() else None,
            geometry=geometry_label(pass_geometry["tensor"],
                                    pass_geometry["seq"]),
        )

        results = []
        offset = 0
        for row, (r, n, images) in enumerate(zip(requests, counts, groups)):
            results.append((images, {
                # a cancelled member's envelope is never built: the flag
                # tells the workflow/worker layers to drop this slot
                **({"cancelled": True} if row in cancelled_rows else {}),
                "model": self.model_name,
                "pipeline": pipeline_type,
                "scheduler": scheduler_type,
                "controlnet": controlnet_model_name,
                "mode": "img2img" if i2i else "txt2img",
                "steps": steps,
                "size": [width, height],
                "guidance_scale": guidance_scale,
                # adapter rows in this pass ran as runtime per-row
                # deltas (ISSUE 13); adapter-free rows stamp nothing
                **({"lora_mode": "delta"} if row_modes[row] == "delta"
                   else {}),
                **({"strength": clamp_strength(strength)} if i2i else {}),
                "batched_with": len(requests),
                "batch_rows": [offset, n],
                "padded_rows": padded,
                "unet_tflops": round(
                    denoise_flops(self.unet.config, lh, lw, n,
                                  steps - t_start, cfg_rows=2) / 1e12, 4,
                ),
                # per-envelope cost stamp (ISSUE 17): THIS job's real-row
                # FLOPs, then the shared pass figures (like embed_cache)
                "cost": costs.job_cost(
                    pass_cost_figures,
                    denoise_flops(self.unet.config, lh, lw, n,
                                  steps - t_start, cfg_rows=2)),
                # shared-pass embed-cache stats, copied per envelope
                # like the timings below (the per-job split is unknown
                # once rows stack — accounting treats them as the
                # pass-level figure they are)
                **({"embed_cache": {
                    "hits": self.last_encode_stats[0],
                    "misses": self.last_encode_stats[1]}}
                   if getattr(self, "last_encode_stats", None) else {}),
                # shared-pass operand-residency stats (ISSUE 16), copied
                # per envelope like embed_cache: bytes_saved is the
                # upload the resident stacks spared this pass
                **({"operand_cache": dict(self.last_operand_stats)}
                   if getattr(self, "last_operand_stats", None) else {}),
                # coalesced passes stamp the data-parallel view they ran
                # under, same key as the solo path (ISSUE 12)
                "geometry": dict(pass_geometry),
                # shared pass timings, copied per envelope: the envelope
                # must stand alone once the hive splits the batch apart
                "timings": dict(timings),
            }))
            offset += n
        return results


def dataclass_items(cfg) -> list[tuple]:
    import dataclasses

    return [(f.name, getattr(cfg, f.name)) for f in dataclasses.fields(cfg)]


@register_family("sd")
def _build_sd(model_name, chipset, **variant):
    return SDPipeline(model_name, chipset, **variant)


@register_family("sdxl")
def _build_sdxl(model_name, chipset, **variant):
    return SDPipeline(model_name, chipset, **variant)
