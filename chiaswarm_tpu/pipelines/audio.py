"""AudioLDM-style txt2audio pipeline (reference swarm/audio/audioldm.py)."""

from __future__ import annotations


def run_audioldm(device_identifier: str, model_name: str, **kwargs):
    raise Exception(
        f"txt2audio is not yet available on this worker (model {model_name})."
    )
