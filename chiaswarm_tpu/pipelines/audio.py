"""AudioLDM-style txt2audio pipeline (reference swarm/audio/audioldm.py).

The reference runs diffusers' AudioLDMPipeline -> 16 kHz wav -> mp3 via
pydub (:23-34). TPU rebuild: mel-spectrogram latents denoise in one jitted
scan on a UNet (mel frames x mel bins ride the spatial dims, so the same
MXU-friendly conv/attention stack serves audio), a mel VAE decodes to the
spectrogram, and a converted HiFi-GAN vocoder (models/hifigan.py, torch
parity vs transformers' SpeechT5HifiGan) reconstructs the waveform.
Artifacts are MPEG audio with the reference's content_type "audio/mpeg"
(pure-numpy Layer I encoder, toolbox/mpeg_audio.py), degrading to WAV —
with the content type saying so — if encoding fails.
"""

from __future__ import annotations

import dataclasses
import io
import logging
import os
import time
import zlib
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np

from ..models import configs as cfgs
from ..models.clap import TINY_CLAP, ClapTextConfig, ClapTextEncoder
from ..models.hifigan import HifiGanConfig, HifiGanGenerator
from ..models.tokenizer import load_tokenizer
from ..models.unet2d import UNet2DConditionModel
from ..models.vae import AutoencoderKL, VAEConfig
from ..post_processors.output_processor import make_result
from ..registry import register_family
from ..schedulers import get_scheduler

logger = logging.getLogger(__name__)

SAMPLE_RATE = 16_000  # reference audioldm.py wav rate
N_MELS = 64
HOP = 160  # 10 ms at 16 kHz
N_FFT = 1024


def _audio_configs(model_name: str, model_dir=None):
    """(unet_cfg, clap_cfg, vae_cfg, vocoder_cfg).

    Real model names REQUIRE a downloaded checkpoint: the UNet/VAE
    geometry is inferred from the state dicts themselves
    (conversion.infer_unet2d_config / infer_vae_config) plus each
    component's config.json, never hardcoded. The tiny config mirrors the
    real conditioning graph — FiLM class embedding of the CLAP joint
    embedding, concatenated to temb, self-attending transformer blocks
    (encoder_hidden_states=None) — at test scale.
    """
    name = model_name.lower()
    if "tiny" in name or name.startswith("test/"):
        vae = VAEConfig(in_channels=1, block_out_channels=(32, 32), layers_per_block=1)
        # hop stays 160 (8*5*4) so tiny jobs emit the same 16 kHz wire rate
        vocoder = HifiGanConfig(
            model_in_dim=N_MELS,
            upsample_initial_channel=16,
            upsample_rates=(8, 5, 4),
            upsample_kernel_sizes=(16, 10, 8),
            resblock_kernel_sizes=(3,),
            resblock_dilation_sizes=((1, 3),),
        )
        unet = dataclasses.replace(
            cfgs.TINY_UNET,
            cross_attention_dim=0,
            class_embed_dim=TINY_CLAP.projection_dim,
            class_embeddings_concat=True,
        )
        return unet, TINY_CLAP, vae, vocoder
    if model_dir is None:
        from ..weights import MissingWeightsError

        raise MissingWeightsError(
            f"audio model '{model_name}' has no downloaded checkpoint; its "
            "geometry is read from the checkpoint. Run "
            "`python -m chiaswarm_tpu.initialize --download`."
        )
    from ..models.conversion import (
        infer_unet2d_config,
        infer_vae_config,
        load_torch_state_dict,
    )

    unet = infer_unet2d_config(
        load_torch_state_dict(model_dir, "unet"), _config_json(model_dir, "unet")
    )
    vae = infer_vae_config(
        load_torch_state_dict(model_dir, "vae"), _config_json(model_dir, "vae")
    )
    clap, vocoder = _infer_clap_vocoder_configs(model_dir)
    return unet, clap, vae, vocoder


def _config_json(model_dir, sub: str) -> dict:
    import json
    from pathlib import Path

    p = Path(model_dir) / sub / "config.json"
    if p.is_file():
        try:
            return json.loads(p.read_text())
        except Exception as e:
            logger.warning("unreadable %s: %s", p, e)
    return {}


def _infer_clap_vocoder_configs(model_dir):
    """CLAP text tower + HiFi-GAN geometry from their config.json files
    (HF transformers components always ship them)."""
    tcfg = _config_json(model_dir, "text_encoder")
    sub = tcfg.get("text_config", tcfg)  # ClapConfig nests the text tower
    clap = ClapTextConfig(
        vocab_size=int(sub.get("vocab_size", 50265)),
        hidden_size=int(sub.get("hidden_size", 768)),
        num_layers=int(sub.get("num_hidden_layers", 12)),
        num_heads=int(sub.get("num_attention_heads", 12)),
        intermediate_size=int(sub.get("intermediate_size", 3072)),
        max_positions=int(sub.get("max_position_embeddings", 514)),
        projection_dim=int(tcfg.get("projection_dim", 512)),
    )
    vcfg = _config_json(model_dir, "vocoder")
    base = HifiGanConfig()
    vocoder = HifiGanConfig(
        model_in_dim=int(vcfg.get("model_in_dim", base.model_in_dim)),
        upsample_initial_channel=int(
            vcfg.get("upsample_initial_channel", base.upsample_initial_channel)
        ),
        upsample_rates=tuple(vcfg.get("upsample_rates", base.upsample_rates)),
        upsample_kernel_sizes=tuple(
            vcfg.get("upsample_kernel_sizes", base.upsample_kernel_sizes)
        ),
        resblock_kernel_sizes=tuple(
            vcfg.get("resblock_kernel_sizes", base.resblock_kernel_sizes)
        ),
        resblock_dilation_sizes=tuple(
            tuple(d)
            for d in vcfg.get(
                "resblock_dilation_sizes", base.resblock_dilation_sizes
            )
        ),
        leaky_relu_slope=float(
            vcfg.get("leaky_relu_slope", base.leaky_relu_slope)
        ),
        normalize_before=bool(
            vcfg.get("normalize_before", base.normalize_before)
        ),
    )
    return clap, vocoder


def _clap_tokenizer(model_dir, vocab_size: int, max_length: int = 77):
    """-> (tokenize_fn, is_real). Real RoBERTa BPE tokenizer when the
    checkpoint ships one; converted CLAP weights paired with the hash
    fallback would hash prompts into arbitrary vocab ids (unconditioned
    audio), so the real path loads the tokenizer files from the model dir
    (offline, via transformers) and the caller FAILS the build when
    converted text weights meet the hash fallback."""
    tok_dir = None
    if model_dir is not None:
        for sub in ("tokenizer", "text_encoder"):
            cand = model_dir / sub
            if (cand / "vocab.json").is_file() or (
                cand / "tokenizer.json"
            ).is_file():
                tok_dir = cand
                break
    if tok_dir is not None:
        try:
            from transformers import AutoTokenizer

            tok = AutoTokenizer.from_pretrained(str(tok_dir))

            def call(texts):
                return tok(
                    list(texts), padding="max_length", truncation=True,
                    max_length=max_length, return_tensors="np",
                )["input_ids"].astype(np.int32)

            return call, True
        except Exception as e:  # corrupt tokenizer dir: fall through
            logger.warning("CLAP tokenizer load failed (%s); hash fallback", e)
    return load_tokenizer(None, vocab_size=vocab_size), False


class AudioPipeline:
    """Resident mel-latent diffusion bundle for txt2audio jobs."""

    def __init__(self, model_name: str, chipset=None,
                 allow_random_init: bool = False):
        from ..weights import is_test_model, require_weights_present

        self.model_name = model_name
        self.chipset = chipset
        model_dir = self._model_dir()
        if not model_dir.is_dir():
            model_dir = None
        if model_dir is None and not is_test_model(model_name):
            require_weights_present(
                model_name, self._model_dir(), allow_random_init,
                component="audio model",
            )
        if model_dir is None and allow_random_init and not is_test_model(
            model_name
        ):
            # bench/bring-up: AudioLDM-s-shaped stand-in geometry (perf
            # does not depend on weight values; serving never takes this
            # branch — require_weights_present above raised already)
            unet_cfg = cfgs.UNet2DConfig(
                block_out_channels=(128, 256, 384, 640),
                transformer_layers=(1, 1, 1, 1),
                num_attention_heads=8,
                cross_attention_dim=0,
                class_embed_dim=512,
                class_embeddings_concat=True,
                in_channels=8, out_channels=8,
            )
            clap_cfg = ClapTextConfig()
            vae_cfg = VAEConfig(
                in_channels=1, latent_channels=8,
                block_out_channels=(128, 256, 512), scaling_factor=0.9227,
            )
            vocoder_cfg = HifiGanConfig(model_in_dim=N_MELS)
        else:
            unet_cfg, clap_cfg, vae_cfg, vocoder_cfg = _audio_configs(
                model_name, model_dir
            )
        self.latent_factor = 2 ** (len(vae_cfg.block_out_channels) - 1)
        on_tpu = jax.default_backend() == "tpu"
        self.dtype = jnp.bfloat16 if on_tpu else jnp.float32
        self.unet = UNet2DConditionModel(unet_cfg, dtype=self.dtype)
        self.text_encoder = ClapTextEncoder(clap_cfg, dtype=self.dtype)
        self.vae = AutoencoderKL(vae_cfg, dtype=self.dtype)
        self.vocoder = HifiGanGenerator(vocoder_cfg, dtype=self.dtype)
        self.vocoder_hop = int(np.prod(vocoder_cfg.upsample_rates))
        self.tokenizer, self._real_tokenizer = _clap_tokenizer(
            self._model_dir(), clap_cfg.vocab_size
        )

        t0 = time.perf_counter()
        rng = jax.random.key(zlib.crc32(model_name.encode()))
        k1, k2, k3, k4 = jax.random.split(rng, 4)
        hw = 4 * self.latent_factor
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            unet_cond = dict(
                encoder_hidden_states=None,
                class_labels=jnp.zeros((1, unet_cfg.class_embed_dim)),
            ) if unet_cfg.class_embed_dim else dict(
                encoder_hidden_states=jnp.zeros(
                    (1, 77, unet_cfg.cross_attention_dim)
                ),
            )
            init_params = {
                "unet": self.unet.init(
                    k1,
                    jnp.zeros((1, 8, 8, unet_cfg.in_channels)),
                    jnp.zeros((1,)),
                    **unet_cond,
                )["params"],
                "text": self.text_encoder.init(
                    k2, jnp.zeros((1, 77), jnp.int32)
                )["params"],
                "vae": self.vae.init(k3, jnp.zeros((1, hw, hw, 1)))["params"],
                "vocoder": self.vocoder.init(
                    k4, jnp.zeros((1, 16, N_MELS))
                )["params"],
            }
            # converted real weights override the random init per component
            # (text_encoder = ClapTextModelWithProjection, vocoder =
            # SpeechT5HifiGan in the HF audioldm layout)
            converted_comps = set()
            for comp, sub, conv in self._conversion_sources():
                try:
                    from ..models.conversion import (
                        assert_tree_shapes_match,
                        load_torch_state_dict,
                    )

                    converted = conv(
                        load_torch_state_dict(self._model_dir(), sub)
                    )
                    # geometry mismatch surfaces HERE as a conversion report,
                    # not later as an opaque flax apply error
                    assert_tree_shapes_match(
                        converted, init_params[comp], prefix=comp
                    )
                    init_params[comp] = converted
                    converted_comps.add(comp)
                    logger.info("loaded converted %s for %s", comp, model_name)
                except (FileNotFoundError, OSError):
                    pass
            if "text" in converted_comps and not self._real_tokenizer:
                # hashed prompt ids through a real CLAP tower produce
                # effectively unconditioned audio — refuse to build real
                # models (tiny test bundles only warn: their parity tests
                # drive the encoder with explicit ids)
                from ..weights import is_test_model

                msg = (
                    f"{model_name}: converted CLAP text weights are present "
                    "but no tokenizer files were found in the model dir; "
                    "re-run initialize --download to fetch the tokenizer"
                )
                if not is_test_model(model_name):
                    raise ValueError(msg)
                logger.warning(msg)
            self.params = jax.tree_util.tree_map(
                lambda x: jnp.asarray(x, self.dtype), init_params
            )
        logger.info(
            "%s audio pipeline resident in %.1fs", model_name,
            time.perf_counter() - t0,
        )
        # insertion-ordered so the program_cache_max bound below can evict
        # least-recently-used first (SW007; same knob as the SD family)
        self._programs: OrderedDict = OrderedDict()

    def _model_dir(self):
        from pathlib import Path

        from ..settings import load_settings

        return Path(load_settings().model_root_dir).expanduser() / self.model_name

    def _conversion_sources(self):
        from ..models.conversion import (
            convert_clap,
            convert_hifigan,
            convert_unet,
            convert_vae,
        )

        return (
            ("text", "text_encoder", convert_clap),
            ("vocoder", "vocoder", convert_hifigan),
            ("unet", "unet", convert_unet),
            ("vae", "vae", convert_vae),
        )

    def release(self):
        self.params = None
        self._programs.clear()

    def _program(self, key):
        if key in self._programs:
            self._programs.move_to_end(key)
            return self._programs[key]
        lt, lf, steps, sched_name = key
        scheduler = get_scheduler(sched_name)
        schedule = scheduler.schedule(steps)

        film = self.unet.config.class_embed_dim > 0

        def run(params, latents, context, guidance_scale, rng):
            latents = latents * jnp.asarray(schedule.init_noise_sigma, latents.dtype)
            state = scheduler.init_state(latents.shape, latents.dtype)

            def body(carry, i):
                latents, state = carry
                inp = scheduler.scale_model_input(schedule, latents, i)
                model_in = jnp.concatenate([inp, inp], axis=0).astype(self.dtype)
                t = jnp.broadcast_to(
                    jnp.asarray(schedule.timesteps)[i], (model_in.shape[0],)
                )
                if film:
                    # real AudioLDM conditioning: the CLAP embedding enters
                    # as a FiLM class embedding, not cross-attention tokens
                    out = self.unet.apply(
                        {"params": params["unet"]}, model_in, t, None,
                        class_labels=context,
                    ).astype(jnp.float32)
                else:
                    out = self.unet.apply(
                        {"params": params["unet"]}, model_in, t, context
                    ).astype(jnp.float32)
                out_u, out_c = jnp.split(out, 2, axis=0)
                out = out_u + guidance_scale * (out_c - out_u)
                noise = jax.random.normal(
                    jax.random.fold_in(rng, i), latents.shape, jnp.float32
                )
                state, latents = scheduler.step(schedule, state, i, latents, out, noise)
                return (latents, state), ()

            (latents, _), _ = jax.lax.scan(
                body, (latents.astype(jnp.float32), state), jnp.arange(steps)
            )
            mel = self.vae.apply(
                {"params": params["vae"]}, latents.astype(self.dtype),
                method=self.vae.decode,
            )
            # HiFi-GAN vocoder fused into the same program: mel [B,T,F,1]
            # -> waveform; only the waveform crosses back to the host
            wav = self.vocoder.apply(
                {"params": params["vocoder"]}, mel[..., 0]
            )
            return wav.astype(jnp.float32)

        program = jax.jit(run)
        self._programs[key] = program
        from .common import PROGRAM_EVICTED, program_cache_cap

        cap = program_cache_cap()
        while cap and len(self._programs) > cap:
            self._programs.popitem(last=False)
            PROGRAM_EVICTED.inc(kind="program")
        return program

    def run(self, prompt="", negative_prompt="", **kwargs):
        # snapshot once: a concurrent registry eviction nulls self.params
        params = self.params
        if params is None:
            raise Exception(f"pipeline {self.model_name} was evicted; resubmit")
        steps = int(kwargs.pop("num_inference_steps", 20))
        guidance_scale = float(kwargs.pop("guidance_scale", 2.5))
        duration_s = float(kwargs.pop("audio_length_in_s", 5.0))
        scheduler_type = kwargs.pop("scheduler_type", "DDIMScheduler")
        rng = kwargs.pop("rng", None)
        if rng is None:
            rng = jax.random.key(0)

        # mel time frames for the requested duration, latent-factor aligned
        frames = int(duration_s * SAMPLE_RATE / HOP)
        lt = max(8, frames // self.latent_factor // 8 * 8)
        lf = max(8, N_MELS // self.latent_factor)

        ids = jnp.asarray(self.tokenizer([negative_prompt, prompt]))
        # AudioLDM conditions on the pooled CLAP joint-space embedding,
        # L2-NORMALIZED (diffusers AudioLDMPipeline._encode_prompt applies
        # F.normalize before conditioning — the UNet was trained on unit-
        # norm embeds); it enters the UNet as one cross-attention token
        pooled = self.text_encoder.apply({"params": params["text"]}, ids)[
            "pooled"
        ].astype(jnp.float32)
        pooled = pooled / jnp.maximum(
            jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-8
        )
        if self.unet.config.class_embed_dim:
            context = pooled.astype(self.dtype)  # [2, D] FiLM class labels
        else:
            context = pooled[:, None, :].astype(self.dtype)

        rng, init_rng, step_rng = jax.random.split(rng, 3)
        latent_c = self.unet.config.in_channels
        noise = jax.random.normal(init_rng, (1, lt, lf, latent_c), jnp.float32)

        t0 = time.perf_counter()
        program = self._program((lt, lf, steps, scheduler_type))
        wav = jax.block_until_ready(
            program(params, noise, context, jnp.float32(guidance_scale),
                    step_rng)
        )
        denoise_s = round(time.perf_counter() - t0, 3)

        wav = normalize_wav(np.asarray(wav, np.float32)[0])
        # frames/sec is fixed by the mel hop; the vocoder hop sets the
        # output rate (real geometry: 100 fps * 160 = 16 kHz = reference)
        out_rate = int(SAMPLE_RATE / HOP * self.vocoder_hop)
        config = {
            "model": self.model_name,
            "steps": steps,
            "duration_s": duration_s,
            "sample_rate": out_rate,
            "scheduler": scheduler_type,
            "vocoder": "hifigan",
            "timings": {"denoise_vocode_s": denoise_s},
        }
        return wav, config


def mel_filterbank(n_mels=N_MELS, n_fft=N_FFT, rate=SAMPLE_RATE) -> np.ndarray:
    """Triangular mel filterbank [n_mels, n_fft//2+1] (HTK mel scale)."""
    mel = lambda f: 2595.0 * np.log10(1.0 + f / 700.0)
    imel = lambda m: 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    points = imel(np.linspace(mel(0.0), mel(rate / 2), n_mels + 2))
    bins = np.floor((n_fft + 1) * points / rate).astype(int)
    fb = np.zeros((n_mels, n_fft // 2 + 1))
    for i in range(n_mels):
        lo, ctr, hi = bins[i], bins[i + 1], bins[i + 2]
        if ctr > lo:
            fb[i, lo:ctr] = (np.arange(lo, ctr) - lo) / (ctr - lo)
        if hi > ctr:
            fb[i, ctr:hi] = (hi - np.arange(ctr, hi)) / (hi - ctr)
    return fb


def griffin_lim(log_mel: np.ndarray, iterations: int = 24) -> np.ndarray:
    """log-mel [F, T] -> waveform via pseudo-inverse mel + Griffin-Lim."""
    from scipy.signal import istft, stft

    power = np.exp(np.clip(log_mel, -12.0, 6.0))
    fb = mel_filterbank(log_mel.shape[0])
    linear = np.maximum(np.linalg.pinv(fb) @ power, 1e-8) ** 0.5

    rng = np.random.default_rng(0)
    angles = np.exp(2j * np.pi * rng.random(linear.shape))
    kw = dict(nperseg=N_FFT, noverlap=N_FFT - HOP, fs=SAMPLE_RATE)
    pad = (N_FFT // 2 + 1) - linear.shape[0]
    if pad > 0:  # lift the mel-height spectrum onto the full fft grid
        linear = np.pad(linear, ((0, pad), (0, 0)))
        angles = np.pad(angles, ((0, pad), (0, 0)), constant_values=1.0)
    for _ in range(iterations):
        _, wav = istft(linear * angles, **kw)
        _, _, spec = stft(wav, **kw)
        spec = spec[:, : linear.shape[1]]
        if spec.shape[1] < linear.shape[1]:
            spec = np.pad(spec, ((0, 0), (0, linear.shape[1] - spec.shape[1])))
        angles = np.exp(1j * np.angle(spec))
    _, wav = istft(linear * angles, **kw)
    return normalize_wav(wav)


def normalize_wav(wav: np.ndarray, headroom: float = 0.95) -> np.ndarray:
    """Peak-normalize to +/-headroom (silence passes through unscaled)."""
    peak = float(np.max(np.abs(wav))) or 1.0
    return (wav / peak * headroom).astype(np.float32)


def wav_to_buffer(wav: np.ndarray, rate: int = SAMPLE_RATE) -> io.BytesIO:
    from scipy.io import wavfile

    buffer = io.BytesIO()
    wavfile.write(buffer, rate, (wav * 32767).astype(np.int16))
    buffer.seek(0)
    return buffer


def audio_artifact(
    wav: np.ndarray, rate: int, content_type: str = "audio/mpeg"
) -> tuple[io.BytesIO, str, int]:
    """Encode a waveform for the artifact envelope.

    Returns (buffer, content_type, sample_rate) — the rate the stream was
    actually encoded at, so envelope metadata can agree with the bytes.

    The reference ships mp3 with content_type "audio/mpeg"
    (swarm/audio/audioldm.py:17,30-34); this rebuild encodes MPEG Layer I
    (toolbox/mpeg_audio.py — same audio/mpeg stream family, verified
    against libmpg123) and honors an explicit "audio/wav" request. Any
    encode failure degrades to WAV with the content type reflecting what
    was actually produced.

    Layer I at high bitrate is an unusual stream some clients may
    mishandle; CHIASWARM_FFMPEG_AUDIO=1 re-encodes through ffmpeg to
    Layer III (MP3) when the binary is present (it is in the Docker
    image), falling back to the built-in encoder otherwise.
    """
    if content_type != "audio/wav" and os.environ.get(
            "CHIASWARM_FFMPEG_AUDIO", "") == "1":
        try:
            # force the output to a legal MP3 rate so the returned rate
            # matches the actual stream (ffmpeg would otherwise resample
            # silently and the envelope metadata would lie)
            mp3_rate = min(_MP3_RATES, key=lambda r: abs(r - rate))
            buf = _ffmpeg_mp3(wav, rate, mp3_rate)
            if buf is not None:
                return buf, "audio/mpeg", mp3_rate
        except Exception as e:
            logger.warning("ffmpeg mp3 encode failed (%s); using built-in "
                           "Layer I encoder", e)
    if content_type != "audio/wav":
        try:
            from ..toolbox.mpeg_audio import SUPPORTED_RATES, encode_mpeg_buffer

            if rate not in SUPPORTED_RATES:
                # MPEG audio supports 6 rates; resample anything else
                # (e.g. tiny test models) up to the nearest one
                from math import gcd

                from scipy.signal import resample_poly

                target = min(SUPPORTED_RATES, key=lambda r: abs(r - rate))
                g = gcd(target, rate)
                wav = resample_poly(wav, target // g, rate // g)
                rate = target
            return encode_mpeg_buffer(wav, rate), "audio/mpeg", rate
        except Exception as e:
            logger.warning("MPEG encode failed (%s); emitting WAV", e)
    return wav_to_buffer(wav, rate), "audio/wav", rate


# sample rates MPEG-1/2/2.5 Layer III can carry
_MP3_RATES = (8000, 11025, 12000, 16000, 22050, 24000, 32000, 44100, 48000)


def _ffmpeg_mp3(wav: np.ndarray, in_rate: int,
                out_rate: int) -> io.BytesIO | None:
    """Pipe f32 PCM through a local ffmpeg to a Layer-III stream at
    `out_rate`; None when no ffmpeg binary is on PATH (caller falls
    back)."""
    import shutil
    import subprocess

    if shutil.which("ffmpeg") is None:
        return None
    pcm = np.clip(np.asarray(wav, np.float32), -1.0, 1.0)
    proc = subprocess.run(
        ["ffmpeg", "-loglevel", "error", "-f", "f32le", "-ar", str(in_rate),
         "-ac", "1", "-i", "pipe:0", "-f", "mp3", "-ar", str(out_rate),
         "-b:a", "192k", "pipe:1"],
        input=pcm.tobytes(), capture_output=True, timeout=120,
    )
    if proc.returncode != 0 or not proc.stdout:
        raise RuntimeError(proc.stderr[-200:].decode("utf-8", "replace"))
    return io.BytesIO(proc.stdout)


@register_family("audioldm")
def _build_audioldm(model_name, chipset, **variant):
    return AudioPipeline(model_name, chipset, **variant)


def run_audioldm(device_identifier: str, model_name: str, **kwargs):
    """txt2audio job -> audio/mpeg artifact (reference swarm/audio/audioldm.py)."""
    from ..registry import get_pipeline

    content_type = kwargs.pop("content_type", "audio/mpeg")
    kwargs.pop("outputs", None)
    if kwargs.pop("test_tiny_model", False):
        model_name = "test/tiny-audio"
    pipeline = get_pipeline(
        model_name,
        pipeline_type=kwargs.pop("pipeline_type", "AudioLDMPipeline"),
        chipset=kwargs.pop("chipset", None),
    )
    wav, config = pipeline.run(**kwargs)
    buf, produced_type, produced_rate = audio_artifact(
        wav, config.get("sample_rate", SAMPLE_RATE), content_type
    )
    config["sample_rate"] = produced_rate
    return {"primary": make_result(buf, None, produced_type)}, config
