"""Stable Video Diffusion img2vid serving — the TRUE spatio-temporal
architecture with converted weights.

Reference behavior replaced: swarm/video/img2vid.py:14-38 loads
`StableVideoDiffusionPipeline` per job with VAE slicing/tiling + CPU
offload. Here the UNetSpatioTemporalConditionModel + temporal-decoder VAE
+ CLIP-vision tower are resident, and the whole job — conditioning
encode, EDM/karras v-prediction Euler denoise over `lax.scan`, per-frame
guidance ramp, temporal VAE decode — is one jitted program per
(frames, size, steps) bucket.

Diffusers-semantics notes (StableVideoDiffusionPipeline):
- the conditioning frame is noise-augmented in PIXEL space
  (`image + noise_aug_strength * randn`) before the VAE mode-encode, and
  its UNSCALED latent mean rides the UNet input channels per frame;
- CFG rows are [zero image embed + zero cond latents | real rows], with
  guidance ramped linearly from `min_guidance_scale` to
  `max_guidance_scale` ACROSS FRAMES;
- sigmas are karras(0.002, 700); the model timestep is continuous
  0.25*log(sigma); prediction type is v.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import zlib
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np
from PIL import Image

from ..models.safety import CLIPVisionEncoder, SafetyConfig
from ..models.svd_unet import TINY_SVD_UNET, UNetSpatioTemporalConditionModel
from ..models.svd_vae import TINY_SVD_VAE, AutoencoderKLTemporalDecoder
from ..parallel.mesh import make_mesh, replicated
from ..registry import register_family
from ..schedulers.common import karras_sigmas
from ..schedulers.solvers import x0_from_sigma_space
from ..weights import is_test_model, require_weights_present

logger = logging.getLogger(__name__)

_NO_WEIGHTS_HINT = (
    "Download the SVD checkpoint (unet + vae + image_encoder) with "
    "`python -m chiaswarm_tpu.initialize --download` so it converts at load."
)

SIGMA_MIN, SIGMA_MAX = 0.002, 700.0
CLIP_MEAN = np.array([0.48145466, 0.4578275, 0.40821073], np.float32)
CLIP_STD = np.array([0.26862954, 0.26130258, 0.27577711], np.float32)

_TINY_SVD_VISION = SafetyConfig(
    image_size=32, patch_size=8, hidden_size=32, num_layers=2, num_heads=4,
    projection_dim=TINY_SVD_UNET.cross_attention_dim, hidden_act="gelu",
)


def _load_converted_svd(model_name: str, model_dir=None):
    """-> {"unet_cfg","unet","vae_cfg","vae","vision_cfg","vision"} or None."""
    if is_test_model(model_name):
        return None
    if model_dir is None:
        from ..weights import model_dir_for

        model_dir = model_dir_for(model_name)
    if model_dir is None:
        return None
    from ..models.conversion import (
        convert_clip_vision,
        convert_svd_unet,
        convert_svd_vae,
        infer_clip_vision_config,
        infer_svd_unet_config,
        infer_svd_vae_config,
        load_torch_state_dict,
    )
    from ..weights import MissingWeightsError

    def read_json(sub):
        p = model_dir / sub / "config.json"
        return json.loads(p.read_text()) if p.is_file() else {}

    try:
        unet_state = load_torch_state_dict(model_dir, "unet")
        vae_state = load_torch_state_dict(model_dir, "vae")
        return {
            "unet_cfg": infer_svd_unet_config(unet_state, read_json("unet")),
            "unet": convert_svd_unet(unet_state),
            "vae_cfg": infer_svd_vae_config(vae_state, read_json("vae")),
            "vae": convert_svd_vae(vae_state),
            "vision_cfg": infer_clip_vision_config(read_json("image_encoder")),
            "vision": convert_clip_vision(
                load_torch_state_dict(model_dir, "image_encoder")
            ),
            "model_dir": model_dir,
        }
    except (FileNotFoundError, OSError):
        return None
    except Exception as e:
        raise MissingWeightsError(
            f"checkpoint under {model_dir} could not be converted for "
            f"'{model_name}': {e}"
        ) from e


class SVDPipeline:
    """Resident StableVideoDiffusionPipeline equivalent."""

    # run_img2vid passes motion_bucket_id / noise_aug_strength through to
    # pipelines advertising this (the motion-module approximation doesn't)
    accepts_micro_conditioning = True

    def __init__(self, model_name: str, chipset=None,
                 allow_random_init: bool = False):
        self.model_name = model_name
        self.chipset = chipset
        conv = _load_converted_svd(model_name)
        if conv is None:
            require_weights_present(
                model_name, None, allow_random_init,
                component="SVD pipeline", hint=_NO_WEIGHTS_HINT,
            )
            self.unet_cfg = TINY_SVD_UNET
            self.vae_cfg = TINY_SVD_VAE
            self.vision_cfg = _TINY_SVD_VISION
            self.default_size = (64, 64)  # (width, height)
        else:
            self.unet_cfg = conv["unet_cfg"]
            self.vae_cfg = conv["vae_cfg"]
            self.vision_cfg = conv["vision_cfg"]
            self.default_size = (1024, 576)
        on_tpu = jax.default_backend() == "tpu"
        self.dtype = jnp.bfloat16 if on_tpu else jnp.float32
        self.unet = UNetSpatioTemporalConditionModel(
            self.unet_cfg, dtype=self.dtype
        )
        self.vae = AutoencoderKLTemporalDecoder(self.vae_cfg, dtype=self.dtype)
        self.vision = CLIPVisionEncoder(self.vision_cfg, dtype=self.dtype)
        self.latent_factor = 2 ** (len(self.vae_cfg.block_out_channels) - 1)
        self.mesh = (
            chipset.mesh() if chipset is not None else make_mesh(jax.devices()[:1])
        )

        if conv is None:
            seed = zlib.crc32(model_name.encode())
            k1, k2, k3 = jax.random.split(jax.random.key(seed), 3)
            icfg = self.vision_cfg
            with jax.default_device(jax.local_devices(backend="cpu")[0]):
                unet_params = self.unet.init(
                    k1,
                    jnp.zeros((1, 2, 8, 8, self.unet_cfg.in_channels)),
                    jnp.zeros((1,)),
                    jnp.zeros((1, 1, self.unet_cfg.cross_attention_dim)),
                    jnp.zeros((1, 3)),
                )["params"]
                vae_params = self.vae.init(
                    k2, jnp.zeros((1, 32, 32, 3))  # num_frames default: static
                )["params"]
                vision_params = self.vision.init(
                    k3,
                    jnp.zeros((1, icfg.image_size, icfg.image_size, 3)),
                )["params"]
            tree = {
                "unet": unet_params, "vae": vae_params,
                "vision": vision_params,
            }
        else:
            tree = {
                "unet": conv["unet"], "vae": conv["vae"],
                "vision": conv["vision"],
            }
        cast = lambda x: jnp.asarray(x, self.dtype)
        self.params = jax.device_put(
            jax.tree_util.tree_map(cast, tree), replicated(self.mesh)
        )
        # insertion-ordered so the program_cache_max bound below can evict
        # least-recently-used first (SW007; same knob as the SD family)
        self._programs: OrderedDict = OrderedDict()
        self._lock = threading.Lock()

    def release(self):
        self.params = None
        self._programs.clear()

    def _program(self, key: tuple):
        with self._lock:
            if key in self._programs:
                self._programs.move_to_end(key)
                return self._programs[key]
        lh, lw, frames, steps = key
        sigmas = np.concatenate(
            [karras_sigmas(SIGMA_MIN, SIGMA_MAX, steps), [0.0]]
        ).astype(np.float32)
        init_noise_sigma = float(np.sqrt(sigmas[0] ** 2 + 1.0))
        unet = self.unet
        vae = self.vae
        scaling = self.vae_cfg.scaling_factor
        latent_c = self.vae_cfg.latent_channels

        def run(params, rng, image_embed, cond_latents, added_ids,
                min_guidance, max_guidance):
            """image_embed [1, 1, D]; cond_latents [1, lh, lw, C] unscaled."""
            sig = jnp.asarray(sigmas)
            latents = (
                jax.random.normal(rng, (1, frames, lh, lw, latent_c), jnp.float32)
                * init_noise_sigma
            )
            # CFG rows: [zeroed conditioning | real conditioning]
            embed2 = jnp.concatenate(
                [jnp.zeros_like(image_embed), image_embed], axis=0
            )
            cond2 = jnp.concatenate(
                [
                    jnp.zeros((1, frames, lh, lw, latent_c), jnp.float32),
                    jnp.broadcast_to(
                        cond_latents[:, None], (1, frames, lh, lw, latent_c)
                    ),
                ],
                axis=0,
            ).astype(self.dtype)
            ids2 = jnp.concatenate([added_ids, added_ids], axis=0)
            # per-frame guidance ramp (diffusers: linspace over frames)
            guidance = jnp.linspace(min_guidance, max_guidance, frames)[
                None, :, None, None, None
            ]

            def body(carry, i):
                latents = carry
                sigma = sig[i]
                inp = latents / jnp.sqrt(sigma**2 + 1.0)
                model_in = jnp.concatenate(
                    [
                        jnp.concatenate([inp, inp], axis=0).astype(self.dtype),
                        cond2,
                    ],
                    axis=-1,
                )
                t = 0.25 * jnp.log(sigma)
                out = unet.apply(
                    {"params": params["unet"]},
                    model_in,
                    jnp.broadcast_to(t, (2,)),
                    embed2,
                    ids2,
                ).astype(jnp.float32)
                out_u, out_c = jnp.split(out, 2, axis=0)
                out = out_u + guidance * (out_c - out_u)
                x0 = x0_from_sigma_space(latents, out, sigma, "v_prediction")
                derivative = (latents - x0) / sigma
                latents = latents + derivative * (sig[i + 1] - sigma)
                return latents, ()

            latents, _ = jax.lax.scan(body, latents, jnp.arange(steps))
            # denoised latents are already in the SCALED latent space;
            # decode() divides by scaling_factor internally
            flat = latents.reshape(frames, lh, lw, latent_c)
            pixels = vae.apply(
                {"params": params["vae"]},
                flat.astype(self.dtype),
                frames,
                method=vae.decode,
            )
            return (
                (pixels.astype(jnp.float32) + 1.0) * 127.5
            ).clip(0.0, 255.0).round().astype(jnp.uint8)

        program = jax.jit(run)
        with self._lock:
            self._programs[key] = program
            from .common import PROGRAM_EVICTED, program_cache_cap

            cap = program_cache_cap()
            while cap and len(self._programs) > cap:
                self._programs.popitem(last=False)
                PROGRAM_EVICTED.inc(kind="program")
        return program

    def _image_embed(self, params, image: Image.Image):
        icfg = self.vision_cfg
        side = icfg.image_size
        arr = (
            np.asarray(
                image.convert("RGB").resize((side, side), Image.BICUBIC),
                np.float32,
            )
            / 255.0
        )
        arr = (arr - CLIP_MEAN) / CLIP_STD
        embed = self.vision.apply(
            {"params": params["vision"]}, jnp.asarray(arr)[None]
        )
        return embed[:, None, :].astype(jnp.float32)  # [1, 1, D]

    def run(self, prompt="", negative_prompt="",
            pipeline_type="StableVideoDiffusionPipeline", **kwargs):
        params = self.params
        if params is None:
            raise Exception(
                f"pipeline {self.model_name} was evicted; resubmit the job"
            )
        image = kwargs.pop("image", None)
        if image is None:
            raise ValueError("img2vid requires an input image. None provided")
        timings: dict[str, float] = {}
        steps = int(kwargs.pop("num_inference_steps", 25))
        frames = int(kwargs.pop("num_frames", 25 if self.default_size[0] > 64 else 8))
        fps = int(kwargs.pop("fps", 7))
        motion_bucket_id = float(kwargs.pop("motion_bucket_id", 127))
        noise_aug = float(kwargs.pop("noise_aug_strength", 0.02))
        min_guidance = float(kwargs.pop("min_guidance_scale", 1.0))
        max_guidance = float(
            kwargs.pop("max_guidance_scale", kwargs.pop("guidance_scale", 3.0))
        )
        rng = kwargs.pop("rng", None)
        if rng is None:
            rng = jax.random.key(0)
        width = int(kwargs.pop("width", None) or self.default_size[0])
        height = int(kwargs.pop("height", None) or self.default_size[1])
        height, width = (max(64, (d // 64) * 64) for d in (height, width))
        lh, lw = height // self.latent_factor, width // self.latent_factor

        rng, aug_rng, denoise_rng = jax.random.split(rng, 3)
        arr = (
            np.asarray(
                image.convert("RGB").resize((width, height), Image.LANCZOS),
                np.float32,
            )
            / 127.5
            - 1.0
        )
        # pixel-space noise augmentation (diffusers parity), then latent
        # MODE encode, UNSCALED
        pix = jnp.asarray(arr)[None] + noise_aug * jax.random.normal(
            aug_rng, (1, height, width, 3), jnp.float32
        )
        cond_latents = self.vae.apply(
            {"params": params["vae"]}, pix.astype(self.dtype),
            method=self.vae.encode,
        ).astype(jnp.float32)
        embed = self._image_embed(params, image)
        added_ids = jnp.asarray(
            [[fps - 1, motion_bucket_id, noise_aug]], jnp.float32
        )

        program = self._program((lh, lw, frames, steps))
        t0 = time.perf_counter()
        pixels = jax.block_until_ready(
            program(
                params, denoise_rng, embed, cond_latents, added_ids,
                jnp.float32(min_guidance), jnp.float32(max_guidance),
            )
        )
        timings["denoise_decode_s"] = round(time.perf_counter() - t0, 3)

        pil_frames = [Image.fromarray(f) for f in np.asarray(pixels)]
        config = {
            "model": self.model_name,
            "pipeline": pipeline_type,
            "frames": frames,
            "fps": fps,
            "steps": steps,
            "size": [width, height],
            "motion_bucket_id": motion_bucket_id,
            "noise_aug_strength": noise_aug,
            "scheduler": "EulerDiscrete(karras, v-prediction)",
            "timings": timings,
        }
        return pil_frames, config


@register_family("svd")
def _build_svd(model_name, chipset, **variant):
    return SVDPipeline(model_name, chipset, **variant)
