"""Bark TTS pipeline: text -> semantic -> coarse -> fine -> waveform.

Reference behavior replaced: swarm/audio/bark.py:16-21 (suno-bark
`preload_models()` + `generate_audio()` per job, wav -> mp3). The TPU
rebuild keeps the four-stage suno/bark architecture (models/bark.py) as
ONE resident jitted program per (prompt-budget, duration) bucket: both AR
stages run as `lax.scan` KV-cache loops, the fine stage refines codebooks
3..8 with a bidirectional transformer, and the codec decoder emits the
waveform — text-in, audio-out in a single XLA program, nothing returns to
the host between stages. Real suno/bark weight conversion is not wired
yet, so non-test model names fail loudly per weights.py.
"""

from __future__ import annotations

import logging
import threading
import time
import zlib

import jax
import jax.numpy as jnp
import numpy as np

from ..models.bark import (
    CODEBOOK_SIZE,
    CODEC_RATE,
    N_COARSE_BOOKS,
    N_FINE_BOOKS,
    SEMANTIC_RATE,
    SEMANTIC_VOCAB,
    BarkGPT,
    CodecDecoder,
    bark_small,
    bark_tiny,
    generate,
)
from ..models.bert_tokenizer import HashBertTokenizer
from ..parallel.mesh import make_mesh, replicated
from ..registry import register_family
from ..weights import is_test_model, require_weights_present

logger = logging.getLogger(__name__)

SAMPLE_RATE = 24_000  # EnCodec rate the bark codec targets

_NO_CONVERSION_HINT = (
    "This worker cannot serve real suno/bark weights yet; only the "
    "test/tiny bark stack is available."
)


_is_tiny = is_test_model


class BarkPipeline:
    """Resident 4-stage TTS stack serving `suno/bark*` model names."""

    def __init__(self, model_name: str, chipset=None,
                 allow_random_init: bool = False):
        require_weights_present(
            model_name, None, allow_random_init, component="Bark TTS",
            hint=_NO_CONVERSION_HINT,
        )
        self.model_name = model_name
        self.chipset = chipset
        self.tiny = _is_tiny(model_name)
        mk = bark_tiny if self.tiny else bark_small
        self.sem_cfg = mk("semantic")
        self.coarse_cfg = mk("coarse")
        self.fine_cfg = mk("fine")
        # OUTPUT-vocab slice width of one coarse codebook
        self.cb = self.coarse_cfg.output_vocab // N_COARSE_BOOKS
        # token rates scale down on the tiny stack so tests stay fast
        self.sem_rate = 8 if self.tiny else SEMANTIC_RATE
        self.codec_rate = 8 if self.tiny else CODEC_RATE

        on_tpu = jax.default_backend() == "tpu"
        self.dtype = jnp.bfloat16 if on_tpu else jnp.float32
        self.semantic = BarkGPT(self.sem_cfg, dtype=self.dtype)
        self.coarse = BarkGPT(self.coarse_cfg, dtype=self.dtype)
        self.fine = BarkGPT(self.fine_cfg, dtype=self.dtype)
        self.codec = CodecDecoder(
            n_books=N_FINE_BOOKS,
            codebook_size=self.cb,
            d_model=32 if self.tiny else 128,
            ratios=(4, 2) if self.tiny else (8, 5, 4, 2),
            dtype=self.dtype,
        )
        self.hop = int(np.prod(self.codec.ratios))
        # text ids ride above the semantic ids in the semantic input vocab
        self.text_vocab = self.sem_cfg.input_vocab - SEMANTIC_VOCAB \
            if not self.tiny else self.sem_cfg.input_vocab - 1000
        self.sem_out = self.sem_cfg.output_vocab
        self.tokenizer = HashBertTokenizer(self.text_vocab)
        self.mesh = (
            chipset.mesh() if chipset is not None else make_mesh(jax.devices()[:1])
        )

        rng = jax.random.key(zlib.crc32(model_name.encode()))
        k1, k2, k3, k4 = jax.random.split(rng, 4)
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            sem_params = self.semantic.init(
                k1, jnp.zeros((1, 8), jnp.int32)
            )["params"]
            coarse_params = self.coarse.init(
                k2, jnp.zeros((1, 8), jnp.int32)
            )["params"]
            fine_params = self.fine.init(
                k3, jnp.zeros((1, N_FINE_BOOKS, 8), jnp.int32)
            )["params"]
            codec_params = self.codec.init(
                k4, jnp.zeros((1, N_FINE_BOOKS, 8), jnp.int32)
            )["params"]
        cast = lambda x: (
            jnp.asarray(x, self.dtype) if jnp.issubdtype(
                jnp.asarray(x).dtype, jnp.floating) else jnp.asarray(x)
        )
        self.params = jax.device_put(
            jax.tree_util.tree_map(cast, {
                "semantic": sem_params,
                "coarse": coarse_params,
                "fine": fine_params,
                "codec": codec_params,
            }),
            replicated(self.mesh),
        )
        self._programs: dict[tuple, callable] = {}
        self._lock = threading.Lock()

    def release(self):
        self.params = None
        self._programs.clear()

    def _program(self, key: tuple):
        """One fused text->waveform program."""
        with self._lock:
            if key in self._programs:
                return self._programs[key]
        t_text, n_sem, n_frames = key
        semantic, coarse, fine, codec = (
            self.semantic, self.coarse, self.fine, self.codec
        )
        cb = self.cb
        sem_offset = SEMANTIC_VOCAB if not self.tiny else 1000
        n_coarse_tokens = n_frames * N_COARSE_BOOKS

        def run(params, rng, text_ids, temperature):
            k_sem, k_coarse, k_fine = jax.random.split(rng, 3)
            # stage 1: text -> semantic (text ids arrive pre-offset)
            sem = generate(
                semantic, params["semantic"], text_ids, n_sem, k_sem,
                temperature=temperature,
            )
            # stage 2: semantic -> coarse, codebooks interleaved with a
            # parity range constraint; coarse ids ride above semantic ids
            # in the coarse input vocab
            def parity_range(gen_idx):
                lo = (gen_idx % N_COARSE_BOOKS) * cb
                return lo, lo + cb

            coarse_tokens = generate(
                coarse, params["coarse"], sem, n_coarse_tokens, k_coarse,
                temperature=temperature, input_offset=sem_offset,
                range_fn=parity_range,
            )
            # de-interleave [B, 2*T] -> [B, 2, T]; strip the parity offset
            c = coarse_tokens.reshape(
                coarse_tokens.shape[0], n_frames, N_COARSE_BOOKS
            )
            c = jnp.moveaxis(c, 1, 2) - (jnp.arange(N_COARSE_BOOKS) * cb)[
                None, :, None
            ]
            c = jnp.clip(c, 0, cb - 1)
            # stage 3: fine refinement — codebooks 3..8 predicted from all
            # books so far (bidirectional, one pass per book)
            codes = jnp.concatenate(
                [c] + [jnp.zeros_like(c[:, :1])] * (N_FINE_BOOKS - N_COARSE_BOOKS),
                axis=1,
            )
            book_offsets = (jnp.arange(N_FINE_BOOKS) * cb)[None, :, None]
            for target in range(N_COARSE_BOOKS, N_FINE_BOOKS):
                logits = fine.apply(
                    {"params": params["fine"]}, codes + book_offsets
                )
                sampled = jax.random.categorical(
                    jax.random.fold_in(k_fine, target),
                    logits.astype(jnp.float32)
                    / jnp.maximum(jnp.asarray(temperature, jnp.float32), 1e-4),
                )
                codes = codes.at[:, target].set(jnp.clip(sampled, 0, cb - 1))
            # stage 4: codec decode to waveform
            return codec.apply({"params": params["codec"]}, codes)

        program = jax.jit(run)
        with self._lock:
            self._programs[key] = program
        return program

    def run(self, prompt="", **kwargs):
        params = self.params
        if params is None:
            raise Exception(
                f"pipeline {self.model_name} was evicted; resubmit the job"
            )
        timings: dict[str, float] = {}
        duration = float(kwargs.pop("duration", 2.0 if self.tiny else 5.0))
        duration = min(duration, 16.0)
        temperature = float(kwargs.pop("temperature", 0.7))
        rng = kwargs.pop("rng", None)
        if rng is None:
            rng = jax.random.key(0)
        kwargs.pop("chipset", None)
        kwargs.pop("negative_prompt", None)
        kwargs.pop("num_inference_steps", None)  # TTS has no denoise steps

        # static text budget: bucket to 32-token multiples
        ids = self.tokenizer.encode(prompt)[: self.sem_cfg.block_size // 4]
        t_text = max(32, (len(ids) + 31) // 32 * 32)
        sem_offset = SEMANTIC_VOCAB if not self.tiny else 1000
        text_arr = np.zeros((1, t_text), np.int32)
        text_arr[0, : len(ids)] = np.asarray(ids, np.int32) % self.text_vocab
        text_arr = text_arr + sem_offset  # text ids live above semantic ids

        n_sem = max(8, int(duration * self.sem_rate))
        n_frames = max(8, int(duration * self.codec_rate))
        # every stage's (prompt + generation) must fit its position table
        n_sem = min(n_sem, self.sem_cfg.block_size - t_text)
        n_frames = min(
            n_frames,
            (self.coarse_cfg.block_size - n_sem) // N_COARSE_BOOKS,
            self.fine_cfg.block_size,
        )
        # the renderable duration is set by n_frames: shrink the semantic
        # plan to match (no point AR-decoding semantic tokens the coarse
        # stage can never render) and surface the truncation to the caller
        renderable_s = n_frames / self.codec_rate
        truncated = renderable_s + 1e-6 < duration
        if truncated:
            logger.warning(
                "bark duration %.1fs truncated to %.1fs (position-table cap)",
                duration, renderable_s,
            )
            n_sem = min(n_sem, max(8, int(renderable_s * self.sem_rate)))
        program = self._program((t_text, n_sem, n_frames))
        t0 = time.perf_counter()
        wav = jax.block_until_ready(
            program(params, rng, jnp.asarray(text_arr),
                    jnp.float32(temperature))
        )
        timings["generate_s"] = round(time.perf_counter() - t0, 3)

        from .audio import normalize_wav

        wav = normalize_wav(np.asarray(wav[0], np.float32))
        rate = self.hop * self.codec_rate  # samples/sec this stack emits
        config = {
            "model": self.model_name,
            "pipeline": "BarkPipeline",
            "mode": "txt2audio",
            "duration_s": round(len(wav) / rate, 3),
            "requested_duration_s": duration,
            **({"duration_truncated": True} if truncated else {}),
            "sample_rate": rate,
            "semantic_tokens": n_sem,
            "codec_frames": n_frames,
            "timings": timings,
        }
        return wav, rate, config


@register_family("bark")
def _build_bark(model_name, chipset, **variant):
    return BarkPipeline(model_name, chipset, **variant)


def run_bark(device_identifier: str, model_name: str, **kwargs):
    """txt2audio (Bark) job -> audio/mpeg artifact (reference swarm/audio/bark.py).

    Bark jobs dispatch before parameter formatting (job_arguments.py:55-58
    mirrors reference :29-30), so the raw `parameters` may still ride in."""
    from ..post_processors.output_processor import make_result
    from ..registry import get_pipeline
    from .audio import audio_artifact

    parameters = kwargs.pop("parameters", {}) or {}
    # bark jobs skip parameter formatting, so job controls may ride in
    # either level — like test_tiny_model below
    content_type = kwargs.pop(
        "content_type", parameters.pop("content_type", "audio/mpeg")
    )
    kwargs.pop("outputs", None)
    if kwargs.pop("test_tiny_model", False) or parameters.pop(
        "test_tiny_model", False
    ):
        model_name = "test/tiny-bark"
    kwargs.update(parameters)
    pipeline = get_pipeline(
        model_name, pipeline_type="BarkPipeline",
        chipset=kwargs.pop("chipset", None),
    )
    wav, rate, config = pipeline.run(**kwargs)
    buf, produced_type, produced_rate = audio_artifact(wav, rate, content_type)
    config["sample_rate"] = produced_rate
    return {
        "primary": make_result(buf, None, produced_type)
    }, config
