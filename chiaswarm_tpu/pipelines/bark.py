"""Bark TTS pipeline: text -> semantic -> coarse -> fine -> waveform.

Reference behavior replaced: swarm/audio/bark.py:16-21 (suno-bark
`preload_models()` + `generate_audio()` per job, wav -> mp3). The TPU
rebuild keeps the four-stage suno/bark architecture (models/bark.py) as
ONE resident jitted program per (prompt-budget, duration) bucket: both AR
stages run as `lax.scan` KV-cache loops, the fine stage refines codebooks
3..8 with a bidirectional transformer, and the EnCodec decoder
(models/encodec.py) emits the waveform — text-in, audio-out in a single
XLA program, nothing returns to the host between stages.

Real suno/bark weights convert from the HF repo's single state dict
(conversion.split_bark_state / convert_bark_gpt /
convert_encodec_decoder), every GPT stage and the codec numerically
validated against transformers' Bark*Model / EncodecModel
(tests/test_bark_conversion.py). The token scheme (text offset 10_048,
pads, infer tokens, coarse codes at 10_000 + book*1024) follows
transformers' Bark generation configs. One deliberate divergence: the
coarse stage runs the full context in one scan instead of 60-token
sliding windows, so the renderable duration is capped by the coarse
position table (~5 s per job) rather than unbounded.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
import zlib
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np

from ..models.bark import (
    CODEBOOK_SIZE,
    CODEC_RATE,
    N_COARSE_BOOKS,
    N_FINE_BOOKS,
    SEMANTIC_RATE,
    BarkGPT,
    bark_small,
    bark_tiny,
    generate,
)
from ..models.bert_tokenizer import BertWordPieceTokenizer, HashBertTokenizer
from ..models.encodec import TINY_ENCODEC, EncodecConfig, EncodecDecoderModel
from ..parallel.mesh import make_mesh, replicated
from ..registry import register_family
from ..weights import is_test_model, require_weights_present

logger = logging.getLogger(__name__)

SAMPLE_RATE = 24_000  # EnCodec rate the bark codec targets


@dataclasses.dataclass(frozen=True)
class BarkTokenScheme:
    """The id bookkeeping between stages (transformers Bark generation
    configs; values are the real suno/bark constants by default)."""

    text_offset: int = 10_048
    text_pad: int = 129_595
    sem_pad: int = 10_000
    sem_infer: int = 129_599
    sem_vocab: int = 10_000
    max_text_len: int = 256
    sem_history_len: int = 256
    # coarse_pad pads the semantic history in transformers' sliding-window
    # coarse generation; the full-context scan here never pads, so the
    # field is carried only for scheme completeness
    coarse_pad: int = 12_048
    coarse_infer: int = 12_050
    coarse_code_offset: int = 10_000  # coarse codes live above semantic ids
    codebook_size: int = CODEBOOK_SIZE


TINY_SCHEME = BarkTokenScheme(
    text_offset=1048, text_pad=1195, sem_pad=1000, sem_infer=1199,
    sem_vocab=1000, max_text_len=32, sem_history_len=32,
    coarse_pad=1128, coarse_infer=1130, coarse_code_offset=1000,
    codebook_size=64,
)


_is_tiny = is_test_model


@dataclasses.dataclass(frozen=True)
class BarkCheckpoint:
    """A converted suno/bark repo: per-stage configs + token scheme +
    params. ONE loader serves both `initialize --check` and the pipeline
    so the two can never drift."""

    sem_cfg: object
    coarse_cfg: object
    fine_cfg: object
    codec_cfg: EncodecConfig
    scheme: BarkTokenScheme
    params: dict


def load_bark_checkpoint(model_dir, model_name: str = "") -> BarkCheckpoint:
    """HF suno/bark repo: one state dict with per-stage prefixes,
    config.json with nested stage configs, generation_config.json with the
    token-scheme constants."""
    import json

    from ..models.conversion import (
        convert_bark_gpt,
        convert_encodec_decoder,
        infer_bark_gpt_config,
        infer_encodec_config,
        load_torch_state_dict,
        split_bark_state,
    )

    cfg_path = model_dir / "config.json"
    repo_cfg = json.loads(cfg_path.read_text()) if cfg_path.is_file() else {}
    sem_cfg = infer_bark_gpt_config(
        repo_cfg.get("semantic_config", {}), "semantic"
    )
    coarse_cfg = infer_bark_gpt_config(
        repo_cfg.get("coarse_acoustics_config", {}), "coarse"
    )
    fine_cfg = infer_bark_gpt_config(
        repo_cfg.get("fine_acoustics_config", {}), "fine"
    )
    codec_cfg = infer_encodec_config(repo_cfg.get("codec_config", {}))
    gen_path = model_dir / "generation_config.json"
    gen = json.loads(gen_path.read_text()) if gen_path.is_file() else {}
    sem_g = gen.get("semantic_config", {})
    coarse_g = gen.get("coarse_acoustics_config", {})
    base = BarkTokenScheme()
    scheme = BarkTokenScheme(
        text_offset=int(sem_g.get("text_encoding_offset", base.text_offset)),
        text_pad=int(sem_g.get("text_pad_token", base.text_pad)),
        sem_pad=int(sem_g.get("semantic_pad_token", base.sem_pad)),
        sem_infer=int(sem_g.get("semantic_infer_token", base.sem_infer)),
        sem_vocab=int(sem_g.get("semantic_vocab_size", base.sem_vocab)),
        max_text_len=int(sem_g.get("max_input_semantic_length",
                                   base.max_text_len)),
        sem_history_len=int(sem_g.get("max_input_semantic_length",
                                      base.sem_history_len)),
        coarse_pad=int(coarse_g.get("coarse_semantic_pad_token",
                                    base.coarse_pad)),
        coarse_infer=int(coarse_g.get("coarse_infer_token",
                                      base.coarse_infer)),
        coarse_code_offset=int(sem_g.get("semantic_vocab_size",
                                         base.coarse_code_offset)),
        codebook_size=codec_cfg.codebook_size,
    )
    split = split_bark_state(load_torch_state_dict(model_dir))
    missing = {"semantic", "coarse", "fine", "codec"} - set(split)
    if missing:
        raise ValueError(
            f"{model_name or model_dir}: checkpoint lacks stages "
            f"{sorted(missing)}"
        )
    params = {
        "semantic": convert_bark_gpt(split["semantic"]),
        "coarse": convert_bark_gpt(split["coarse"]),
        "fine": convert_bark_gpt(split["fine"]),
        "codec": convert_encodec_decoder(split["codec"], N_FINE_BOOKS),
    }
    return BarkCheckpoint(
        sem_cfg, coarse_cfg, fine_cfg, codec_cfg, scheme, params
    )


def verify_bark_params(ckpt: BarkCheckpoint) -> dict:
    """Shape-check every converted stage against its architecture;
    -> per-stage param counts (the `--check` report)."""
    import functools

    from ..models.conversion import assert_tree_shapes_match

    expected = {
        "semantic": jax.eval_shape(
            BarkGPT(ckpt.sem_cfg).init, jax.random.key(0),
            jnp.zeros((1, 8), jnp.int32),
        )["params"],
        "coarse": jax.eval_shape(
            BarkGPT(ckpt.coarse_cfg).init, jax.random.key(0),
            jnp.zeros((1, 8), jnp.int32),
        )["params"],
        "fine": jax.eval_shape(
            functools.partial(
                BarkGPT(ckpt.fine_cfg).init, method=BarkGPT.init_all
            ),
            jax.random.key(0), jnp.zeros((1, N_FINE_BOOKS, 8), jnp.int32),
        )["params"],
        "codec": jax.eval_shape(
            EncodecDecoderModel(ckpt.codec_cfg).init, jax.random.key(0),
            jnp.zeros((1, N_FINE_BOOKS, 8), jnp.int32),
        )["params"],
    }
    report = {}
    for comp, tree in expected.items():
        assert_tree_shapes_match(ckpt.params[comp], tree, prefix=comp)
        report[comp] = sum(
            int(np.prod(x.shape))
            for x in jax.tree_util.tree_leaves(ckpt.params[comp])
        )
    return report


class BarkPipeline:
    """Resident 4-stage TTS stack serving `suno/bark*` model names."""

    def __init__(self, model_name: str, chipset=None,
                 allow_random_init: bool = False):
        self.model_name = model_name
        self.chipset = chipset
        self.tiny = _is_tiny(model_name)
        from ..weights import model_dir_for

        model_dir = None if self.tiny else model_dir_for(model_name)
        if not self.tiny and model_dir is None:
            require_weights_present(
                model_name, None, allow_random_init, component="Bark TTS",
            )

        converted = None
        if model_dir is not None:
            ckpt = load_bark_checkpoint(model_dir, model_name)
            verify_bark_params(ckpt)  # geometry mismatches surface here
            self.sem_cfg = ckpt.sem_cfg
            self.coarse_cfg = ckpt.coarse_cfg
            self.fine_cfg = ckpt.fine_cfg
            self.codec_cfg = ckpt.codec_cfg
            self.scheme = ckpt.scheme
            converted = ckpt.params
        else:
            mk = bark_tiny if self.tiny else bark_small
            self.sem_cfg = mk("semantic")
            self.coarse_cfg = mk("coarse")
            self.fine_cfg = mk("fine")
            self.codec_cfg = TINY_ENCODEC if self.tiny else EncodecConfig()
            self.scheme = TINY_SCHEME if self.tiny else BarkTokenScheme()

        self.cb = self.scheme.codebook_size
        # token rates scale down on the tiny stack so tests stay fast
        self.sem_rate = 8 if self.tiny else SEMANTIC_RATE
        self.codec_rate = 8 if self.tiny else CODEC_RATE

        on_tpu = jax.default_backend() == "tpu"
        self.dtype = jnp.bfloat16 if on_tpu else jnp.float32
        self.semantic = BarkGPT(self.sem_cfg, dtype=self.dtype)
        self.coarse = BarkGPT(self.coarse_cfg, dtype=self.dtype)
        self.fine = BarkGPT(self.fine_cfg, dtype=self.dtype)
        self.codec = EncodecDecoderModel(self.codec_cfg, dtype=self.dtype)
        self.hop = int(np.prod(self.codec_cfg.upsampling_ratios))
        self.tokenizer = self._tokenizer(model_dir)
        self.mesh = (
            chipset.mesh() if chipset is not None else make_mesh(jax.devices()[:1])
        )

        rng = jax.random.key(zlib.crc32(model_name.encode()))
        k1, k2, k3, k4 = jax.random.split(rng, 4)
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            if converted is not None:
                params = converted
            else:
                params = {
                    "semantic": self.semantic.init(
                        k1, jnp.zeros((1, 8), jnp.int32)
                    )["params"],
                    "coarse": self.coarse.init(
                        k2, jnp.zeros((1, 8), jnp.int32)
                    )["params"],
                    "fine": self.fine.init(
                        k3, jnp.zeros((1, N_FINE_BOOKS, 8), jnp.int32),
                        method=BarkGPT.init_all,
                    )["params"],
                    "codec": self.codec.init(
                        k4, jnp.zeros((1, N_FINE_BOOKS, 8), jnp.int32)
                    )["params"],
                }
        cast = lambda x: (
            jnp.asarray(x, self.dtype) if jnp.issubdtype(
                jnp.asarray(x).dtype, jnp.floating) else jnp.asarray(x)
        )
        self.params = jax.device_put(
            jax.tree_util.tree_map(cast, params), replicated(self.mesh)
        )
        # insertion-ordered so the program_cache_max bound below can evict
        # least-recently-used first (SW007; same knob as the SD family)
        self._programs: OrderedDict = OrderedDict()
        self._lock = threading.Lock()

    def _tokenizer(self, model_dir):
        if model_dir is not None:
            vocab = model_dir / "tokenizer" / "vocab.txt"
            if not vocab.is_file():
                vocab = model_dir / "vocab.txt"
            if vocab.is_file():
                return BertWordPieceTokenizer.from_file(vocab)
            raise ValueError(
                f"{self.model_name}: converted weights present but no "
                "tokenizer vocab.txt — hash-tokenized prompts would drive "
                "the real semantic stage with garbage ids"
            )
        text_vocab = 100 if self.tiny else 119_547  # bert-multilingual size
        return HashBertTokenizer(text_vocab)

    def release(self):
        self.params = None
        self._programs.clear()

    def _program(self, key: tuple):
        """One fused text->waveform program."""
        with self._lock:
            if key in self._programs:
                self._programs.move_to_end(key)
                return self._programs[key]
        t_text, n_sem, n_frames = key
        semantic, coarse, fine, codec = (
            self.semantic, self.coarse, self.fine, self.codec
        )
        cb = self.cb
        scheme = self.scheme
        n_coarse_tokens = n_frames * N_COARSE_BOOKS

        def run(params, rng, sem_prompt, temperature):
            k_sem, k_coarse, k_fine = jax.random.split(rng, 3)
            # stage 1: text -> semantic. Prompt arrives pre-built per the
            # transformers scheme ([text+offset | pad]*L + [sem history
            # pads] + [infer]); sampling stays inside the semantic vocab
            # (fixed-length generation; no eos early-stop — static shapes)
            sem = generate(
                semantic, params["semantic"], sem_prompt, n_sem, k_sem,
                temperature=temperature,
                range_fn=lambda _: (0, scheme.sem_vocab),
            )
            # stage 2: semantic -> coarse. Prompt = semantic ids ++
            # [coarse_infer]; the two codebooks interleave, each book's
            # codes living at coarse_code_offset + book*cb inside the
            # SHARED coarse vocab (output vocab == input vocab, so sampled
            # ids feed back with no extra offset)
            coarse_prompt = jnp.concatenate(
                [sem, jnp.full((sem.shape[0], 1), scheme.coarse_infer,
                               sem.dtype)], axis=1,
            )

            def parity_range(gen_idx):
                lo = scheme.coarse_code_offset + (
                    gen_idx % N_COARSE_BOOKS
                ) * cb
                return lo, lo + cb

            coarse_tokens = generate(
                coarse, params["coarse"], coarse_prompt, n_coarse_tokens,
                k_coarse, temperature=temperature, range_fn=parity_range,
            )
            # de-interleave [B, 2*T] -> [B, 2, T]; strip offsets to raw codes
            c = coarse_tokens.reshape(
                coarse_tokens.shape[0], n_frames, N_COARSE_BOOKS
            )
            c = jnp.moveaxis(c, 1, 2) - scheme.coarse_code_offset - (
                jnp.arange(N_COARSE_BOOKS) * cb
            )[None, :, None]
            c = jnp.clip(c, 0, cb - 1)
            # stage 3: fine refinement — books 3..8 predicted one pass per
            # book (bidirectional). Unpredicted books carry the pad id
            # (= codebook size, transformers BarkFineModel.generate), and
            # each book embeds through its own table — no id offsets.
            codes = jnp.concatenate(
                [c] + [jnp.full_like(c[:, :1], cb)]
                * (N_FINE_BOOKS - N_COARSE_BOOKS),
                axis=1,
            )
            for target in range(N_COARSE_BOOKS, N_FINE_BOOKS):
                logits = fine.apply(
                    {"params": params["fine"]}, codes, codebook_idx=target
                )
                # real fine heads are wider than the codebook (1056 vs
                # 1024: pad/unused columns); sample only the valid codes
                # like transformers BarkFineModel.generate, never clip
                # out-of-range draws onto code cb-1
                sampled = jax.random.categorical(
                    jax.random.fold_in(k_fine, target),
                    logits[..., :cb].astype(jnp.float32)
                    / jnp.maximum(jnp.asarray(temperature, jnp.float32), 1e-4),
                )
                codes = codes.at[:, target].set(sampled)
            # stage 4: codec decode to waveform
            return codec.apply({"params": params["codec"]}, codes)

        program = jax.jit(run)
        with self._lock:
            self._programs[key] = program
            from .common import PROGRAM_EVICTED, program_cache_cap

            cap = program_cache_cap()
            while cap and len(self._programs) > cap:
                self._programs.popitem(last=False)
                PROGRAM_EVICTED.inc(kind="program")
        return program

    def run(self, prompt="", **kwargs):
        params = self.params
        if params is None:
            raise Exception(
                f"pipeline {self.model_name} was evicted; resubmit the job"
            )
        timings: dict[str, float] = {}
        duration = float(kwargs.pop("duration", 2.0 if self.tiny else 5.0))
        duration = min(duration, 16.0)
        temperature = float(kwargs.pop("temperature", 0.7))
        rng = kwargs.pop("rng", None)
        if rng is None:
            rng = jax.random.key(0)
        kwargs.pop("chipset", None)
        kwargs.pop("negative_prompt", None)
        kwargs.pop("num_inference_steps", None)  # TTS has no denoise steps

        # transformers Bark prompt: [text ids + text_offset, padded with
        # text_pad] ++ [semantic-history pads] ++ [semantic infer token]
        scheme = self.scheme
        ids = self.tokenizer.encode(prompt)[: scheme.max_text_len]
        text_arr = np.full((1, scheme.max_text_len), scheme.text_pad, np.int32)
        if ids:
            text_arr[0, : len(ids)] = (
                np.asarray(ids, np.int32) + scheme.text_offset
            )
        sem_prompt = np.concatenate(
            [
                text_arr,
                np.full((1, scheme.sem_history_len), scheme.sem_pad, np.int32),
                np.full((1, 1), scheme.sem_infer, np.int32),
            ],
            axis=1,
        )
        t_text = sem_prompt.shape[1]

        n_sem = max(8, int(duration * self.sem_rate))
        n_frames = max(8, int(duration * self.codec_rate))
        # every stage's (prompt + generation) must fit its position table
        n_sem = min(n_sem, self.sem_cfg.block_size - t_text)
        n_frames = min(
            n_frames,
            # coarse prompt = n_sem semantic ids + infer token
            (self.coarse_cfg.block_size - n_sem - 1) // N_COARSE_BOOKS,
            self.fine_cfg.block_size,
        )
        # the renderable duration is set by n_frames: shrink the semantic
        # plan to match (no point AR-decoding semantic tokens the coarse
        # stage can never render) and surface the truncation to the caller
        renderable_s = n_frames / self.codec_rate
        truncated = renderable_s + 1e-6 < duration
        if truncated:
            logger.warning(
                "bark duration %.1fs truncated to %.1fs (position-table cap)",
                duration, renderable_s,
            )
            n_sem = min(n_sem, max(8, int(renderable_s * self.sem_rate)))
        program = self._program((t_text, n_sem, n_frames))
        t0 = time.perf_counter()
        wav = jax.block_until_ready(
            program(params, rng, jnp.asarray(sem_prompt),
                    jnp.float32(temperature))
        )
        timings["generate_s"] = round(time.perf_counter() - t0, 3)

        from .audio import normalize_wav

        wav = normalize_wav(np.asarray(wav[0], np.float32))
        rate = self.hop * self.codec_rate  # samples/sec this stack emits
        config = {
            "model": self.model_name,
            "pipeline": "BarkPipeline",
            "mode": "txt2audio",
            "duration_s": round(len(wav) / rate, 3),
            "requested_duration_s": duration,
            **({"duration_truncated": True} if truncated else {}),
            "sample_rate": rate,
            "semantic_tokens": n_sem,
            "codec_frames": n_frames,
            "timings": timings,
        }
        return wav, rate, config


@register_family("bark")
def _build_bark(model_name, chipset, **variant):
    return BarkPipeline(model_name, chipset, **variant)


def run_bark(device_identifier: str, model_name: str, **kwargs):
    """txt2audio (Bark) job -> audio/mpeg artifact (reference swarm/audio/bark.py).

    Bark jobs dispatch before parameter formatting (job_arguments.py:55-58
    mirrors reference :29-30), so the raw `parameters` may still ride in."""
    from ..post_processors.output_processor import make_result
    from ..registry import get_pipeline
    from .audio import audio_artifact

    parameters = kwargs.pop("parameters", {}) or {}
    # bark jobs skip parameter formatting, so job controls may ride in
    # either level — like test_tiny_model below
    content_type = kwargs.pop(
        "content_type", parameters.pop("content_type", "audio/mpeg")
    )
    kwargs.pop("outputs", None)
    if kwargs.pop("test_tiny_model", False) or parameters.pop(
        "test_tiny_model", False
    ):
        model_name = "test/tiny-bark"
    kwargs.update(parameters)
    pipeline = get_pipeline(
        model_name, pipeline_type="BarkPipeline",
        chipset=kwargs.pop("chipset", None),
    )
    wav, rate, config = pipeline.run(**kwargs)
    buf, produced_type, produced_rate = audio_artifact(wav, rate, content_type)
    config["sample_rate"] = produced_rate
    return {
        "primary": make_result(buf, None, produced_type)
    }, config
