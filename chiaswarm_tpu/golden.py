"""Golden-image protocol: mechanical real-checkpoint parity proof.

Every conversion in this repo is validated against torch mirrors and
synthetic checkpoints (no egress in the build environment), which leaves
one gap: a mirror could encode the same misreading of a diffusers graph as
the flax code (VERDICT r04 missing #3). This runner closes it the first
time a session has real weights: it executes one pinned job per family
(fixed model, prompt, seed, steps, size — see goldens/manifest.json) and
compares the artifact bytes against recorded SHA-256 hashes.

    chiaswarm-tpu-golden --record [--tiny]   # run + write hashes/env
    chiaswarm-tpu-golden --check  [--tiny]   # run + compare, rc = mismatches

Hashes are exact over artifact bytes, so they pin (jax, PIL, numpy,
platform) — all recorded in the manifest next to the hashes; a check on a
different stack reports the environment drift instead of pretending the
comparison is meaningful. `--tiny` is the hermetic rehearsal tier (tiny
random-weight models, CPU-runnable): it proves the record/check machinery
end-to-end and is executed in CI-sized time; the `real` tier awaits the
first session with converted real checkpoints (`initialize --download`).

The reference needs no analog: it serves real published weights by
construction (`from_pretrained`, swarm/diffusion/diffusion_func.py:103).
"""

from __future__ import annotations

import argparse
import asyncio
import base64
import hashlib
import json
import pathlib
import sys
import time

GOLDEN_SEED = 31337


def _manifest_path() -> pathlib.Path:
    """CHIASWARM_GOLDEN_MANIFEST env, else the source checkout's
    goldens/ next to the package, else ./goldens/manifest.json — the
    package-relative path is wrong under pip install (site-packages)."""
    import os

    override = os.environ.get("CHIASWARM_GOLDEN_MANIFEST")
    if override:
        return pathlib.Path(override)
    checkout = pathlib.Path(__file__).resolve().parent.parent / "goldens"
    if checkout.is_dir():
        return checkout / "manifest.json"
    return pathlib.Path("goldens/manifest.json")

# families excluded from the golden sweep: echo (no model), stitch (pure
# PIL compositing, already byte-tested hermetically), qr (optional qrcode
# dependency)
_SKIP = {"echo", "stitch", "qr"}


def _env_fingerprint() -> dict:
    import platform

    import jax
    import numpy as np
    import PIL

    return {
        "jax": jax.__version__,
        "numpy": np.__version__,
        "pillow": PIL.__version__,
        "backend": jax.default_backend(),
        "device_kind": getattr(jax.devices()[0], "device_kind", "?"),
        "python": platform.python_version(),
    }


def _hash_artifacts(artifacts: dict) -> dict[str, str]:
    out = {}
    for key, art in (artifacts or {}).items():
        blob = art.get("blob")
        if blob:
            out[key] = hashlib.sha256(base64.b64decode(blob)).hexdigest()
    return out


def golden_jobs(assets, tiny: bool) -> dict[str, dict]:
    """One deterministic canned job per family, seed pinned."""
    from .smoke import _apply_tiny, canned_jobs

    jobs = {}
    for name, job in canned_jobs(assets).items():
        if name in _SKIP:
            continue
        job = _apply_tiny(name, job) if tiny else dict(job)
        job["seed"] = GOLDEN_SEED
        jobs[name] = job
    return jobs


def _load_manifest() -> dict:
    try:
        return json.loads(_manifest_path().read_text())
    except FileNotFoundError:
        return {"seed": GOLDEN_SEED, "tiers": {}}


def _save_manifest(manifest: dict) -> None:
    path = _manifest_path()
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(manifest, indent=1, sort_keys=True) + "\n")


def _normalize_uris(obj, base: str):
    """Replace the ephemeral localhost asset base with 'asset:' in the
    job copy written to the manifest (the asset bytes are deterministic;
    only the port churns)."""
    if isinstance(obj, dict):
        return {k: _normalize_uris(v, base) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_normalize_uris(v, base) for v in obj]
    if isinstance(obj, str) and obj.startswith(base):
        return "asset:" + obj[len(base):]
    return obj


async def _run_job(name, job, chipset, settings):
    from .job_arguments import format_args

    job = dict(job, id=f"golden-{name}")
    func, kwargs = await format_args(job, settings, chipset.identifier())
    kwargs.pop("id", None)
    loop = asyncio.get_running_loop()
    return await loop.run_in_executor(None, lambda: chipset(func, **kwargs))


async def amain(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="chiaswarm-tpu-golden", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    mode = parser.add_mutually_exclusive_group(required=True)
    mode.add_argument("--record", action="store_true",
                      help="run and write hashes into goldens/manifest.json")
    mode.add_argument("--check", action="store_true",
                      help="run and compare against recorded hashes")
    parser.add_argument("--tiny", action="store_true",
                        help="hermetic rehearsal tier (tiny models)")
    parser.add_argument("families", nargs="*",
                        help="subset of families (default: all)")
    args = parser.parse_args(argv)

    from .chips.allocator import SliceAllocator
    from .settings import load_settings
    from .smoke import AssetServer

    tier = "tiny" if args.tiny else "real"
    manifest = _load_manifest()
    tier_entries = manifest.setdefault("tiers", {}).setdefault(tier, {})

    assets = await AssetServer().start()
    failures = 0
    try:
        jobs = golden_jobs(assets, tiny=args.tiny)
        selected = args.families or list(jobs)
        unknown = [f for f in selected if f not in jobs]
        if unknown:
            parser.error(f"unknown families: {unknown}")

        settings = load_settings()
        allocator = SliceAllocator(
            chips_per_job=settings.chips_per_job,
            tensor_parallelism=settings.tensor_parallelism,
            sequence_parallelism=settings.sequence_parallelism,
        )
        chipset = await allocator.acquire()
        env = _env_fingerprint()
        print(f"golden {('record' if args.record else 'check')} "
              f"[{tier}] on {chipset.descriptor()}: "
              f"{len(selected)} families, seed {GOLDEN_SEED}")
        try:
            for name in selected:
                t0 = time.perf_counter()
                try:
                    artifacts, config = await _run_job(
                        name, jobs[name], chipset, settings)
                    if "error" in config:
                        raise RuntimeError(config["error"])
                except Exception as e:
                    print(f"  {name}: RUN FAILED {type(e).__name__}: {e}")
                    failures += 1
                    continue
                hashes = _hash_artifacts(artifacts)
                elapsed = time.perf_counter() - t0
                if args.record:
                    # committed manifest shows the full pinned job (model,
                    # prompt, seed, steps) next to its expected hashes;
                    # ephemeral asset-server URLs normalize to their path
                    # so re-recording doesn't churn the committed file
                    job_public = _normalize_uris(jobs[name], assets.base)
                    tier_entries[name] = {
                        "job": job_public,
                        "expected_sha256": hashes,
                        "recorded_env": env,
                    }
                    print(f"  {name}: recorded {list(hashes)} "
                          f"({elapsed:.1f}s)")
                    continue
                entry = tier_entries.get(name)
                if entry is None or not entry.get("expected_sha256"):
                    print(f"  {name}: NO RECORDED GOLDEN ({elapsed:.1f}s)")
                    failures += 1
                    continue
                drift = {k: (env[k], entry["recorded_env"].get(k))
                         for k in env
                         if env[k] != entry["recorded_env"].get(k)}
                if entry["expected_sha256"] == hashes:
                    print(f"  {name}: ok ({elapsed:.1f}s)")
                elif drift:
                    # exact hashes pin the stack; a mismatch under a
                    # different stack is environment drift, not proof of a
                    # conversion bug — surfaced as its own category
                    print(f"  {name}: HASH MISMATCH under env drift "
                          f"{drift} — re-record on this stack "
                          f"({elapsed:.1f}s)")
                    failures += 1
                else:
                    print(f"  {name}: MISMATCH got {hashes} want "
                          f"{entry['expected_sha256']} ({elapsed:.1f}s)")
                    failures += 1
        finally:
            allocator.release(chipset)
        if args.record:
            _save_manifest(manifest)
            print(f"manifest written: {_manifest_path()}")
        print(f"golden: {len(selected) - failures}/{len(selected)} ok")
        return failures
    finally:
        await assets.stop()


def main() -> None:
    sys.exit(asyncio.run(amain()))


if __name__ == "__main__":
    main()
