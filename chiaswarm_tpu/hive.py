"""Hive wire protocol client.

Protocol parity with reference swarm/hive.py:9-88:

  GET  {hive}/work?worker_version&worker_name&memory&gpu  -> {"jobs": [...]}
  POST {hive}/results  <- result envelope                  -> ack JSON
  GET  {hive}api/models                                    -> {models, language_models}

Auth is a bearer token; 400 from /work carries a {"message": ...} explaining
why the hive is refusing this worker (e.g. too slow). We additionally
advertise TPU capability (`chips`, `hbm_gb`, `topology`) alongside the legacy
`memory`/`gpu` keys so a capability-aware hive can place by chip count while
legacy hives keep working.

Tracing (ISSUE 8): a tracing hive stamps each handed job with a `trace`
context — `{id, attempt, dispatched_wall, queue_wait_s}`, pinned by the
protocol-conformance suite — which the worker enriches (receipt instant,
linger split) and echoes back inside the result envelope's
`pipeline_config.trace`, so the hive can assemble one end-to-end timeline
per job (`GET /api/jobs/{id}/trace`). Legacy hives send no context and
nothing is added; legacy workers ignore the key harmlessly.

Unlike the reference (one aiohttp session per call), `HiveClient` holds a
single session for connection reuse; the module-level functions keep the
reference's call signatures for drop-in use (routed through a shared
process-wide client, so they too reuse connections and failover state).

Multi-hive failover (hive_server/replication.py is the serving half):
`HiveClient` accepts a LIST of endpoints (`Settings.sdaas_uris` /
`CHIASWARM_HIVE_URIS`, primary first) and PINS to one. It fails over —
advances the pin to the next endpoint — on `hive_failover_errors`
consecutive transport-level failures, or immediately on a not-primary
refusal (HTTP 409 from a standby or a deposed, stale-epoch primary).
Between attempts the existing retry layers supply the decorrelated
backoff (the poll loop's `_next_backoff`, the outbox's `backoff_delay`),
so a fleet failing over together does not stampede the survivor. The
client also tracks the highest fencing epoch any hive has advertised
(`X-Hive-Epoch`) and echoes it on every request — that echo is what lets
a deposed primary discover it was deposed and refuse, instead of
double-dispatching (split-brain fencing).
"""

from __future__ import annotations

import asyncio
import atexit
import contextlib
import json
import logging
import os
import time
from typing import Any

import aiohttp

from . import USER_AGENT, __version__, faults, telemetry

logger = logging.getLogger(__name__)

ASK_TIMEOUT_S = 10
SUBMIT_TIMEOUT_S = 90
# one retry with a short backoff for transient submit failures — losing a
# finished job's artifacts to a single 502 wastes a whole denoise pass
SUBMIT_RETRY_BACKOFF_S = 0.5

_REQUEST_SECONDS = telemetry.histogram(
    "swarm_hive_request_seconds",
    "Hive HTTP round-trip latency by endpoint (errors included)",
    ("endpoint",),
)
_ERRORS = telemetry.counter(
    "swarm_hive_errors_total",
    "Hive HTTP requests that raised, by endpoint",
    ("endpoint",),
)
_RETRIES = telemetry.counter(
    "swarm_hive_retries_total",
    "Hive requests retried after a transient failure, by endpoint",
    ("endpoint",),
)
_FAILOVERS = telemetry.counter(
    "swarm_hive_failover_total",
    "Worker-side hive failovers (the client pinned to the next "
    "configured endpoint after transport errors or a not-primary 409)",
)
_ENDPOINT_ERRORS = telemetry.counter(
    "swarm_hive_endpoint_errors_total",
    "Transport-level hive failures by configured endpoint URI",
    ("uri",),
)
_ACTIVE_ENDPOINT = telemetry.gauge(
    "swarm_hive_active_endpoint",
    "1 for the hive endpoint this worker is currently pinned to, "
    "0 for the others",
    ("uri",),
)


class HiveError(Exception):
    """Raised when the hive keeps refusing a request.

    `permanent` is True when the final failure was a non-transient client
    error (4xx) — the outbox parks such envelopes instead of retrying a
    refusal the hive will repeat forever.
    """

    def __init__(self, message: str, permanent: bool = False):
        super().__init__(message)
        self.permanent = permanent


class HiveNotPrimary(Exception):
    """The pinned endpoint answered 409: it is a standby still
    replicating, or a deposed primary fenced by a newer epoch. Always
    transient — the job belongs to whichever hive IS primary."""


# the worker host's highest-seen fencing epoch, persisted so outbox
# redelivery after a restart still carries it (see HiveClient.__init__)
EPOCH_FILENAME = "hive_epoch"


def _load_persisted_epoch() -> int:
    from .settings import resolve_path

    try:
        return int(resolve_path(EPOCH_FILENAME).read_text().strip())
    except (OSError, ValueError):
        return 0


def _persist_epoch(epoch: int) -> None:
    """Best-effort: a failed write degrades split-brain fencing back to
    in-memory (this process still fences), never the request path."""
    from .settings import resolve_path

    try:
        path = resolve_path(EPOCH_FILENAME)
        tmp = path.with_name(path.name + ".tmp")
        tmp.write_text(str(int(epoch)))
        os.replace(tmp, path)
    except OSError:
        logger.warning("could not persist hive epoch %d; fencing is "
                       "in-memory only for this process", epoch)


def hive_endpoints(settings) -> list[str]:
    """The worker-facing API endpoint list, multi-hive aware:
    `sdaas_uris` (CHIASWARM_HIVE_URIS) names site URIs in preference
    order — primary first, standbys after; empty falls back to the
    single `sdaas_uri`. Site URIs are normalized to their `/api` base."""
    raw = str(getattr(settings, "sdaas_uris", "") or "")
    uris = [u.strip().rstrip("/")
            for u in raw.replace(";", ",").split(",") if u.strip()]
    if not uris:
        uris = [str(settings.sdaas_uri).rstrip("/")]
    return [u if u.endswith("/api") else f"{u}/api" for u in uris]


class HiveClient:
    def __init__(self, settings, hive_uri: str | list[str]):
        self.settings = settings
        if isinstance(hive_uri, str):
            endpoints = [hive_uri]
        else:
            endpoints = list(hive_uri)
        self.endpoints = [u.rstrip("/") for u in endpoints if u]
        if not self.endpoints:
            raise ValueError("HiveClient needs at least one hive endpoint")
        self._pin = 0
        self.failovers = 0
        # highest fencing epoch any hive has advertised; echoed on every
        # request so a deposed primary can recognize itself and refuse.
        # PERSISTED under $SDAAS_ROOT: the outbox redelivers across
        # worker restarts, and a restarted worker that forgot the epoch
        # would hand its envelope to a revived deposed primary — the
        # exact double-settle the fence exists to stop
        self.epoch = _load_persisted_epoch()
        self._consecutive_errors = 0
        self._failover_errors = max(
            int(getattr(settings, "hive_failover_errors", 2) or 2), 1)
        # job ids the last successful /work reply asked this worker to
        # CANCEL (the hive's `cancels` piggyback, ISSUE 10); the worker
        # routes them through its BatchScheduler / cancel registry after
        # each poll. A legacy hive sends none and this stays empty.
        self.last_cancels: list[str] = []
        self._session: aiohttp.ClientSession | None = None
        self._session_loop: asyncio.AbstractEventLoop | None = None
        self._refresh_active_gauge()

    @property
    def hive_uri(self) -> str:
        """The endpoint this client is currently pinned to (the only one
        there is, in the classic single-hive configuration)."""
        return self.endpoints[self._pin]

    def _headers(self) -> dict[str, str]:
        headers = {
            "Content-type": "application/json",
            "Authorization": f"Bearer {self.settings.sdaas_token}",
            "user-agent": USER_AGENT,
        }
        if self.epoch > 0:
            headers["X-Hive-Epoch"] = str(self.epoch)
        return headers

    async def _get_session(self) -> aiohttp.ClientSession:
        loop = asyncio.get_running_loop()
        if self._session is not None and (
                self._session.closed or self._session_loop is not loop):
            if not self._session.closed and self._session_loop is not loop:
                # born on another (likely dead) event loop — the shared
                # module-level clients hit this across asyncio.run calls.
                # Release the old sockets synchronously; awaiting close()
                # on a foreign loop is not an option
                with contextlib.suppress(Exception):
                    self._session.connector.close()
            self._session = None
        if self._session is None:
            self._session = aiohttp.ClientSession()
            self._session_loop = loop
        return self._session

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()

    # --- failover bookkeeping ---

    def _refresh_active_gauge(self) -> None:
        for uri in self.endpoints:
            _ACTIVE_ENDPOINT.set(1 if uri == self.hive_uri else 0, uri=uri)

    def _failover(self, reason: str) -> None:
        """Pin to the next configured endpoint (no-op with one). The
        caller's retry loop supplies the decorrelated backoff before the
        next attempt lands on the new pin."""
        self._consecutive_errors = 0
        if len(self.endpoints) <= 1:
            return
        old = self.hive_uri
        self._pin = (self._pin + 1) % len(self.endpoints)
        self.failovers += 1
        _FAILOVERS.inc()
        self._refresh_active_gauge()
        logger.warning("hive failover: %s -> %s (%s)",
                       old, self.hive_uri, reason)

    def _note_transport_error(self, uri: str) -> None:
        _ENDPOINT_ERRORS.inc(uri=uri)
        self._consecutive_errors += 1
        if self._consecutive_errors >= self._failover_errors:
            self._failover(
                f"{self._consecutive_errors} consecutive transport errors")

    def _note_success(self) -> None:
        self._consecutive_errors = 0

    def _note_request_failure(self, endpoint: str, uri: str,
                              exc: Exception) -> None:
        """Failover accounting shared by the poll and delivery paths:
        transport-level failures and 5xx count toward the pin advancing;
        any other HTTP status proves the endpoint alive and authoritative
        (a drain refusal, bad params) — reachability-wise a success.
        HiveNotPrimary already moved the pin at the refusal site."""
        if isinstance(exc, HiveNotPrimary):
            return
        _ERRORS.inc(endpoint=endpoint)
        if isinstance(exc, aiohttp.ClientResponseError) and exc.status < 500:
            self._note_success()
        else:
            self._note_transport_error(uri)

    def _note_epoch(self, response) -> None:
        raw = response.headers.get("X-Hive-Epoch", "")
        try:
            seen = int(raw)
        except ValueError:
            return
        if seen > self.epoch:
            self.epoch = seen
            _persist_epoch(seen)  # rare: epochs bump only on promotions

    async def _raise_not_primary(self, response) -> None:
        """Map a 409 into HiveNotPrimary (pin already advanced)."""
        self._note_epoch(response)
        try:
            message = (await response.json()).get("message", "not primary")
        except Exception:
            message = "not primary"
        logger.warning("hive %s refused as not-primary: %s",
                       self.hive_uri, message)
        self._failover(message)
        raise HiveNotPrimary(message)

    async def ask_for_work(self, capabilities: dict[str, Any]) -> list[dict]:
        """Poll the hive for jobs, advertising this worker's capabilities.

        `capabilities` comes from the chip layer (chips/allocator.py) and
        includes legacy keys (`memory`, `gpu`) plus TPU keys — and, for
        a stats-reporting worker, the compact per-stage EWMA blob the
        hive's straggler detector reads (`stats`, a JSON string; the
        worker pre-serializes it because every value here is stringified
        onto the query). A not-primary 409 fails over and retries the
        next endpoint within this call (one full cycle at most);
        transport errors surface to the poll loop's backoff after noting
        the endpoint failure."""
        last: Exception | None = None
        for _ in range(len(self.endpoints)):
            try:
                return await self._ask_once(capabilities)
            except HiveNotPrimary as e:
                last = e  # pin already advanced; try the next hive now
        remedy = ""
        if self.epoch > 0 and "stale hive epoch" in str(last):
            # every hive is BEHIND our persisted epoch: either a failover
            # is mid-flight (transient) or the fleet was rebuilt from
            # scratch and this worker's fencing memory outlived it —
            # name the recovery, or the wedge looks like an outage
            remedy = (f"; if the hive fleet was rebuilt (fresh epoch 0), "
                      f"delete {EPOCH_FILENAME} under $SDAAS_ROOT on this "
                      f"worker to reset its fencing epoch ({self.epoch})")
        raise HiveError(
            f"every hive endpoint refused as not-primary: {last}{remedy}",
            permanent=False) from last

    async def _ask_once(self, capabilities: dict[str, Any]) -> list[dict]:
        uri = self.hive_uri
        logger.info("asking for work from %s", uri)
        params = {
            "worker_version": __version__,
            "worker_name": self.settings.worker_name,
            **{k: str(v) for k, v in capabilities.items()},
        }
        # placement signal for a residency-aware hive (hive_server/
        # dispatch.py): which models are warm HERE rides the poll itself,
        # so dispatch needs no second round trip. Filled from the
        # process-global registry unless the caller already provided it;
        # legacy hives ignore unknown query params.
        if "resident_models" not in params:
            try:
                from .registry import resident_models

                params["resident_models"] = ",".join(resident_models())
            except Exception:  # advertisement is advisory, never a gate
                pass
        # adapter-operand residency signal (ISSUE 16): which adapters'
        # stacked device operands are warm HERE, so an adapter-aware hive
        # can route a repeat gang back to the worker that pays zero
        # upload for it. Same contract as resident_models: advisory,
        # caller-overridable, ignored by legacy hives.
        if "resident_adapters" not in params:
            try:
                from .lora_operands import resident_adapter_refs

                params["resident_adapters"] = ",".join(
                    resident_adapter_refs())
            except Exception:
                pass
        session = await self._get_session()
        timeout = aiohttp.ClientTimeout(total=ASK_TIMEOUT_S)
        t0 = time.perf_counter()
        try:
            async with session.get(
                f"{uri}/work",
                params=params,
                headers=self._headers(),
                timeout=timeout,
            ) as response:
                self._note_epoch(response)
                if response.status == 200:
                    self._note_success()
                    try:
                        payload = await response.json()
                        # lease revocations ride the same reply; surface
                        # them per-poll (stale cancels must not linger
                        # into the next poll's view)
                        self.last_cancels = [
                            str(c) for c in (payload.get("cancels") or [])
                            if c]
                        return payload["jobs"]
                    except Exception:
                        logger.exception("malformed /work response")
                        self.last_cancels = []
                        return []

                if response.status == 400:
                    # hive refuses this worker (reference swarm/hive.py:39-44)
                    try:
                        message = (await response.json()).get(
                            "message", "bad worker")
                    except Exception:
                        # a proxy's HTML 400 must not read as a transport
                        # error below — the endpoint is alive
                        message = "bad worker (unparseable refusal body)"
                    logger.warning("hive refused worker: %s", message)
                if response.status == 409:
                    # standby, or a deposed stale-epoch primary
                    await self._raise_not_primary(response)

                response.raise_for_status()
                return []
        except Exception as e:
            self._note_request_failure("work", uri, e)
            raise
        finally:
            _REQUEST_SECONDS.observe(time.perf_counter() - t0, endpoint="work")

    async def _submit_once(self, result: dict) -> dict:
        uri = self.hive_uri
        session = await self._get_session()
        timeout = aiohttp.ClientTimeout(total=SUBMIT_TIMEOUT_S)
        t0 = time.perf_counter()
        try:
            # fault-injection point: the POST never leaves the worker
            # (faults.py "drop_submit"); raised as the connection error a
            # real network drop would produce so classification is shared
            faults.fire(
                "drop_submit",
                exc=aiohttp.ClientConnectionError("injected fault: drop_submit"),
            )
            async with session.post(
                f"{uri}/results",
                data=json.dumps(result),
                headers=self._headers(),
                timeout=timeout,
            ) as response:
                self._note_epoch(response)
                if response.status == 409:
                    # not primary: this envelope belongs on the promoted
                    # hive's idempotent-ACK path, not parked as a 4xx
                    await self._raise_not_primary(response)
                response.raise_for_status()
                self._note_success()
                ack = await response.json()
                logger.info("result ack: %s", ack)
                return ack
        except Exception as e:
            self._note_request_failure("results", uri, e)
            raise
        finally:
            _REQUEST_SECONDS.observe(
                time.perf_counter() - t0, endpoint="results")

    async def submit_result(self, result: dict) -> dict:
        """POST one result envelope; a TRANSIENT failure (connection-level
        aiohttp.ClientError or a 5xx status) gets exactly one retry after a
        short backoff before surfacing as HiveError — the artifacts in
        `result` cost a full denoise pass and a single hive hiccup must not
        discard them. A not-primary 409 retries the NEXT endpoint
        immediately (the pin already moved; one extra attempt per
        configured hive), so a failover lands the envelope on the new
        primary's idempotent-ACK path within this call when possible.
        Non-transient client errors (4xx) surface immediately; timeouts
        keep propagating as asyncio.TimeoutError (the worker's result
        loop already has a policy for those)."""
        last_exc: Exception | None = None
        transient = True
        attempts = len(self.endpoints) + 1
        for attempt in range(attempts):
            try:
                return await self._submit_once(result)
            except HiveNotPrimary as e:
                last_exc = e
                transient = True
                continue  # the pin advanced; the next try is a new hive
            except aiohttp.ClientResponseError as e:
                transient = e.status >= 500
                last_exc = e
            except aiohttp.ClientError as e:
                transient = True
                last_exc = e
            if not transient or attempt == attempts - 1:
                break
            _RETRIES.inc(endpoint="results")
            logger.warning(
                "transient submit failure for %s (%s); retrying once",
                result.get("id"), last_exc,
            )
            await asyncio.sleep(SUBMIT_RETRY_BACKOFF_S)
        raise HiveError(
            f"submit_result failed for job {result.get('id')}: {last_exc}",
            permanent=not transient,
        ) from last_exc

    async def fetch_artifact(self, href: str) -> bytes | None:
        """GET one spooled blob by its hive href (``/api/artifacts/<digest>``,
        the shape a /work reply's `resume` offer carries). Best-effort by
        contract: every failure returns None — a resume offer degrades to
        a full pass, never to an error."""
        uri = self.hive_uri
        # hrefs are site-absolute; the pinned endpoint is the API base
        base = uri[:-4] if uri.endswith("/api") else uri
        session = await self._get_session()
        timeout = aiohttp.ClientTimeout(total=SUBMIT_TIMEOUT_S)
        t0 = time.perf_counter()
        try:
            async with session.get(
                f"{base}{href}",
                headers=self._headers(),
                timeout=timeout,
            ) as response:
                self._note_epoch(response)
                if response.status != 200:
                    logger.warning("artifact fetch %s answered %d",
                                   href, response.status)
                    return None
                self._note_success()
                return await response.read()
        except Exception as e:
            self._note_request_failure("artifact", uri, e)
            logger.warning("artifact fetch %s failed: %s", href, e)
            return None
        finally:
            _REQUEST_SECONDS.observe(
                time.perf_counter() - t0, endpoint="artifact")

    async def submit_workflow(self, payload: dict) -> dict:
        """POST one multi-stage workflow to ``/api/workflows`` (ISSUE 20).
        Single attempt against the pinned hive — the submit ACK is cheap
        to retry at the caller's policy, unlike a result envelope.
        Raises on any non-2xx."""
        uri = self.hive_uri
        session = await self._get_session()
        timeout = aiohttp.ClientTimeout(total=SUBMIT_TIMEOUT_S)
        t0 = time.perf_counter()
        try:
            async with session.post(
                f"{uri}/workflows",
                data=json.dumps(payload),
                headers=self._headers(),
                timeout=timeout,
            ) as response:
                self._note_epoch(response)
                response.raise_for_status()
                self._note_success()
                return await response.json()
        except Exception as e:
            self._note_request_failure("workflows", uri, e)
            raise
        finally:
            _REQUEST_SECONDS.observe(
                time.perf_counter() - t0, endpoint="workflows")

    async def post_partial(self, kind: str, job_id: str,
                           payload: dict) -> dict | None:
        """POST one mid-pass partial (`kind` is ``checkpoint`` or
        ``preview``) to the hive's durability endpoints (ISSUE 18).
        Best-effort: the denoise pass never waits on this and never
        fails because of it — any refusal (a 409 means the lease moved
        or the job went terminal, so further partials are pointless) or
        transport error returns None."""
        uri = self.hive_uri
        session = await self._get_session()
        timeout = aiohttp.ClientTimeout(total=SUBMIT_TIMEOUT_S)
        t0 = time.perf_counter()
        try:
            async with session.post(
                f"{uri}/jobs/{job_id}/{kind}",
                data=json.dumps(payload),
                headers=self._headers(),
                timeout=timeout,
            ) as response:
                self._note_epoch(response)
                if response.status != 200:
                    logger.info("%s upload for %s refused with %d",
                                kind, job_id, response.status)
                    return None
                self._note_success()
                return await response.json()
        except Exception as e:
            self._note_request_failure(kind, uri, e)
            logger.warning("%s upload for %s failed: %s", kind, job_id, e)
            return None
        finally:
            _REQUEST_SECONDS.observe(time.perf_counter() - t0, endpoint=kind)

    async def get_models(self) -> list[dict]:
        """Fetch the hive's model catalog; cached to models.json on success.

        Tries each configured endpoint once, starting from the pin (the
        catalog is replicated trivially — every hive serves it, standby
        included, so no 409 handling applies). Raises the last failure —
        the caller decides what a missing catalog means
        (`initialize --download` treats it as fatal rather than silently
        proceeding with zero models)."""
        last: Exception | None = None
        for offset in range(len(self.endpoints)):
            uri = self.endpoints[(self._pin + offset) % len(self.endpoints)]
            try:
                return await self._get_models_once(uri)
            except Exception as e:
                last = e
                if offset + 1 < len(self.endpoints):
                    logger.warning(
                        "model catalog fetch from %s failed (%s); trying "
                        "the next hive", uri, e)
        raise last

    async def _get_models_once(self, base: str) -> list[dict]:
        from .settings import save_file

        # normalize whether we were handed the API base ({uri}/api, as Worker
        # does) or the bare site URI (as the reference's initialize CLI does)
        models_url = (
            f"{base}/models" if base.endswith("/api") else f"{base}/api/models"
        )
        session = await self._get_session()
        timeout = aiohttp.ClientTimeout(total=ASK_TIMEOUT_S)
        t0 = time.perf_counter()
        try:
            async with session.get(
                models_url,
                headers={"user-agent": USER_AGENT},
                timeout=timeout,
            ) as response:
                response.raise_for_status()
                data = await response.json()
                save_file(data, "models.json")
                return data["language_models"] + data["models"]
        except Exception:
            _ERRORS.inc(endpoint="models")
            raise
        finally:
            _REQUEST_SECONDS.observe(
                time.perf_counter() - t0, endpoint="models")


# --- reference-signature wrappers (swarm/hive.py:9,50,69) ---
#
# These used to build a fresh HiveClient (and a fresh HTTP session) per
# call — connection reuse and failover pinning evaporated for every
# caller outside Worker (initialize.py's catalog fetch included). They
# now route through a process-wide client cache: same signatures, shared
# sessions, shared pin/epoch state.

_SHARED_CLIENTS: dict[tuple, HiveClient] = {}


def shared_client(settings, hive_uri: str | list[str]) -> HiveClient:
    """The process-wide HiveClient for (endpoints, token). Callers must
    NOT close it — it outlives any single call so failover pinning and
    connection reuse apply everywhere; sessions re-open per event loop
    (see _get_session), so it survives sequential asyncio.run calls."""
    uris = ((hive_uri,) if isinstance(hive_uri, str) else tuple(hive_uri))
    key = (uris, str(getattr(settings, "sdaas_token", "")))
    client = _SHARED_CLIENTS.get(key)
    if client is None:
        client = HiveClient(settings, list(uris))
        _SHARED_CLIENTS[key] = client
    else:
        # latest caller's settings win (worker_name etc.); token is part
        # of the key, so auth can never silently change underneath
        client.settings = settings
    return client


async def close_shared_clients() -> None:
    """Close every cached client's session (test teardown hygiene)."""
    for client in _SHARED_CLIENTS.values():
        await client.close()
    _SHARED_CLIENTS.clear()


def _close_shared_clients_at_exit() -> None:
    """Short-lived CLI callers (initialize --download) exit without a
    running loop to await close() on; closing the connector releases the
    sockets synchronously and marks the session closed, so aiohttp's
    'Unclosed client session' GC warning never fires."""
    for client in _SHARED_CLIENTS.values():
        session = client._session
        if session is not None and not session.closed:
            with contextlib.suppress(Exception):
                session.connector.close()
    _SHARED_CLIENTS.clear()


atexit.register(_close_shared_clients_at_exit)


async def ask_for_work(settings, hive_uri: str, capabilities: dict) -> list[dict]:
    return await shared_client(settings, hive_uri).ask_for_work(capabilities)


async def submit_result(settings, hive_uri: str, result: dict) -> dict:
    return await shared_client(settings, hive_uri).submit_result(result)


class _AnonymousSettings:
    """Settings stand-in for the unauthenticated model-catalog endpoint.

    The reference's get_models (swarm/hive.py:69-88) sends no auth; the
    catalog is public. A real class (not a type() one-liner) so the seam is
    visible and testable.
    """

    sdaas_token = ""


async def get_models(hive_uri: str) -> list[dict]:
    return await shared_client(_AnonymousSettings(), hive_uri).get_models()
