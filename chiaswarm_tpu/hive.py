"""Hive wire protocol client.

Protocol parity with reference swarm/hive.py:9-88:

  GET  {hive}/work?worker_version&worker_name&memory&gpu  -> {"jobs": [...]}
  POST {hive}/results  <- result envelope                  -> ack JSON
  GET  {hive}api/models                                    -> {models, language_models}

Auth is a bearer token; 400 from /work carries a {"message": ...} explaining
why the hive is refusing this worker (e.g. too slow). We additionally
advertise TPU capability (`chips`, `hbm_gb`, `topology`) alongside the legacy
`memory`/`gpu` keys so a capability-aware hive can place by chip count while
legacy hives keep working.

Unlike the reference (one aiohttp session per call), `HiveClient` holds a
single session for connection reuse; the module-level functions keep the
reference's call signatures for drop-in use.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from typing import Any

import aiohttp

from . import USER_AGENT, __version__, faults, telemetry

logger = logging.getLogger(__name__)

ASK_TIMEOUT_S = 10
SUBMIT_TIMEOUT_S = 90
# one retry with a short backoff for transient submit failures — losing a
# finished job's artifacts to a single 502 wastes a whole denoise pass
SUBMIT_RETRY_BACKOFF_S = 0.5

_REQUEST_SECONDS = telemetry.histogram(
    "swarm_hive_request_seconds",
    "Hive HTTP round-trip latency by endpoint (errors included)",
    ("endpoint",),
)
_ERRORS = telemetry.counter(
    "swarm_hive_errors_total",
    "Hive HTTP requests that raised, by endpoint",
    ("endpoint",),
)
_RETRIES = telemetry.counter(
    "swarm_hive_retries_total",
    "Hive requests retried after a transient failure, by endpoint",
    ("endpoint",),
)


class HiveError(Exception):
    """Raised when the hive keeps refusing a request.

    `permanent` is True when the final failure was a non-transient client
    error (4xx) — the outbox parks such envelopes instead of retrying a
    refusal the hive will repeat forever.
    """

    def __init__(self, message: str, permanent: bool = False):
        super().__init__(message)
        self.permanent = permanent


class HiveClient:
    def __init__(self, settings, hive_uri: str):
        self.settings = settings
        self.hive_uri = hive_uri.rstrip("/")
        self._session: aiohttp.ClientSession | None = None

    def _headers(self) -> dict[str, str]:
        return {
            "Content-type": "application/json",
            "Authorization": f"Bearer {self.settings.sdaas_token}",
            "user-agent": USER_AGENT,
        }

    async def _get_session(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession()
        return self._session

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()

    async def ask_for_work(self, capabilities: dict[str, Any]) -> list[dict]:
        """Poll the hive for jobs, advertising this worker's capabilities.

        `capabilities` comes from the chip layer (chips/allocator.py) and
        includes legacy keys (`memory`, `gpu`) plus TPU keys.
        """
        logger.info("asking for work from %s", self.hive_uri)
        params = {
            "worker_version": __version__,
            "worker_name": self.settings.worker_name,
            **{k: str(v) for k, v in capabilities.items()},
        }
        # placement signal for a residency-aware hive (hive_server/
        # dispatch.py): which models are warm HERE rides the poll itself,
        # so dispatch needs no second round trip. Filled from the
        # process-global registry unless the caller already provided it;
        # legacy hives ignore unknown query params.
        if "resident_models" not in params:
            try:
                from .registry import resident_models

                params["resident_models"] = ",".join(resident_models())
            except Exception:  # advertisement is advisory, never a gate
                pass
        session = await self._get_session()
        timeout = aiohttp.ClientTimeout(total=ASK_TIMEOUT_S)
        t0 = time.perf_counter()
        try:
            async with session.get(
                f"{self.hive_uri}/work",
                params=params,
                headers=self._headers(),
                timeout=timeout,
            ) as response:
                if response.status == 200:
                    try:
                        payload = await response.json()
                        return payload["jobs"]
                    except Exception:
                        logger.exception("malformed /work response")
                        return []

                if response.status == 400:
                    # hive refuses this worker (reference swarm/hive.py:39-44)
                    payload = await response.json()
                    message = payload.get("message", "bad worker")
                    logger.warning("hive refused worker: %s", message)

                response.raise_for_status()
                return []
        except Exception:
            _ERRORS.inc(endpoint="work")
            raise
        finally:
            _REQUEST_SECONDS.observe(time.perf_counter() - t0, endpoint="work")

    async def _submit_once(self, result: dict) -> dict:
        session = await self._get_session()
        timeout = aiohttp.ClientTimeout(total=SUBMIT_TIMEOUT_S)
        t0 = time.perf_counter()
        try:
            # fault-injection point: the POST never leaves the worker
            # (faults.py "drop_submit"); raised as the connection error a
            # real network drop would produce so classification is shared
            faults.fire(
                "drop_submit",
                exc=aiohttp.ClientConnectionError("injected fault: drop_submit"),
            )
            async with session.post(
                f"{self.hive_uri}/results",
                data=json.dumps(result),
                headers=self._headers(),
                timeout=timeout,
            ) as response:
                response.raise_for_status()
                ack = await response.json()
                logger.info("result ack: %s", ack)
                return ack
        except Exception:
            _ERRORS.inc(endpoint="results")
            raise
        finally:
            _REQUEST_SECONDS.observe(
                time.perf_counter() - t0, endpoint="results")

    async def submit_result(self, result: dict) -> dict:
        """POST one result envelope; a TRANSIENT failure (connection-level
        aiohttp.ClientError or a 5xx status) gets exactly one retry after a
        short backoff before surfacing as HiveError — the artifacts in
        `result` cost a full denoise pass and a single hive hiccup must not
        discard them. Non-transient client errors (4xx) surface
        immediately; timeouts keep propagating as asyncio.TimeoutError (the
        worker's result loop already has a policy for those)."""
        last_exc: Exception | None = None
        for attempt in (0, 1):
            try:
                return await self._submit_once(result)
            except aiohttp.ClientResponseError as e:
                transient = e.status >= 500
                last_exc = e
            except aiohttp.ClientError as e:
                transient = True
                last_exc = e
            if not transient or attempt == 1:
                break
            _RETRIES.inc(endpoint="results")
            logger.warning(
                "transient submit failure for %s (%s); retrying once",
                result.get("id"), last_exc,
            )
            await asyncio.sleep(SUBMIT_RETRY_BACKOFF_S)
        raise HiveError(
            f"submit_result failed for job {result.get('id')}: {last_exc}",
            permanent=not transient,
        ) from last_exc

    async def get_models(self) -> list[dict]:
        """Fetch the hive's model catalog; cached to models.json on success.

        Raises on network/auth/shape failure — the caller decides what a
        missing catalog means (`initialize --download`, the sole caller
        today, treats it as fatal rather than silently proceeding with
        zero models).
        """
        from .settings import save_file

        # normalize whether we were handed the API base ({uri}/api, as Worker
        # does) or the bare site URI (as the reference's initialize CLI does)
        base = self.hive_uri
        models_url = (
            f"{base}/models" if base.endswith("/api") else f"{base}/api/models"
        )
        session = await self._get_session()
        timeout = aiohttp.ClientTimeout(total=ASK_TIMEOUT_S)
        t0 = time.perf_counter()
        try:
            async with session.get(
                models_url,
                headers={"user-agent": USER_AGENT},
                timeout=timeout,
            ) as response:
                response.raise_for_status()
                data = await response.json()
                save_file(data, "models.json")
                return data["language_models"] + data["models"]
        except Exception:
            _ERRORS.inc(endpoint="models")
            raise
        finally:
            _REQUEST_SECONDS.observe(
                time.perf_counter() - t0, endpoint="models")


# --- reference-signature wrappers (swarm/hive.py:9,50,69) ---


async def ask_for_work(settings, hive_uri: str, capabilities: dict) -> list[dict]:
    client = HiveClient(settings, hive_uri)
    try:
        return await client.ask_for_work(capabilities)
    finally:
        await client.close()


async def submit_result(settings, hive_uri: str, result: dict) -> dict:
    client = HiveClient(settings, hive_uri)
    try:
        return await client.submit_result(result)
    finally:
        await client.close()


class _AnonymousSettings:
    """Settings stand-in for the unauthenticated model-catalog endpoint.

    The reference's get_models (swarm/hive.py:69-88) sends no auth; the
    catalog is public. A real class (not a type() one-liner) so the seam is
    visible and testable.
    """

    sdaas_token = ""


async def get_models(hive_uri: str) -> list[dict]:
    client = HiveClient(_AnonymousSettings(), hive_uri)
    try:
        return await client.get_models()
    finally:
        await client.close()
