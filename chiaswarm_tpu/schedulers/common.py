"""Shared scheduler machinery: beta schedules, sigma tables, Karras spacing.

All functions are host-side (numpy) — schedules are computed once per
(scheduler, step-count) at trace time and baked into the jitted program as
constants; only `step()` runs on device.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class SchedulerConfig:
    num_train_timesteps: int = 1000
    beta_start: float = 0.00085
    beta_end: float = 0.012
    beta_schedule: str = "scaled_linear"  # linear | scaled_linear | squaredcos_cap_v2
    prediction_type: str = "epsilon"  # epsilon | v_prediction | sample | flow
    use_karras_sigmas: bool = False
    timestep_spacing: str = "leading"  # leading | trailing | linspace
    steps_offset: int = 1
    # LCM distillation params
    original_inference_steps: int = 50
    # flow-matching (Flux) params
    shift: float = 3.0

    def replace(self, **kw) -> "SchedulerConfig":
        return dataclasses.replace(self, **kw)


@dataclasses.dataclass(frozen=True)
class Schedule:
    """Precomputed per-step constants for one (scheduler, num_steps) pair.

    Arrays are length num_steps (+1 where a terminal boundary is needed);
    `step()` indexes them with the scan counter.
    """

    timesteps: np.ndarray  # model-conditioning timesteps, float32 [n]
    sigmas: np.ndarray  # noise levels incl. terminal 0, float32 [n+1]
    init_noise_sigma: float  # latent init scale
    num_steps: int


def make_betas(config: SchedulerConfig) -> np.ndarray:
    n = config.num_train_timesteps
    if config.beta_schedule == "linear":
        return np.linspace(config.beta_start, config.beta_end, n, dtype=np.float64)
    if config.beta_schedule == "scaled_linear":
        return (
            np.linspace(config.beta_start**0.5, config.beta_end**0.5, n, dtype=np.float64)
            ** 2
        )
    if config.beta_schedule == "squaredcos_cap_v2":
        t = np.arange(n, dtype=np.float64)
        f = lambda x: np.cos((x / n + 0.008) / 1.008 * np.pi / 2) ** 2
        return np.clip(1.0 - f(t + 1) / f(t), 0.0, 0.999)
    raise ValueError(f"Unknown beta schedule: {config.beta_schedule}")


def make_alphas_cumprod(config: SchedulerConfig) -> np.ndarray:
    return np.cumprod(1.0 - make_betas(config))


def train_sigmas(config: SchedulerConfig) -> np.ndarray:
    """sigma(t) table over all train timesteps: sqrt((1-a)/a)."""
    ac = make_alphas_cumprod(config)
    return np.sqrt((1.0 - ac) / ac)


def spaced_timesteps(config: SchedulerConfig, num_steps: int) -> np.ndarray:
    """Inference timestep selection (descending), diffusers-compatible."""
    n = config.num_train_timesteps
    if config.timestep_spacing == "linspace":
        ts = np.linspace(0, n - 1, num_steps)[::-1]
    elif config.timestep_spacing == "leading":
        step = n // num_steps
        ts = (np.arange(num_steps) * step)[::-1].astype(np.float64)
        ts = ts + config.steps_offset
    elif config.timestep_spacing == "trailing":
        ts = np.arange(n, 0, -n / num_steps).round().astype(np.float64) - 1
    else:
        raise ValueError(f"Unknown timestep spacing: {config.timestep_spacing}")
    return np.clip(ts, 0, n - 1).astype(np.float64)


def karras_sigmas(sigma_min: float, sigma_max: float, num_steps: int, rho: float = 7.0) -> np.ndarray:
    """Karras et al. (2022) sigma spacing, descending."""
    ramp = np.linspace(0, 1, num_steps)
    min_inv, max_inv = sigma_min ** (1 / rho), sigma_max ** (1 / rho)
    return (max_inv + ramp * (min_inv - max_inv)) ** rho


def sigma_to_timestep(sigmas: np.ndarray, table: np.ndarray) -> np.ndarray:
    """Map sigmas back to (fractional) train timesteps by log-interpolation."""
    log_table = np.log(np.maximum(table, 1e-10))
    log_s = np.log(np.maximum(sigmas, 1e-10))
    # table is increasing in t
    return np.interp(log_s, log_table, np.arange(len(table), dtype=np.float64))


def discrete_schedule(config: SchedulerConfig, num_steps: int) -> Schedule:
    """Sigma schedule for k-diffusion style solvers (Euler/DPM++), with the
    Karras option the reference toggles per-job."""
    table = train_sigmas(config)
    ts = spaced_timesteps(config, num_steps)
    sigmas = np.interp(ts, np.arange(len(table)), table)
    if config.use_karras_sigmas:
        sigmas = karras_sigmas(float(sigmas[-1]), float(sigmas[0]), num_steps)
        ts = sigma_to_timestep(sigmas, table)
    sigmas = np.concatenate([sigmas, [0.0]]).astype(np.float32)
    return Schedule(
        timesteps=ts.astype(np.float32),
        sigmas=sigmas,
        init_noise_sigma=float(np.sqrt(sigmas[0] ** 2 + 1.0)),
        num_steps=num_steps,
    )


def ddpm_schedule(config: SchedulerConfig, num_steps: int) -> Schedule:
    """Alpha-bar schedule for variance-preserving solvers (DDIM/DDPM/LCM).

    `sigmas` here stores sqrt(1-abar)/sqrt(abar) for interface uniformity;
    solvers that need abar recover it as 1/(1+sigma^2).
    """
    table = train_sigmas(config)
    ts = spaced_timesteps(config, num_steps)
    sigmas = np.interp(ts, np.arange(len(table)), table)
    sigmas = np.concatenate([sigmas, [0.0]]).astype(np.float32)
    return Schedule(
        timesteps=ts.astype(np.float32),
        sigmas=sigmas,
        init_noise_sigma=1.0,
        num_steps=num_steps,
    )
