"""Diffusion noise schedulers, designed for `lax.scan` denoising loops.

The reference swaps diffusers scheduler classes by wire name with optional
Karras sigmas (swarm/diffusion/diffusion_func.py:129-132). Here schedulers
are *functional*: `make_schedule()` precomputes all per-step constants as
arrays at trace time (static shapes, no data-dependent control flow), and
`step()` is a pure function `(state, i, sample, model_output, noise) ->
(state, sample)` suitable for the body of a jitted scan. Multistep history
(DPM-Solver++) lives in the state pytree.

Wire names accepted (reference hive schema, SURVEY §2.7) map via
`get_scheduler`.
"""

from .common import Schedule, SchedulerConfig
from .solvers import (
    DDPMWuerstchenScheduler,
    HeunDiscreteScheduler,
    UniPCMultistepScheduler,
    DDIMScheduler,
    DDPMScheduler,
    DPMSolverMultistepScheduler,
    EulerAncestralDiscreteScheduler,
    EulerDiscreteScheduler,
    FlowMatchEulerScheduler,
    LCMScheduler,
)

# wire name -> implementation; aliases cover every scheduler_type string the
# reference test matrix sends (swarm/test.py)
SCHEDULERS = {
    "DPMSolverMultistepScheduler": DPMSolverMultistepScheduler,
    # singlestep still aliases to 2M (logged divergence); UniPC/Heun are real
    "DPMSolverSinglestepScheduler": DPMSolverMultistepScheduler,
    "UniPCMultistepScheduler": UniPCMultistepScheduler,
    "EulerDiscreteScheduler": EulerDiscreteScheduler,
    "EulerAncestralDiscreteScheduler": EulerAncestralDiscreteScheduler,
    "DDIMScheduler": DDIMScheduler,
    "DDPMScheduler": DDPMScheduler,
    "DDPMWuerstchenScheduler": DDPMWuerstchenScheduler,
    "PNDMScheduler": DDIMScheduler,
    "LMSDiscreteScheduler": EulerDiscreteScheduler,
    "HeunDiscreteScheduler": HeunDiscreteScheduler,
    "LCMScheduler": LCMScheduler,
    "FlowMatchEulerDiscreteScheduler": FlowMatchEulerScheduler,
    "FlowMatchEulerScheduler": FlowMatchEulerScheduler,
}


def get_scheduler(name: str, **config):
    try:
        cls = SCHEDULERS[name]
    except KeyError:
        raise ValueError(f"Unknown scheduler type: {name}") from None
    return cls(SchedulerConfig(**config))


__all__ = [
    "Schedule",
    "SchedulerConfig",
    "get_scheduler",
    "SCHEDULERS",
    "DDIMScheduler",
    "DDPMScheduler",
    "DDPMWuerstchenScheduler",
    "DPMSolverMultistepScheduler",
    "EulerAncestralDiscreteScheduler",
    "EulerDiscreteScheduler",
    "FlowMatchEulerScheduler",
    "LCMScheduler",
]
